"""Causal-LM pretraining over file-backed token shards.

The NLP-training face of the flagship trainer (no reference counterpart
— its models are CNNs + served ERNIE; this is the net-new transformer
path that pairs with ring attention and the Pallas flash kernel):
dp/fsdp-sharded transformer LM over a device mesh, token shards streamed
through the deterministic file pipeline, cosine LR with warmup, optional
sharded checkpoints (per-process chunks + resharding restore), tokens/s
+ eval-loss benchmark log.

  python -m edl_tpu.examples.lm_train --make-synthetic 4 \\
      --data-dir /tmp/lm --d-model 128 --n-layers 2 --seq-len 128 \\
      --epochs 2 --batch-size 16
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.data.pipeline import DataLoader, FileSource
from edl_tpu.models.transformer import (Transformer, TransformerConfig,
                                        lm_loss_fn, lm_loss_fused,
                                        lm_loss_moe)
from edl_tpu.parallel import distributed, mesh as mesh_lib, sharding as shd
from edl_tpu.train import lr as lr_lib
from edl_tpu.train.benchlog import BenchmarkLog
from edl_tpu.train.loop import LoopConfig, TrainLoop
from edl_tpu.train.state import TrainState
from edl_tpu.train.step import make_train_step
from edl_tpu.utils.config import from_env
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.examples.lm_train")


def make_synthetic_shards(data_dir: str, n_files: int, rows: int,
                          seq_len: int, vocab: int, seed: int = 0) -> None:
    """Markov-chain token shards (learnable: next-token depends on
    current token through a fixed random transition table)."""
    os.makedirs(data_dir, exist_ok=True)
    gen = np.random.default_rng(55)
    # each token has 8 plausible successors
    successors = gen.integers(0, vocab, size=(vocab, 8))
    for i in range(n_files + 1):  # last = validation
        rng = np.random.default_rng(seed * 271 + i)
        toks = np.empty((rows, seq_len), np.int32)
        toks[:, 0] = rng.integers(0, vocab, size=rows)
        for t in range(1, seq_len):
            pick = rng.integers(0, 8, size=rows)
            toks[:, t] = successors[toks[:, t - 1], pick]
        name = "val.npz" if i == n_files else f"train-{i:04d}.npz"
        np.savez(os.path.join(data_dir, name), tokens=toks)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="edl_tpu.examples.lm_train")
    parser.add_argument("--data-dir", required=True)
    parser.add_argument("--make-synthetic", type=int, default=0)
    parser.add_argument("--rows-per-file", type=int, default=512)
    parser.add_argument("--loader-workers", type=int, default=None,
                        help="input-plane worker PROCESSES with "
                             "shared-memory batch hand-off (default: "
                             "$EDL_TPU_LOADER_WORKERS, else 0 = inline)")
    parser.add_argument("--vocab", type=int, default=512)
    parser.add_argument("--seq-len", type=int, default=256)
    parser.add_argument("--d-model", type=int, default=256)
    parser.add_argument("--n-heads", type=int, default=8)
    parser.add_argument("--n-layers", type=int, default=4)
    parser.add_argument("--d-ff", type=int, default=1024)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--schedule-epochs", type=int, default=0,
                        help="LR horizon (default --epochs); pin to the "
                             "job's total for elastic segments")
    parser.add_argument("--batch-size", type=int, default=32,
                        help="GLOBAL batch size")
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--warmup-steps", type=int, default=100)
    parser.add_argument("--bf16", action="store_true")
    parser.add_argument("--fp16", action="store_true",
                        help="float16 activations + dynamic loss scaling "
                             "(train/amp.py; the reference's --fp16/"
                             "--scale_loss). bf16 is the TPU-native "
                             "choice — this exists for parity and "
                             "fp16 experiments")
    parser.add_argument("--fused-loss", action="store_true",
                        help="streamed-vocab CE: never materializes the "
                             "(B,S,V) logits (ops/fused_xent.py) — use "
                             "when the vocab is large")
    parser.add_argument("--dcn-compress", choices=("off", "topk", "int8"),
                        default=None,
                        help="cross-slice gradient wire format (default "
                             "$EDL_TPU_DCN_COMPRESS, else off): topk "
                             "ships values+indices, int8 one scale per "
                             "chip — both with error-feedback residuals "
                             "behind the loss-parity gate "
                             "(doc/design_comm.md)")
    parser.add_argument("--comm-bucket-mb", type=float, default=None,
                        help="bucket the gradient tree into N-MiB "
                             "reduction groups so late-backward buckets "
                             "overlap earlier buckets' communication "
                             "(default $EDL_TPU_COMM_BUCKET_MB, else 0 "
                             "= XLA's single fused reduction)")
    parser.add_argument("--moe", action="store_true",
                        help="mixture-of-experts FFNs: top-k capacity-"
                             "factor router, expert tables sharded over "
                             "an ep mesh, hierarchical all-to-all "
                             "dispatch (train/comm.py; "
                             "doc/design_comm.md)")
    parser.add_argument("--n-experts", type=int, default=0,
                        help="expert count (default 2x device count; "
                             "must divide evenly over the devices)")
    parser.add_argument("--moe-top-k", type=int, default=2,
                        help="experts per token")
    parser.add_argument("--moe-dispatch", choices=("flat", "hier"),
                        default=None,
                        help="MoE all-to-all decomposition (default "
                             "$EDL_TPU_MOE_DISPATCH, else hier): flat = "
                             "one global collective; hier = ICI leg + "
                             "cross-slice DCN leg, bitwise with flat")
    parser.add_argument("--moe-compress", choices=("off", "int8"),
                        default=None,
                        help="MoE DCN-leg wire format (default "
                             "$EDL_TPU_MOE_COMPRESS, else off): int8 "
                             "ships dispatched activations at one scale "
                             "per destination slice (parity-gated)")
    parser.add_argument("--fused-opt",
                        choices=("off", "fp32", "int8", "fp8"),
                        default=None,
                        help="fused optimizer path (train/fused_opt.py; "
                             "default $EDL_TPU_FUSED_OPT, else off): "
                             "fp32 = one kernel pass per bucket, "
                             "bitwise vs the optax chain; int8/fp8 also "
                             "hold the adam moments quantized with "
                             "error-feedback residuals (opt state and "
                             "checkpoint bytes halve, convergence-"
                             "parity gated)")
    parser.add_argument("--remat", choices=("off", "on", "auto"),
                        default="off",
                        help="per-block activation checkpointing: on = "
                             "always, auto = models.transformer."
                             "choose_remat decides from the activation-"
                             "footprint estimate vs device memory")
    parser.add_argument("--mesh", choices=("dp", "fsdp", "sp"),
                        default="dp",
                        help="dp: data parallel; fsdp: params sharded; "
                             "sp: sequence parallel — ring attention over "
                             "the sequence axis (long-context mode)")
    parser.add_argument("--fsdp", action="store_true",
                        help=argparse.SUPPRESS)  # legacy alias of --mesh fsdp
    parser.add_argument("--ckpt-dir", default="")
    parser.add_argument("--ckpt-sharded", action="store_true")
    parser.add_argument("--ckpt-steps", type=int, default=None,
                        help="also checkpoint every N optimizer steps "
                             "(cheap under async saves; default "
                             "$EDL_TPU_CKPT_STEPS, else epoch-end only)")
    parser.add_argument("--ckpt-sync", action="store_true",
                        help="synchronous saves (escape hatch; default "
                             "async snapshot-then-write)")
    parser.add_argument("--benchmark-log", default="")
    parser.add_argument("--profile", default="",
                        help="jax profiler trace dir (steps 10-15, rank 0)")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    if args.fp16 and args.bf16:
        parser.error("--fp16 and --bf16 are mutually exclusive")

    if 0 < args.schedule_epochs < args.epochs:
        raise SystemExit(
            f"--schedule-epochs {args.schedule_epochs} < --epochs "
            f"{args.epochs}: epochs past the horizon would train at "
            "LR ~0 (the horizon is the job TOTAL; the stop point is "
            "--epochs)")
    distributed.force_platform_from_env()
    env = distributed.init_from_env()
    world = max(1, env.world_size)
    rank = max(0, env.rank)
    if args.make_synthetic and rank == 0:
        make_synthetic_shards(args.data_dir, args.make_synthetic,
                              args.rows_per_file, args.seq_len, args.vocab,
                              args.seed)
    if args.make_synthetic and jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("edl_lm_data_gen")

    files = sorted(os.path.join(args.data_dir, f)
                   for f in os.listdir(args.data_dir)
                   if f.startswith("train-") and f.endswith(".npz"))
    if not files:
        raise SystemExit(f"no train-*.npz under {args.data_dir}")
    if args.batch_size % world:
        raise SystemExit("global batch not divisible by world")
    local_bs = args.batch_size // world

    ckpt_kw = {}
    if args.ckpt_steps is not None:
        ckpt_kw["ckpt_every_steps"] = args.ckpt_steps
    if args.ckpt_sync:
        ckpt_kw["ckpt_async"] = False
    loop_cfg = from_env(LoopConfig, num_epochs=args.epochs,
                        ckpt_dir=args.ckpt_dir or env.checkpoint_path
                        or None, ckpt_sharded=args.ckpt_sharded,
                        profile_dir=args.profile or None, **ckpt_kw)
    # --loader-workers wins when given; otherwise the LoopConfig (its
    # EDL_TPU_LOADER_WORKERS binding) sets the mp pool width.
    loader_workers = (args.loader_workers
                      if args.loader_workers is not None
                      else loop_cfg.loader_workers)

    if args.fsdp and args.mesh != "dp":
        raise SystemExit("--fsdp is a legacy alias of --mesh fsdp; "
                         f"it conflicts with --mesh {args.mesh}")
    kind = "fsdp" if args.fsdp else args.mesh
    if kind == "sp":
        if world > 1:
            # rank-sharded loading + replicate_host_tree assume a data
            # axis; an sp-only mesh would feed divergent "replicated"
            # batches across processes — corrupt, not slow.
            raise SystemExit("--mesh sp is single-process long-context "
                             "mode; combine sp with dp/fsdp axes for "
                             "multi-pod (see parallel/mesh.MeshSpec)")
        n_dev = jax.device_count()
        if args.seq_len % n_dev:
            raise SystemExit(f"--mesh sp shards the sequence over "
                             f"{n_dev} devices; --seq-len {args.seq_len} "
                             f"is not divisible by {n_dev}")
    if args.moe:
        if kind != "dp":
            raise SystemExit(f"--moe owns the ep mesh (expert tables "
                             f"sharded over every chip); --mesh {kind} "
                             "conflicts")
        if args.fp16:
            raise SystemExit("--moe is not supported with --fp16 (the "
                             "MoE comm step owns the backward; no "
                             "loss-scale hook)")
        if args.fused_loss:
            raise SystemExit("--fused-loss has no MoE variant (the MoE "
                             "loss collects router aux terms)")
        if args.batch_size % jax.device_count():
            raise SystemExit(f"--moe routes per chip: --batch-size "
                             f"{args.batch_size} must divide over "
                             f"{jax.device_count()} devices")
    # env-aware: multi-slice jobs get the hybrid ICI x DCN layout (needs
    # a dp axis — or ep under --moe — to carry DCN; other --mesh kinds
    # fail fast there); single-slice worlds get the flat mesh as before
    mesh = distributed.make_mesh_from_env(
        mesh_lib.MeshSpec({"ep" if args.moe else kind: -1}), env)
    # DCN-aware gradient path: CLI > env (LoopConfig binding) > off.
    # A compressed wire implies bucketing (default 4 MiB target).
    dcn_compress = (args.dcn_compress if args.dcn_compress is not None
                    else loop_cfg.dcn_compress)
    comm_bucket_mb = (args.comm_bucket_mb
                      if args.comm_bucket_mb is not None
                      else loop_cfg.comm_bucket_mb)
    comm_cfg = None
    if dcn_compress != "off" or comm_bucket_mb > 0:
        if kind != "dp":
            raise SystemExit(
                f"--dcn-compress/--comm-bucket-mb own the dp gradient "
                f"reduction; --mesh {kind} keeps the XLA-partitioned "
                "step (fsdp/tp collectives are slice-local already)")
        if args.fp16:
            raise SystemExit("--dcn-compress/--comm-bucket-mb are not "
                             "supported with --fp16 (the manual path "
                             "owns the backward's reduction)")
        from edl_tpu.train.comm import CommConfig
        comm_cfg = CommConfig(bucket_mb=comm_bucket_mb or 4.0,
                              compress=dcn_compress)
    # MoE dispatch knobs: CLI > env (LoopConfig binding) > hier/off.
    moe_dispatch = (args.moe_dispatch if args.moe_dispatch is not None
                    else loop_cfg.moe_dispatch)
    moe_compress = (args.moe_compress if args.moe_compress is not None
                    else loop_cfg.moe_compress)
    if args.moe and dcn_compress != "off":
        raise SystemExit("--dcn-compress compresses the dp gradient "
                         "wire; under --moe the wire knob is "
                         "--moe-compress (gradient compression over "
                         "the ep axis is not parity-gated yet)")
    # Fused optimizer path: CLI > env (LoopConfig binding) > off;
    # EDL_TPU_OPT_QUANT overrides just the resident-moment codec.
    fused_opt = (args.fused_opt if args.fused_opt is not None
                 else loop_cfg.fused_opt)
    if loop_cfg.opt_quant and fused_opt != "off":
        if loop_cfg.opt_quant not in ("off", "int8", "fp8"):
            raise SystemExit(f"EDL_TPU_OPT_QUANT must be off|int8|fp8, "
                             f"got {loop_cfg.opt_quant!r}")
        fused_opt = ("fp32" if loop_cfg.opt_quant == "off"
                     else loop_cfg.opt_quant)
    if fused_opt not in ("off", "fp32", "int8", "fp8"):
        raise SystemExit(f"EDL_TPU_FUSED_OPT must be off|fp32|int8|fp8, "
                         f"got {fused_opt!r}")
    if args.fp16 and fused_opt in ("int8", "fp8"):
        raise SystemExit(
            "--fused-opt int8/fp8 is not supported with --fp16: on a "
            "non-finite step the loss-scaler rolls the state back, but "
            "quantized moments would still carry the overflowed "
            "requantization residuals. Use --fused-opt fp32 (bitwise, "
            "rollback-safe) or bf16/fp32 activations.")
    moe_kw = {}
    if args.moe:
        moe_kw = dict(moe=True,
                      n_experts=args.n_experts or 2 * jax.device_count(),
                      moe_top_k=args.moe_top_k)
    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff, max_len=args.seq_len,
        dtype=(jnp.float16 if args.fp16
               else jnp.bfloat16 if args.bf16 else jnp.float32),
        # the comm/moe step's manual region is mesh-free: sharding
        # constraints / nested shard_maps would clash with the manual
        # dp/ep axis — each shard computes exactly one chip's backward
        mesh=None if (comm_cfg is not None or args.moe) else mesh,
        **moe_kw)
    if args.remat != "off":
        from edl_tpu.models.transformer import auto_remat
        cfg = (auto_remat(cfg, local_bs)
               if args.remat == "auto"
               else dataclasses.replace(cfg, remat=True))
        log.info("remat=%s (mode %s)", cfg.remat, args.remat)
    model = Transformer(cfg)

    source = FileSource(files)
    loader = DataLoader(source, local_bs, rank=rank, world=world,
                        seed=args.seed, num_workers=loader_workers)
    steps_per_epoch = loader.steps_per_epoch()
    total_steps = steps_per_epoch * (args.schedule_epochs or args.epochs)
    # --batch-size is GLOBAL: LR stays batch-tied across elastic resizes
    # (scale_for_world is for per-pod batch semantics)
    schedule = lr_lib.cosine_with_warmup(
        args.lr, total_steps,
        min(args.warmup_steps, max(1, total_steps // 10)))
    if fused_opt != "off":
        from edl_tpu.train.fused_opt import make_fused_tx
        tx = make_fused_tx("adam", schedule, fused_opt,
                           weight_decay=0.01)
        log.info("fused optimizer path: adam %s", fused_opt)
    else:
        tx = optax.adamw(schedule, weight_decay=0.01)

    toks0 = jnp.zeros((1, args.seq_len), jnp.int32)
    variables = shd.init_sharded(
        lambda: model.init(jax.random.PRNGKey(args.seed), toks0,
                           train=False), mesh)
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"], tx=tx)
    loss = lm_loss_fused if args.fused_loss else lm_loss_fn
    if args.fp16:
        # TrainLoop's contract is step(state, batch); the loss-scale
        # state rides a closure cell. It is NOT checkpointed — after an
        # elastic restart the scale re-warms from init, costing at most
        # a few skipped steps (the reference's decorate() state is
        # likewise process-local).
        from edl_tpu.train.amp import DynamicLossScale
        raw_step = make_train_step(loss, donate=True, loss_scale=True)
        ls_box = [DynamicLossScale.create()]

        def step(state, batch):
            state, metrics, ls_box[0] = raw_step(state, batch, ls_box[0])
            return state, metrics
    elif args.moe:
        from edl_tpu.train.comm import (MoEDispatchConfig,
                                        make_moe_comm_step)

        def moe_loss_factory(wire):
            wired = Transformer(dataclasses.replace(cfg, moe_wire=wire))
            return functools.partial(lm_loss_moe,
                                     aux_weight=cfg.moe_aux_weight,
                                     apply_fn=wired.apply)

        step = make_moe_comm_step(
            moe_loss_factory, mesh=mesh,
            topology=distributed.slice_topology(env),
            config=comm_cfg, donate=True,
            moe_config=MoEDispatchConfig(mode=moe_dispatch,
                                         compress=moe_compress))
        log.info("moe path: E=%d top_k=%d dispatch=%s compress=%s",
                 cfg.n_experts, cfg.moe_top_k, moe_dispatch,
                 moe_compress)
    elif comm_cfg is not None:
        step = make_train_step(loss, donate=True, comm=comm_cfg,
                               mesh=mesh,
                               topology=distributed.slice_topology(env))
        log.info("dcn-aware gradient path: bucket=%.1fMiB compress=%s",
                 comm_cfg.bucket_mb, comm_cfg.compress)
    else:
        step = make_train_step(loss, donate=True)
    log.info("world=%d rank=%d devices=%d params=%s steps/epoch=%d",
             world, rank, jax.device_count(),
             sum(p.size for p in jax.tree.leaves(state.params)),
             steps_per_epoch)

    eval_toks = None
    val_path = os.path.join(args.data_dir, "val.npz")
    if os.path.exists(val_path):
        with np.load(val_path) as z:
            eval_toks = z["tokens"][: 4 * local_bs]

    # eval must honor the fused path too — the dense loss would
    # materialize exactly the logits tensor --fused-loss exists to avoid
    # (MoE eval rides the jit-dense router: global-batch capacity)
    eval_loss_fn = (functools.partial(lm_loss_moe,
                                      aux_weight=cfg.moe_aux_weight)
                    if args.moe
                    else lm_loss_fused if args.fused_loss else lm_loss_fn)
    eval_step = jax.jit(lambda s, b: eval_loss_fn(s, s.params, b)[0])
    blog = BenchmarkLog(f"transformer_lm_{args.d_model}d{args.n_layers}L",
                        batch_size=args.batch_size, world_size=world)
    epoch_t0 = [time.perf_counter()]

    def eval_fn(state, epoch):
        elapsed = time.perf_counter() - epoch_t0[0]
        # per-rank sequences/s under the examples_per_sec key: benchlog
        # world-scales exactly that key into the global figure
        # (max_examples_per_sec_global); tokens_per_sec is pre-scaled.
        seqs_per_sec = steps_per_epoch * local_bs / max(elapsed, 1e-9)
        results = {"examples_per_sec": seqs_per_sec,
                   "tokens_per_sec": seqs_per_sec * args.seq_len * world}
        if eval_toks is not None:
            losses = [float(eval_step(state, {"tokens": jnp.asarray(
                eval_toks[lo:lo + local_bs])}))
                for lo in range(0, len(eval_toks) - local_bs + 1, local_bs)]
            results["eval_loss"] = float(np.mean(losses))
        blog.epoch(epoch, **results)
        epoch_t0[0] = time.perf_counter()
        return results

    loop = TrainLoop(
        step, state, mesh=mesh, config=loop_cfg, eval_fn=eval_fn,
        place_state=lambda t: mesh_lib.replicate_host_tree(mesh, t),
        batch_axes=("ep",) if args.moe else None)

    def data_fn(epoch):
        return ({"tokens": b["tokens"]} for b in loader.epoch(epoch))

    data_fn.close = loader.close  # TrainLoop tears down the mp workers
    status = loop.run(data_fn)
    blog.extra(**loop.ckpt_stats())  # save-stall / restore accounting
    if comm_cfg is not None or args.moe:
        blog.extra(**step.stats())  # bucket plan + DCN wire accounting
    if rank == 0 and args.benchmark_log:
        blog.write(args.benchmark_log, rank)
    final = blog.finalize().get("final", {})
    log.info("done: epoch=%d step=%d %s", status.epoch, status.step, final)
    if "eval_loss" in final:
        print(f"final_eval_loss={final['eval_loss']:.4f}")
    distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
