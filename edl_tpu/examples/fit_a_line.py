"""fit_a_line: linear-regression smoke job with checkpoint/resume.

The minimum end-to-end slice (BASELINE config 1; reference
example/fit_a_line/train_ft.py): a single-process job exercising the whole
framework path — typed config, mesh, jitted SPMD step, TrainLoop,
atomic versioned checkpoints, resume.

    python -m edl_tpu.examples.fit_a_line --num_epochs 5 --ckpt_dir /tmp/fal

Re-running with the same --ckpt_dir resumes from the last completed epoch.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.models.linear import LinearRegression, mse_loss
from edl_tpu.parallel.mesh import make_mesh
from edl_tpu.train.loop import LoopConfig, TrainLoop
from edl_tpu.train.state import TrainState
from edl_tpu.train.step import make_train_step
from edl_tpu.utils.config import describe, field, from_env
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.examples.fit_a_line")

NUM_FEATURES = 13  # uci-housing shape


@dataclass
class Config:
    num_epochs: int = field(5, env="EDL_TPU_NUM_EPOCHS")
    steps_per_epoch: int = 50
    batch_size: int = 64
    lr: float = 0.05
    seed: int = 0
    ckpt_dir: str | None = field(None, env="EDL_TPU_CHECKPOINT_PATH")


def synthetic_batches(epoch: int, cfg: Config):
    """Deterministic per-epoch data order (seed-per-pass)."""
    rng = np.random.default_rng(cfg.seed * 1000 + epoch)
    w = np.arange(1, NUM_FEATURES + 1, dtype=np.float32) / NUM_FEATURES
    for _ in range(cfg.steps_per_epoch):
        x = rng.standard_normal((cfg.batch_size, NUM_FEATURES),
                                dtype=np.float32)
        y = x @ w[:, None] + 0.5 + 0.01 * rng.standard_normal(
            (cfg.batch_size, 1), dtype=np.float32)
        yield {"x": x, "y": y}


def build(cfg: Config):
    model = LinearRegression(features=1)
    params = model.init(jax.random.key(cfg.seed),
                        jnp.zeros((1, NUM_FEATURES)))["params"]
    tx = optax.sgd(cfg.lr)
    state = TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    def loss_fn(state, params, batch):
        pred = state.apply_fn({"params": params}, batch["x"])
        return mse_loss(pred, batch["y"]), {}

    return state, make_train_step(loss_fn)


def main(argv: list[str] | None = None) -> float:
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_epochs", type=int, default=None)
    parser.add_argument("--ckpt_dir", default=None)
    parser.add_argument("--batch_size", type=int, default=None)
    args = parser.parse_args(argv)
    overrides = {k: v for k, v in vars(args).items() if v is not None}
    cfg = from_env(Config, **overrides)
    log.info("\n%s", describe(cfg))

    mesh = make_mesh()
    state, step_fn = build(cfg)
    loop = TrainLoop(
        step_fn, state, mesh=mesh,
        config=LoopConfig(num_epochs=cfg.num_epochs, ckpt_dir=cfg.ckpt_dir,
                          log_every_steps=25),
    )
    loop.run(lambda epoch: synthetic_batches(epoch, cfg))
    if loop.last_metrics:
        final_loss = float(loop.last_metrics["loss"])
        log.info("done: epoch=%d step=%d loss=%.5f",
                 loop.status.epoch, loop.status.step, final_loss)
        return final_loss
    log.info("done (nothing to train): epoch=%d step=%d",
             loop.status.epoch, loop.status.step)
    return 0.0


if __name__ == "__main__":
    main()
