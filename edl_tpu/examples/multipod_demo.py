"""One-world multi-pod data-parallel trainer — the flagship trainer path.

The capability the reference reaches through `fleet.init(is_collective=True)`
(example/collective/resnet50/train_with_fleet.py:376-377 — every trainer
joins ONE NCCL world formed from the PADDLE_TRAINER_* env the launcher
exported, collective/launch.py:163-194): here each launcher-spawned trainer
calls `init_from_env()`, which joins the `jax.distributed` world at the
rank-0 pod's coordinator endpoint; a single `dp` mesh then spans every
pod's devices and one jitted train step carries the gradient all-reduce —
XLA compiles it over ICI/DCN (gloo on CPU test worlds).

Determinism contract (what makes elastic resize testable): the data stream
is a function of (epoch, global batch size) ONLY — each process feeds its
rank's slice of the same global batch — so a run resized N->M pods produces
bit-comparable parameters to an unresized run, modulo reduction order.

  launcher: python -m edl_tpu.collective.launch --store HOST:PORT -- \
      python -m edl_tpu.examples.multipod_demo --epochs 5
"""

from __future__ import annotations

import argparse
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.models.linear import LinearRegression, mse_loss
from edl_tpu.parallel import distributed, mesh as mesh_lib
from edl_tpu.train.loop import LoopConfig, TrainLoop
from edl_tpu.train.state import TrainState
from edl_tpu.train.step import make_train_step
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.examples.multipod_demo")

TRUE_W, TRUE_B = 3.0, -1.5


def make_global_data(epoch: int, steps: int, global_batch: int):
    """The full epoch stream, identical on every process (seed-per-pass)."""
    rng = np.random.default_rng(7000 + epoch)
    n = steps * global_batch
    x = rng.normal(size=(n, 1)).astype(np.float32)
    y = (TRUE_W * x + TRUE_B
         + 0.01 * rng.normal(size=(n, 1)).astype(np.float32))
    for i in range(steps):
        s = slice(i * global_batch, (i + 1) * global_batch)
        yield {"x": x[s], "y": y[s]}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=5)
    parser.add_argument("--steps-per-epoch", type=int, default=20)
    parser.add_argument("--global-batch", type=int, default=32)
    parser.add_argument("--step-time", type=float, default=0.0,
                        help="artificial per-step delay (resize-window test)")
    parser.add_argument("--out", default="",
                        help="rank 0 writes final params JSON here")
    args = parser.parse_args(argv)

    distributed.force_platform_from_env()  # before any backend init
    env = distributed.init_from_env()  # forms the world iff world_size > 1
    world = max(1, env.world_size)
    if args.global_batch % world:
        raise SystemExit(f"global batch {args.global_batch} not divisible "
                         f"by world size {world}")
    local_bs = args.global_batch // world
    # Hybrid ICI x DCN mesh when the job is (or declares itself)
    # multi-slice — dp's major dimension crosses DCN; flat dp otherwise.
    mesh = distributed.make_mesh_from_env(mesh_lib.MeshSpec({"dp": -1}),
                                          env)
    topo = distributed.slice_topology(env)
    log.info("trainer up: rank=%d world=%d devices=%d cluster_v=%d "
             "slices=%dx%d", env.rank, world, jax.device_count(),
             env.cluster_version, topo.n_slices, topo.chips_per_slice)

    model = LinearRegression(features=1)
    tx = optax.sgd(0.05)
    replicated = mesh_lib.replicated(mesh)

    def build_state():
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 1)))["params"]
        return TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    # Params materialize directly as global replicated arrays — in a
    # multi-process world host-created state can't feed a global-mesh jit.
    state = jax.jit(build_state, out_shardings=replicated)()

    def loss_fn(state, params, batch):
        pred = state.apply_fn({"params": params}, batch["x"])
        return mse_loss(pred, batch["y"]), {}

    step = make_train_step(loss_fn, donate=False)
    if args.step_time > 0:
        import time
        raw_step = step

        def step(s, b):  # noqa: F811 — wrapped for the resize-window test
            time.sleep(args.step_time)
            return raw_step(s, b)

    def data_fn(epoch):
        for g in make_global_data(epoch, args.steps_per_epoch,
                                  args.global_batch):
            lo = env.rank * local_bs
            local = {k: v[lo:lo + local_bs] for k, v in g.items()}
            yield mesh_lib.form_global_batch(mesh, local)

    from edl_tpu.utils.config import from_env
    # from_env picks up the launcher-forwarded EDL_TPU_* overrides —
    # notably EDL_TPU_CKPT_REMOTE for the gs:// checkpoint mirror on
    # clusters without a shared FS (deploy/k8s/train-job.yaml).
    loop = TrainLoop(step, state, config=from_env(
        LoopConfig,
        num_epochs=args.epochs,
        ckpt_dir=env.checkpoint_path or None,
        log_every_steps=args.steps_per_epoch),
        place_state=lambda t: mesh_lib.replicate_host_tree(mesh, t))
    status = loop.run(data_fn)

    w = float(np.asarray(loop.state.params["Dense_0"]["kernel"])[0, 0])
    b = float(np.asarray(loop.state.params["Dense_0"]["bias"])[0])
    log.info("done: epoch=%d step=%d w=%.5f b=%.5f", status.epoch,
             status.step, w, b)
    if args.out and jax.process_index() == 0:
        with open(args.out, "w") as f:
            json.dump({"w": w, "b": b, "epoch": status.epoch,
                       "step": status.step, "world": world}, f)
    distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
