"""Elastic trainer demo — the end-to-end probe for the launcher.

Capability of the reference's `edl_demo.py` + fit_a_line fault-tolerant job
(example/demo/collective/ + example/fit_a_line/train_ft.py): a tiny linear
regression that reads the launcher's TrainerEnv, trains its data shard with
checkpoint/resume, and survives stop-resume resizes. Runs on CPU; with a
multi-pod world it shards data by rank (orchestration-level elasticity —
the same TrainLoop drives pjit models on real TPU meshes).

  python -m edl_tpu.examples.elastic_demo --epochs 5 --steps-per-epoch 20

`--scaler` turns the demo into the full controller-driven elasticity
loop on one host: an in-process store + JobServer + JobClient spawn
launcher pods running THIS trainer, while a leader-elected
`ScalerController` (edl_tpu/scaler) scrapes the trainers' published
utilization and resizes the job through `/resize` — every decision
journaled. The closed loop the reference's scheduler pillar describes,
runnable on a laptop:

  python -m edl_tpu.examples.elastic_demo --scaler --nodes-range 1:2

`--serve-scaler` runs the OTHER elasticity loop — the serving plane: a
teacher pool behind the discovery registry, an open-loop load
generator, and a `ServingPolicy` holding a latency SLO by growing the
pool on sustained breach and DRAINING it on sustained idleness
(`run_serve_scaler_demo`):

  python -m edl_tpu.examples.elastic_demo --serve-scaler
"""

from __future__ import annotations

import argparse
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.collective.job_env import TrainerEnv
from edl_tpu.models.linear import LinearRegression, mse_loss
from edl_tpu.train.loop import LoopConfig, TrainLoop
from edl_tpu.train.state import TrainState
from edl_tpu.train.step import make_train_step
from edl_tpu.utils.config import from_env
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.examples.elastic_demo")

TRUE_W, TRUE_B = 3.0, -1.5


def make_data(epoch: int, rank: int, world: int, steps: int, batch: int):
    """Seed-per-pass + shard-by-rank (reference pass_id_as_seed recipe)."""
    rng = np.random.default_rng(1000 + epoch)
    n = steps * batch * max(1, world)
    x = rng.normal(size=(n, 1)).astype(np.float32)
    y = TRUE_W * x + TRUE_B + 0.01 * rng.normal(size=(n, 1)).astype(
        np.float32)
    shard = slice(rank * steps * batch, (rank + 1) * steps * batch)
    xs, ys = x[shard], y[shard]
    for i in range(steps):
        s = slice(i * batch, (i + 1) * batch)
        yield {"x": xs[s], "y": ys[s]}


def run_scaler_demo(args) -> int:
    """Controller-driven elasticity end-to-end on this host: store +
    JobServer + JobClient-spawned launcher pods + ScalerController, all
    wired to each other; returns non-zero if the job never completes,
    a resize the JobServer served escaped the decision journal (the
    served resize_log and the journal's applied resizes must match),
    or the scaler never observed fresh utilization while the node
    range left it room to act (the silently-doing-nothing failure)."""
    import os
    import shutil
    import subprocess
    import tempfile
    import threading
    import time

    from edl_tpu.collective import register as reg
    from edl_tpu.collective.job_server import JobClient, JobServer, JobState
    from edl_tpu.coord.server import StoreServer
    from edl_tpu.scaler.controller import ScalerConfig, ScalerController
    from edl_tpu.scaler.policy import ThroughputPolicy

    # the spawned pods are CPU trainers (the orchestration is the demo);
    # never let a child dial a TPU tunnel or fan out virtual devices
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["JAX_NUM_CPU_DEVICES"] = "1"

    job_id = "scaler_demo"
    lo, hi = (int(x) for x in args.nodes_range.split(":"))
    tmp = tempfile.mkdtemp(prefix="edl-scaler-demo-")
    journal_path = args.journal or os.path.join(tmp, "scaler.jsonl")
    srv = StoreServer(port=0, host="127.0.0.1", sweep_interval=0.2).start()
    store_ep = f"127.0.0.1:{srv.port}"
    state = JobState(job_id, lo, hi, desired=lo)
    server = JobServer(state, port=0).start()
    trainer_cmd = [
        sys.executable, "-m", "edl_tpu.collective.launch",
        "--store", store_ep, "--job-id", job_id,
        "--nodes-range", f"{lo}:{hi}",
        "--checkpoint-path", os.path.join(tmp, "ckpt"),
        "--log-dir", os.path.join(tmp, "log"), "--",
        sys.executable, "-m", "edl_tpu.examples.elastic_demo",
        "--epochs", str(args.epochs),
        "--steps-per-epoch", str(args.steps_per_epoch),
        "--batch", str(args.batch),
        # pace the trainers a little by default: an instant run would
        # complete before the scaler ever observes a utilization record
        "--step-time", str(args.step_time or 0.05),
        "--ckpt-steps", str(args.ckpt_steps or 10)]
    client = JobClient(f"127.0.0.1:{server.port}", trainer_cmd, poll=0.5)
    client_thread = threading.Thread(target=client.run, daemon=True,
                                     name="scaler-demo-jobclient")
    config = ScalerConfig(interval=args.scaler_interval,
                          cooldown_s=args.scaler_cooldown,
                          downtime_s=args.scaler_downtime,
                          staleness_s=10.0)
    controller = ScalerController(
        srv.store, [job_id],
        ThroughputPolicy(gain_threshold=config.gain_threshold,
                         cooldown_s=config.cooldown_s,
                         horizon_s=max(config.cooldown_s, 30.0)),
        config=config, job_server=f"127.0.0.1:{server.port}",
        journal_path=journal_path, owner="scaler-demo")
    log.info("scaler demo: store=%s job_server=:%d nodes=%d:%d "
             "journal=%s", store_ep, server.port, lo, hi, journal_path)
    complete = False
    try:
        client_thread.start()
        controller.start()
        deadline = time.time() + args.scaler_timeout
        while time.time() < deadline:
            if srv.store.get(reg.complete_key(job_id)) is not None:
                complete = True
                break
            time.sleep(0.5)
    finally:
        controller.stop()
        client.stop()
        client_thread.join(timeout=15)
        for p in client.procs:  # belt and braces: no orphan launchers
            if p.poll() is None:
                p.kill()
        server.stop()
        srv.stop()

    entries = []
    try:
        with open(journal_path, encoding="utf-8") as f:
            entries = [json.loads(line) for line in f if line.strip()]
    except OSError:
        pass
    resizes = [e for e in entries if e["action"] == "resize"]
    # Cross-check the docstring's promise: every resize the JobServer
    # actually served must have a matching journal entry (same applied
    # values, same order). `final_desired` moving off the initial `lo`
    # with an empty journal is the same escape.
    served = [s["to"] for s in state.resize_log]
    journaled = [e["applied"] if e.get("applied") is not None
                 else e["desired"] for e in resizes]
    escaped = served != journaled or \
        state.desired != (served[-1] if served else lo)
    # A scaler that silently does nothing (e.g. every record filtered
    # as pre-resize) never sees fresh utilization: with room to act
    # (hi > lo) that is a failure, not a quiet pass.
    fresh_seen = any(e.get("fresh") for e in entries)
    silent = hi > lo and not fresh_seen
    summary = {"complete": complete, "decisions": len(entries),
               "resizes": [{"tick": e["seq"], "from": e["current"],
                            "to": e["desired"], "reason": e["reason"]}
                           for e in resizes],
               "served_resizes": state.resize_log,
               "journal_matches_served": not escaped,
               "fresh_utilization_seen": fresh_seen,
               "final_desired": state.desired,
               "journal": journal_path if args.journal else None}
    log.info("scaler demo done: complete=%s decisions=%d resizes=%d "
             "served=%d journal_matches_served=%s fresh_seen=%s",
             complete, len(entries), len(resizes), len(served),
             not escaped, fresh_seen)
    if escaped:
        log.error("resize escaped the decision journal: served=%s "
                  "journaled=%s final_desired=%d", served, journaled,
                  state.desired)
    if silent:
        log.error("scaler never observed fresh utilization (nodes %d:%d"
                  ") — the closed loop is not closing", lo, hi)
    # machine-readable (mirrors the ckpt_stats= convention bench.py reads)
    print("scaler_summary=" + json.dumps(summary), flush=True)
    if args.journal is None:
        shutil.rmtree(tmp, ignore_errors=True)
    else:
        shutil.rmtree(os.path.join(tmp, "ckpt"), ignore_errors=True)
    return 0 if complete and not escaped and not silent else 1


def run_serve_scaler_demo(args) -> int:
    """Serving elasticity end-to-end on this host: an in-process store,
    a `TeacherPoolActuator` spawning real `TeacherServer`s (sleepy
    predict_fn standing in for chip time) with registrars publishing
    latency/queue stats, an open-loop load generator, and a
    `ScalerController` running a `ServingPolicy` — the closed loop from
    student traffic to pool size. Three load phases: cruise (SLO met),
    a 4x step (sustained p95 breach -> grow), then near-idle
    (utilization under the low-water mark -> DRAINED shrink).

    Self-audits on exit and returns non-zero unless:

      - at least one grow AND one shrink were journaled and applied,
      - every actuated pool resize has a matching journal entry,
      - at least one shrink completed as a graceful DRAIN (deregister
        -> in-flight work done -> stop), with zero hard kills,
      - the pool's latency SLO was met again by the end of the run.

    Prints a machine-readable ``serve_summary=`` line (bench.py-style).
    """
    import os
    import shutil
    import tempfile
    import threading
    import time

    import numpy as np

    from edl_tpu.coord.registry import ServiceRegistry
    from edl_tpu.coord.server import StoreServer
    from edl_tpu.distill.registrar import DISTILL_ROOT, TeacherRegistrar
    from edl_tpu.distill.teacher_server import TeacherClient, TeacherServer
    from edl_tpu.scaler.controller import ScalerConfig, ScalerController
    from edl_tpu.scaler.policy import ThroughputPolicy
    from edl_tpu.scaler.serving import (LocalTeacher, ServingConfig,
                                        ServingPolicy, TeacherPoolActuator)

    service = "serve_demo_teacher"
    tmp = tempfile.mkdtemp(prefix="edl-serve-scaler-")
    journal_path = args.journal or os.path.join(tmp, "serving.jsonl")
    srv = StoreServer(port=0, host="127.0.0.1", sweep_interval=0.2).start()
    per_row_s = 0.002      # the fake chip: 2 ms per row
    request_rows = 8

    def spawn(index: int) -> LocalTeacher:
        def predict(feeds):
            rows = next(iter(feeds.values())).shape[0]
            time.sleep(rows * per_row_s)
            return {"logits": np.zeros((rows, 4), np.float32)}
        server = TeacherServer(predict, port=0, host="127.0.0.1",
                               max_batch=32, max_wait=0.001).start()
        registrar = TeacherRegistrar(
            srv.store, service, f"127.0.0.1:{server.port}",
            ttl=2.0, stats_interval=0.25, probe_timeout=10.0)
        registrar.start()
        return LocalTeacher(server, registrar)

    serve_cfg = ServingConfig(
        slo_p95_ms=200.0, queue_high=4.0, util_low=0.25,
        breach_ticks=2, idle_ticks=3, cooldown_s=2.0,
        min_teachers=1, max_teachers=3, drain_deadline_s=15.0)
    actuator = TeacherPoolActuator(
        spawn, min_teachers=serve_cfg.min_teachers,
        max_teachers=serve_cfg.max_teachers,
        drain_deadline_s=serve_cfg.drain_deadline_s, service=service)
    controller = ScalerController(
        srv.store, [], ThroughputPolicy(),
        config=ScalerConfig(interval=0.5, min_tick_s=0.2,
                            staleness_s=5.0),
        services=[service], serving_policy=ServingPolicy(serve_cfg),
        serving_actuate=actuator.actuate, serving_config=serve_cfg,
        journal_path=journal_path, owner="serve-scaler-demo",
        scope="serve_demo")

    # open-loop-ish load generator: requests/sec follows the phase plan;
    # endpoints are re-read from the registry so a drained teacher stops
    # receiving traffic the moment it deregisters
    phase = {"rate": 20.0}
    stop = threading.Event()

    def load_loop() -> None:
        registry = ServiceRegistry(srv.store, root=DISTILL_ROOT)
        clients: dict[str, TeacherClient] = {}
        endpoints: list[str] = []
        rr, last_refresh = 0, 0.0
        feed = {"image": np.zeros((request_rows, 4), np.float32)}
        while not stop.is_set():
            now = time.monotonic()
            if now - last_refresh > 0.3 or not endpoints:
                endpoints = [m.server for m in
                             registry.get_service(service)]
                for ep in list(clients):
                    if ep not in endpoints:
                        clients.pop(ep).close()
                last_refresh = now
            if not endpoints:
                time.sleep(0.05)
                continue
            ep = endpoints[rr % len(endpoints)]
            rr += 1
            try:
                client = clients.get(ep)
                if client is None:
                    # lifecycle: long-lived(pooled per-endpoint client; closed on dict eviction above and drained at loop end)
                    client = TeacherClient(ep, timeout=30.0,
                                           max_inflight=64)
                    clients[ep] = client
                client.predict_async(feed)
            except Exception:  # noqa: BLE001 — teacher went away
                clients.pop(ep, None)
            time.sleep(1.0 / max(phase["rate"], 1e-6))
        for client in clients.values():
            client.close()

    load_thread = threading.Thread(target=load_loop, daemon=True,
                                   name="serve-demo-load")
    final_ok = False
    try:
        actuator.resize(1)   # the initial pool, before any decisions
        controller.start()
        load_thread.start()
        # phase 1 — cruise: ~160 rows/s against 500 rows/s capacity
        time.sleep(args.serve_phase_s)
        # phase 2 — 4x step: ~640 rows/s > one teacher's capacity; the
        # backlog drives p95 over the SLO and the pool must grow
        phase["rate"] = 80.0
        time.sleep(2.5 * args.serve_phase_s)
        # phase 3 — near-idle: the pool must DRAIN back down
        phase["rate"] = 4.0
        time.sleep(3.0 * args.serve_phase_s)
        # final check: SLO met at the end (use the live rollup)
        roll = controller._service_collector.service_rollup(service)
        final_ok = (roll["latency_ms_p95"] is None
                    or roll["latency_ms_p95"] <= serve_cfg.slo_p95_ms)
    finally:
        stop.set()
        load_thread.join(timeout=10)
        controller.stop()
        actuator.wait_drains(timeout=serve_cfg.drain_deadline_s + 5)
        actuator.close()
        srv.stop()

    entries = []
    try:
        with open(journal_path, encoding="utf-8") as f:
            entries = [json.loads(line) for line in f if line.strip()]
    except OSError:
        pass
    serving = [e for e in entries if e.get("kind") == "serving"]
    resizes = [e for e in serving if e["action"] == "resize"]
    grows = [e for e in resizes if e["desired"] > e["current"]]
    shrinks = [e for e in resizes if e["desired"] < e["current"]]
    # every actuated resize must be journaled: the actuator's log minus
    # the initial pre-controller resize(1) is exactly the journal's
    journaled = [e["applied"] for e in resizes]
    actuated = [r["to"] for r in actuator.resize_log[1:]]
    drained = [d for d in actuator.drain_log if d["drained"]]
    hard_killed = [d for d in actuator.drain_log if d["hard_killed"]]
    ok = (len(grows) >= 1 and len(shrinks) >= 1
          and journaled == actuated
          and len(drained) >= 1 and not hard_killed
          and final_ok)
    summary = {"ok": ok, "decisions": len(serving),
               "grows": len(grows), "shrinks": len(shrinks),
               "resizes": [{"tick": e["seq"], "from": e["current"],
                            "to": e["desired"], "reason": e["reason"]}
                           for e in resizes],
               "journal_matches_actuated": journaled == actuated,
               "drained": len(drained),
               "hard_killed": len(hard_killed),
               "drain_log": actuator.drain_log,
               "final_slo_met": final_ok,
               "journal": journal_path if args.journal else None}
    log.info("serve-scaler demo done: %s", summary)
    if not ok:
        log.error("serve-scaler audit failed: grows=%d shrinks=%d "
                  "journal_matches=%s drained=%d hard_killed=%d "
                  "final_slo_met=%s", len(grows), len(shrinks),
                  journaled == actuated, len(drained),
                  len(hard_killed), final_ok)
    print("serve_summary=" + json.dumps(summary), flush=True)
    if args.journal is None:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0 if ok else 1


def run_serve_load_demo(args) -> int:
    """Continuous batching + admission control end-to-end on this host
    (r23): real `TeacherServer`s with sleepy predict_fns standing in
    for chip time, probed by the open-loop generator
    (`edl_tpu.distill.loadgen`) — arrivals never wait on completions,
    so overload shows up as latency/shed instead of being absorbed by
    a self-throttling client.

    Two self-audited phases:

      A. **batching A/B** — one teacher per mode at the same offered
         rates (low and mid load, well under capacity): continuous
         must sustain the same throughput as the r6 window Batcher
         with at least 1.5x lower p95 (the window's coalesce delay is
         pure latency when the device is idle; continuous dispatches
         the moment the pipeline has room).

      B. **overload + chaos** — two continuous teachers with the
         overload-shed rule armed, offered 2x pool capacity on a
         high/normal/low mix, one teacher HARD-killed mid-phase (no
         deregistration, no drain — the loadgen's failover path).
         Degradation must be per class: the high class holds >= 90%
         SLO attainment and (almost) never sheds, shedding
         concentrates on low, and completions keep flowing after both
         the first shed and the kill (the graceful-recovery audit).

    Prints a machine-readable ``serve_load_summary=`` line and returns
    non-zero unless every gate holds.
    """
    import threading
    import time

    from edl_tpu.distill.admission import AdmissionConfig
    from edl_tpu.distill.loadgen import LoadStats, run_open_loop
    from edl_tpu.distill.teacher_server import TeacherServer

    phase_s = args.serve_phase_s
    failures: list[str] = []

    def gate(cond: bool, what: str) -> None:
        if not cond:
            failures.append(what)
        log.info("%s %s", "ok  " if cond else "FAIL", what)

    # -- phase A: window vs continuous at equal offered load ------------

    def sleepy(per_row_s: float, base_s: float):
        def predict(feeds):
            rows = next(iter(feeds.values())).shape[0]
            time.sleep(base_s + per_row_s * rows)
            return {"logits": np.zeros((rows, 4), np.float32)}
        return predict

    ab: dict[str, dict] = {}
    for mode in ("window", "continuous"):
        # fast fake chip (~0.3 ms/row): service time is small against
        # the 20 ms coalesce window, so the window's cost is visible
        server = TeacherServer(
            sleepy(0.0003, 0.001), port=0, host="127.0.0.1",
            max_batch=64, max_wait=0.02,
            admission=AdmissionConfig(batching=mode)).start()
        runs = {}
        try:
            for load, rps in (("low", 25.0), ("mid", 100.0)):
                stats = run_open_loop(
                    [f"127.0.0.1:{server.port}"], duration_s=phase_s,
                    rps=rps, rows=4, seed=11)
                runs[load] = stats.summary()
        finally:
            server.stop()
        ab[mode] = runs
    for load in ("low", "mid"):
        w, c = ab["window"][load], ab["continuous"][load]
        gate(w["error"] == 0 and c["error"] == 0
             and w["shed"] == 0 and c["shed"] == 0,
             f"A/{load}: clean run (no shed, no errors)")
        gate(abs(w["rps_sustained"] - c["rps_sustained"])
             <= 0.15 * max(w["rps_sustained"], c["rps_sustained"]),
             f"A/{load}: equal sustained throughput "
             f"(window {w['rps_sustained']} vs continuous "
             f"{c['rps_sustained']} rps)")
        gate(c["p95_ms"] * 1.5 <= w["p95_ms"],
             f"A/{load}: continuous p95 >=1.5x lower "
             f"({c['p95_ms']:.1f} vs {w['p95_ms']:.1f} ms)")

    # -- phase B: 2x overload + chaos teacher-kill ----------------------

    # slower chip (36 ms device batches): pool capacity ~2 * 222 rows/s
    # = ~55 rps of 8-row requests; offered 111 rps is a 2x overload.
    # SLO 500 ms ~= 3x the saturated pipeline latency: breached by
    # queue collapse, not by the kill transient's tail
    slo_ms = 500.0
    adm = AdmissionConfig(batching="continuous", shed_ms=150.0)
    servers = [TeacherServer(sleepy(0.004, 0.004), port=0,
                             host="127.0.0.1", max_batch=8,
                             admission=adm).start() for _ in range(2)]
    live = [f"127.0.0.1:{s.port}" for s in servers]
    by_ep = dict(zip(live, servers))
    killed: dict = {}
    kill_at = 1.5 * phase_s

    def chaos_kill(i: int, t: float) -> None:
        del i
        if t >= kill_at and not killed:
            ep = live.pop()
            killed["ep"], killed["t"] = ep, t
            # hard kill: stop() RSTs live connections; no drain, no
            # deregistration — the loadgen must fail over on its own
            threading.Thread(target=by_ep[ep].stop, daemon=True,
                             name="serve-load-chaos").start()

    stats = LoadStats()
    try:
        run_open_loop(lambda: list(live), duration_s=3.0 * phase_s,
                      rps=111.0, rows=8,
                      mix={"high": 0.1, "normal": 0.15, "low": 0.75},
                      seed=12, stats=stats, on_arrival=chaos_kill)
    finally:
        for server in servers:
            try:
                server.stop()
            except Exception:  # noqa: BLE001 — one already chaos-killed
                pass
    over = stats.summary(slo_ms=slo_ms)
    cls = over["by_class"]
    sheds = {c: v["shed"] for c, v in cls.items()}
    low_share = sheds.get("low", 0) / max(sum(sheds.values()), 1)
    first_shed = stats.first_event("shed")
    gate(killed and over["shed"] >= 1 and first_shed is not None,
         f"B: overload shed happened ({over['shed']} rejects)")
    gate(cls["high"]["attainment"] is not None
         and cls["high"]["attainment"] >= 0.9,
         f"B: high class holds >=90% SLO attainment "
         f"(got {cls['high']['attainment']})")
    gate(cls["high"]["shed_pct"] <= 5.0,
         f"B: high class (almost) never sheds "
         f"(got {cls['high']['shed_pct']}%)")
    gate(low_share >= 0.7 and cls["low"]["shed_pct"] >= 30.0,
         f"B: shedding concentrates on low (low share "
         f"{low_share:.2f}, low shed {cls['low']['shed_pct']}%)")
    gate(first_shed is not None and stats.ok_after(first_shed) > 0,
         "B: completions resume after the first shed")
    gate(bool(killed) and stats.ok_after(killed.get("t", 0.0)) > 0,
         "B: completions resume after the chaos kill (failover)")
    gate(over["error"] <= 0.05 * max(over["offered"], 1),
         f"B: errors bounded to the kill's in-flight "
         f"({over['error']}/{over['offered']})")

    ok = not failures
    summary = {"ok": ok, "failures": failures,
               "ab": {m: {load: {k: r[k] for k in
                                 ("rps_offered", "rps_sustained",
                                  "p50_ms", "p95_ms")}
                          for load, r in runs.items()}
                      for m, runs in ab.items()},
               "overload": {**{k: over[k] for k in
                               ("rps_offered", "rps_sustained",
                                "offered", "ok", "shed", "error")},
                            "slo_ms": slo_ms,
                            "low_share_of_shed": round(low_share, 3),
                            "by_class": cls}}
    if not ok:
        log.error("serve-load audit failed: %s", failures)
    print("serve_load_summary=" + json.dumps(summary), flush=True)
    return 0 if ok else 1


def run_p2p_demo(args) -> int:
    """Peer-to-peer state migration end-to-end on one host: in-process
    store + JobServer (store-attached, so /resize publishes migration
    epochs) + JobClient-spawned launcher pods running THIS trainer, with
    a scripted shrink and grow driven through /resize. Self-audits that
    the p2p plane actually carried the resizes:

      - at least one pod ADOPTED a resize in place (no respawn),
      - at least one pod restored FROM PEERS with bytes over the wire,
      - /resize published a migration epoch per applied resize,

    and exits 1 when any of it silently degraded to the disk recipe.
    Prints a machine-readable ``p2p_summary=`` line (bench.py reads
    ``elastic_downtime_p2p_s`` — the worst surviving-pod training gap —
    and ``resize_bytes_from_peers`` from it)."""
    import os
    import shutil
    import tempfile
    import threading
    import time

    from edl_tpu.collective import migration as mig
    from edl_tpu.collective import register as reg
    from edl_tpu.collective.barrier import read_cluster
    from edl_tpu.collective.job_server import (JobClient, JobServer,
                                               JobState, request_resize)
    from edl_tpu.coord.server import StoreServer

    # the pods are CPU trainers (the orchestration is the demo)
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["JAX_NUM_CPU_DEVICES"] = "1"
    # fast membership plumbing so the measured gaps are the migration
    # plane's, not the default 10s leases (children inherit these)
    os.environ.setdefault("EDL_TPU_BARRIER_STABLE", "0.5")
    os.environ.setdefault("EDL_TPU_LEASE_TTL", "3.0")
    os.environ["EDL_TPU_RESIZE_P2P"] = "1"

    job_id = "p2p_demo"
    lo, hi = (int(x) for x in args.nodes_range.split(":"))
    if hi < 2:
        hi = 2
    tmp = tempfile.mkdtemp(prefix="edl-p2p-demo-")
    srv = StoreServer(port=0, host="127.0.0.1", sweep_interval=0.2).start()
    store_ep = f"127.0.0.1:{srv.port}"
    state = JobState(job_id, lo, hi, desired=hi, store=srv.store)
    server = JobServer(state, port=0).start()
    # long enough that training spans both scripted resizes
    epochs = max(args.epochs, 30)
    steps = max(args.steps_per_epoch, 20)
    step_time = args.step_time or 0.06
    trainer_cmd = [
        sys.executable, "-m", "edl_tpu.collective.launch",
        "--store", store_ep, "--job-id", job_id,
        "--nodes-range", f"{lo}:{hi}",
        "--checkpoint-path", os.path.join(tmp, "ckpt"),
        "--log-dir", os.path.join(tmp, "log"), "--",
        sys.executable, "-m", "edl_tpu.examples.elastic_demo",
        "--epochs", str(epochs), "--steps-per-epoch", str(steps),
        "--batch", str(args.batch), "--step-time", str(step_time),
        "--ckpt-steps", str(args.ckpt_steps or 10)]
    client = JobClient(f"127.0.0.1:{server.port}", trainer_cmd, poll=0.5)
    client_thread = threading.Thread(target=client.run, daemon=True,
                                     name="p2p-demo-jobclient")

    acks: dict[tuple, dict] = {}   # (pod_id, ts) -> ack doc

    def sample_acks() -> None:
        records, _ = srv.store.get_prefix(mig.ack_prefix(job_id))
        for rec in records:
            try:
                doc = json.loads(rec.value)
                acks[(doc["pod_id"], doc["ts"])] = doc
            except (ValueError, KeyError):
                continue

    def wait_for(pred, timeout, what) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            sample_acks()
            if pred():
                return True
            time.sleep(0.25)
        log.error("p2p demo: timeout waiting for %s", what)
        return False

    def world() -> int:
        c = read_cluster(srv.store, job_id)
        return c.world_size if c is not None else 0

    phases_ok = True
    complete = False
    t_shrink = t_grow = None
    try:
        client_thread.start()
        # Phase 1: full world up, at least one donor advertising a
        # sealed snapshot (training + checkpointing live).
        phases_ok &= wait_for(
            lambda: world() == hi and mig.live_donors(srv.store, job_id),
            args.p2p_timeout, "world up with live donors")
        if phases_ok:
            # Phase 2: shrink. Survivors must ADOPT in place.
            t_shrink = time.time()
            request_resize(f"127.0.0.1:{server.port}", lo)
            phases_ok &= wait_for(
                lambda: world() == lo and any(
                    d["mode"] == "adopted" and d["ts"] > t_shrink
                    for d in acks.values()),
                args.p2p_timeout, "shrink adopted in place")
        if phases_ok:
            # Phase 3: grow. The new pod must restore FROM PEERS.
            time.sleep(2.0)  # let survivors seal fresh versions
            t_grow = time.time()
            request_resize(f"127.0.0.1:{server.port}", hi)
            phases_ok &= wait_for(
                lambda: world() == hi and any(
                    d["mode"] == "peers" and d["ts"] > t_grow
                    for d in acks.values()),
                args.p2p_timeout, "grow restored from peers")
        # Let the job finish (proves the migrated world still trains).
        if phases_ok:
            complete = wait_for(
                lambda: srv.store.get(reg.complete_key(job_id))
                is not None,
                args.p2p_timeout + epochs * steps * step_time,
                "job completion")
        sample_acks()
    finally:
        client.stop()
        client_thread.join(timeout=15)
        for p in client.procs:  # belt and braces: no orphan launchers
            if p.poll() is None:
                p.kill()
        server.stop()
        srv.stop()

    adoptions = [d for d in acks.values() if d["mode"] == "adopted"]
    peer_restores = [d for d in acks.values() if d["mode"] == "peers"]
    disk_restores = [d for d in acks.values() if d["mode"] == "disk"]
    bytes_from_peers = sum(d.get("bytes_from_peers") or 0
                           for d in peer_restores)
    gaps = [d["downtime_s"] for d in adoptions
            if d.get("downtime_s") is not None]
    ok = (phases_ok and complete and len(adoptions) >= 1
          and len(peer_restores) >= 1 and bytes_from_peers > 0
          and state._migration_epoch >= 2)
    summary = {
        "ok": ok, "complete": complete,
        "adoptions": len(adoptions),
        "peer_restores": len(peer_restores),
        "disk_restores": len(disk_restores),
        "resize_bytes_from_peers": bytes_from_peers,
        # worst surviving-pod training gap across the scripted resizes:
        # the p2p analogue of the kill->first-step stop-resume downtime
        "elastic_downtime_p2p_s": round(max(gaps), 4) if gaps else None,
        "adoption_gaps_s": [round(g, 4) for g in sorted(gaps)],
        "peer_restore_s": [d.get("restore_s") for d in peer_restores],
        "migration_epochs_published": state._migration_epoch,
        "served_resizes": state.resize_log}
    from edl_tpu.obs import trace as obs_trace
    if obs_trace.enabled():
        # the traced-resize acceptance surface: one causally-linked
        # trace per resize, phases summing against the measured
        # downtime — viewable via `python -m edl_tpu.obs trace <dir>`.
        # Only traces started by THIS run count (the sink dir persists
        # across runs by design).
        spans = obs_trace.load_spans(obs_trace.sink_dir())
        resizes = [r for r in obs_trace.resize_phase_summary(spans)
                   if t_shrink is None or r["t0"] >= t_shrink - 60.0]
        summary["trace_dir"] = obs_trace.sink_dir()
        summary["resize_traces"] = [
            {"trace_id": r["trace_id"], "spans": r["spans"],
             "phases": r["phases"], "downtime_s": r["downtime_s"]}
            for r in resizes]
    log.info("p2p demo done: %s", summary)
    if not ok:
        log.error("p2p audit failed: the resize path fell back to the "
                  "disk recipe (adoptions=%d peer_restores=%d bytes=%d "
                  "epochs=%d complete=%s)", len(adoptions),
                  len(peer_restores), bytes_from_peers,
                  state._migration_epoch, complete)
    print("p2p_summary=" + json.dumps(summary), flush=True)
    shutil.rmtree(tmp, ignore_errors=True)
    return 0 if ok else 1


def run_reform_demo(args) -> int:
    """Multi-host resize WITHOUT restart, end-to-end on one host: the
    reform-state-machine loop (collective/reform.py). Pods run with TWO
    virtual CPU devices and a local dp mesh sized by the elastic world
    (``--local-mesh-by-world``), so every resize is a true device-world
    change for every survivor: the surviving OS process quiesce-seals
    its live state, re-forms its mesh, restores reshaped state from
    peers over the tensor wire, re-jits (under the in-process jit cache
    + ``EDL_TPU_COMPILE_CACHE_DIR``), steps, and acks — generation-
    fenced. Scripted shrink + grow through /resize; self-audits:

      - at least TWO in-place reforms completed (result "in-place"
        with the full phase ladder in the adoption ack),
      - at least one pod rode BOTH resizes on the SAME pid — a
        multi-process resize with zero process restarts,
      - at least one reform restored its reshaped state FROM PEERS
        with bytes over the wire (disk is only the typed fallback),
      - the job still completes on the final world.

    Prints ``reform_summary=``: `elastic_downtime_multihost_s` is the
    best (compile-cache-warm) survivor gap — the steady-state cost of a
    device-world change; `_cold_s` is the worst (first sight of a new
    shape pays exactly one compile). bench.py and the resize_bench
    world axis read both.
    """
    import os
    import shutil
    import tempfile
    import threading
    import time

    from edl_tpu.collective import migration as mig
    from edl_tpu.collective import register as reg
    from edl_tpu.collective.barrier import read_cluster
    from edl_tpu.collective.job_server import (JobClient, JobServer,
                                               JobState, request_resize)
    from edl_tpu.coord.server import StoreServer

    # the pods are CPU trainers; TWO virtual devices each so the local
    # mesh can genuinely change size across resizes
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["JAX_NUM_CPU_DEVICES"] = "2"
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # jax < 0.5 reads the XLA flag, not JAX_NUM_CPU_DEVICES
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2").strip()
    os.environ.setdefault("EDL_TPU_BARRIER_STABLE", "0.5")
    os.environ.setdefault("EDL_TPU_LEASE_TTL", "3.0")
    os.environ["EDL_TPU_RESIZE_P2P"] = "1"
    # reforms pay seal + restore + re-jit: give the launcher's adoption
    # fence room beyond the default 10s on a busy 1-core host
    os.environ.setdefault("EDL_TPU_ADOPT_TIMEOUT", "30")

    job_id = "reform_demo"
    lo, hi = (int(x) for x in args.nodes_range.split(":"))
    if hi < 2:
        hi = 2
    tmp = tempfile.mkdtemp(prefix="edl-reform-demo-")
    # persistent XLA cache: a respawned pod (and any repeat shape)
    # skips its re-jits — the knob the re-jit phase is built around
    os.environ.setdefault("EDL_TPU_COMPILE_CACHE_DIR",
                          os.path.join(tmp, "xla-cache"))
    srv = StoreServer(port=0, host="127.0.0.1", sweep_interval=0.2).start()
    store_ep = f"127.0.0.1:{srv.port}"
    state = JobState(job_id, lo, hi, desired=hi, store=srv.store)
    server = JobServer(state, port=0).start()
    epochs = max(args.epochs, 30)
    steps = max(args.steps_per_epoch, 20)
    step_time = args.step_time or 0.05
    trainer_cmd = [
        sys.executable, "-m", "edl_tpu.collective.launch",
        "--store", store_ep, "--job-id", job_id,
        "--nodes-range", f"{lo}:{hi}",
        "--checkpoint-path", os.path.join(tmp, "ckpt"),
        "--log-dir", os.path.join(tmp, "log"), "--",
        sys.executable, "-m", "edl_tpu.examples.elastic_demo",
        "--epochs", str(epochs), "--steps-per-epoch", str(steps),
        "--batch", str(args.batch), "--step-time", str(step_time),
        "--local-mesh-by-world",
        "--ckpt-steps", str(args.ckpt_steps or 10)]
    client = JobClient(f"127.0.0.1:{server.port}", trainer_cmd, poll=0.5)
    client_thread = threading.Thread(target=client.run, daemon=True,
                                     name="reform-demo-jobclient")

    acks: dict[tuple, dict] = {}   # (pod_id, ts) -> ack doc

    def sample_acks() -> None:
        records, _ = srv.store.get_prefix(mig.ack_prefix(job_id))
        for rec in records:
            try:
                doc = json.loads(rec.value)
                acks[(doc["pod_id"], doc["ts"])] = doc
            except (ValueError, KeyError):
                continue

    def wait_for(pred, timeout, what) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            sample_acks()
            if pred():
                return True
            time.sleep(0.25)
        log.error("reform demo: timeout waiting for %s", what)
        return False

    def world() -> int:
        c = read_cluster(srv.store, job_id)
        return c.world_size if c is not None else 0

    def reform_acks(after: float) -> list[dict]:
        return [d for d in acks.values()
                if d["mode"] == "adopted" and d["ts"] > after
                and (d.get("reform") or {}).get("result") == "in-place"]

    phases_ok = True
    complete = False
    t_shrink = t_grow = None
    try:
        client_thread.start()
        phases_ok &= wait_for(
            lambda: world() == hi and mig.live_donors(srv.store, job_id),
            args.p2p_timeout, "world up with live donors")
        if phases_ok:
            # shrink: every survivor's local mesh GROWS (world hi -> lo
            # frees devices per pod) — a device-world change they must
            # reform through in place
            t_shrink = time.time()
            request_resize(f"127.0.0.1:{server.port}", lo)
            phases_ok &= wait_for(
                lambda: world() == lo and reform_acks(t_shrink),
                args.p2p_timeout, "shrink reformed in place")
        if phases_ok:
            time.sleep(1.5)  # survivors seal fresh versions
            # grow: survivors reform BACK to an already-seen shape (the
            # compile-cache-hot path) while the new pod restores from
            # peers through a full respawn
            t_grow = time.time()
            request_resize(f"127.0.0.1:{server.port}", hi)
            phases_ok &= wait_for(
                lambda: world() == hi and reform_acks(t_grow) and any(
                    d["mode"] == "peers" and d["ts"] > t_grow
                    for d in acks.values()),
                args.p2p_timeout, "grow reformed + peer-restored")
        if phases_ok:
            complete = wait_for(
                lambda: srv.store.get(reg.complete_key(job_id))
                is not None,
                args.p2p_timeout + epochs * steps * step_time,
                "job completion")
        sample_acks()
    finally:
        client.stop()
        client_thread.join(timeout=15)
        for p in client.procs:  # belt and braces: no orphan launchers
            if p.poll() is None:
                p.kill()
        server.stop()
        srv.stop()

    reforms = [d for d in acks.values()
               if d["mode"] == "adopted"
               and (d.get("reform") or {}).get("result") == "in-place"]
    peer_reforms = [d for d in reforms
                    if d["reform"].get("restore") == "peers"]
    disk_reforms = [d for d in reforms
                    if d["reform"].get("restore") == "disk"]
    respawn_restores = [d for d in acks.values() if d["mode"] == "peers"]
    # zero-restart proof: one pod rode >=2 generations on ONE pid while
    # the world was multi-process
    by_pod: dict[str, set] = {}
    for d in reforms:
        by_pod.setdefault(d["pod_id"], set()).add(
            (d.get("pid"), d.get("generation")))
    survivors = [pod for pod, gens in by_pod.items()
                 if len({g for _, g in gens}) >= 2
                 and len({p for p, _ in gens}) == 1]
    bytes_from_peers = sum(d.get("bytes_from_peers") or 0
                           for d in reforms + respawn_restores)
    gaps = sorted(d["downtime_s"] for d in reforms
                  if d.get("downtime_s") is not None)
    # respawned-pod gap: the stop-resume price a NON-surviving process
    # pays on the same resize (resize_bench's world-axis column)
    respawn_gaps = sorted(d["ts"] - t_grow for d in respawn_restores
                          if t_grow is not None and d["ts"] > t_grow)
    ok = (phases_ok and complete and len(reforms) >= 2
          and len(survivors) >= 1 and len(peer_reforms) >= 1
          and bytes_from_peers > 0)
    last_reform = max(reforms, key=lambda d: d["ts"])["reform"] \
        if reforms else None
    summary = {
        "ok": ok, "complete": complete,
        "reforms_in_place": len(reforms),
        "reform_restores_peers": len(peer_reforms),
        "reform_restores_disk": len(disk_reforms),
        "respawn_peer_restores": len(respawn_restores),
        "zero_restart_survivors": survivors,
        "resize_bytes_from_peers": bytes_from_peers,
        # best gap = compile-cache-warm reform (the steady state);
        # worst = first sight of a new shape (exactly one compile)
        "elastic_downtime_multihost_s": round(gaps[0], 4) if gaps
        else None,
        "elastic_downtime_multihost_cold_s": round(gaps[-1], 4) if gaps
        else None,
        "reform_gaps_s": [round(g, 4) for g in gaps],
        "respawn_downtime_s": round(respawn_gaps[0], 4)
        if respawn_gaps else None,
        "last_reform": last_reform,
        "migration_epochs_published": state._migration_epoch,
        "served_resizes": state.resize_log}
    log.info("reform demo done: %s", summary)
    if not ok:
        log.error("reform audit failed: reforms=%d survivors=%s "
                  "peer_reforms=%d bytes=%d complete=%s", len(reforms),
                  survivors, len(peer_reforms), bytes_from_peers,
                  complete)
    print("reform_summary=" + json.dumps(summary), flush=True)
    shutil.rmtree(tmp, ignore_errors=True)
    return 0 if ok else 1


def run_spot_demo(args) -> int:
    """Spot-capacity riding end-to-end on one host: the live elastic
    world (store + JobServer + launcher pods running THIS trainer)
    receives a spot preemption NOTICE and must ride it as a SCHEDULED
    quiesce-seal-donate shrink inside the notice window — never a
    surprise kill, never lost progress. The window comes from
    ``EDL_TPU_SPOT_NOTICE_S`` (a live CPU-jax world needs a generous
    one; a real fleet gets 30-120s from its provider).

    The script: bring the full world up with live donors (sealed
    snapshots advertised), stamp the notice deadline, then issue the
    scheduled shrink through /resize — exactly what the fleet
    scheduler's preemptive policy does when a notice lands
    (scaler/fleet_policy.py). Self-audits, exit 1 on any miss:

      - the shrink COMPLETED before the deadline (world at the target
        and a survivor's in-place adoption acked) — the notice was
        ridden, so the provider's reclaim at the deadline finds the
        capacity already donated and has nothing to kill;
      - zero lost progress: the survivor adopted IN PLACE (same
        process, in-memory state carried — mode "adopted", no respawn)
        and nothing fell back to the disk recipe after the notice;
      - the job still completes on the shrunk world.

    Prints ``spot_summary=`` with the ride margin (deadline minus
    completion) — the live counterpart of the fleet simulator's
    ``notices_ridden`` column and the chaos soak's I7 invariant.
    """
    import os
    import shutil
    import tempfile
    import threading
    import time

    from edl_tpu.collective import migration as mig
    from edl_tpu.collective import register as reg
    from edl_tpu.collective.barrier import read_cluster
    from edl_tpu.collective.job_server import (JobClient, JobServer,
                                               JobState, request_resize)
    from edl_tpu.coord.server import StoreServer
    from edl_tpu.utils.config import env_float

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["JAX_NUM_CPU_DEVICES"] = "1"
    os.environ.setdefault("EDL_TPU_BARRIER_STABLE", "0.5")
    os.environ.setdefault("EDL_TPU_LEASE_TTL", "3.0")
    os.environ["EDL_TPU_RESIZE_P2P"] = "1"

    notice_s = env_float("EDL_TPU_SPOT_NOTICE_S", 60.0)
    if notice_s <= 0:
        log.error("spot demo: EDL_TPU_SPOT_NOTICE_S=0 means notices "
                  "are ignored — nothing to demonstrate")
        return 1
    job_id = "spot_demo"
    lo, hi = (int(x) for x in args.nodes_range.split(":"))
    if hi < 2:
        hi = 2
    tmp = tempfile.mkdtemp(prefix="edl-spot-demo-")
    srv = StoreServer(port=0, host="127.0.0.1", sweep_interval=0.2).start()
    store_ep = f"127.0.0.1:{srv.port}"
    state = JobState(job_id, lo, hi, desired=hi, store=srv.store)
    server = JobServer(state, port=0).start()
    epochs = max(args.epochs, 30)
    steps = max(args.steps_per_epoch, 20)
    step_time = args.step_time or 0.06
    trainer_cmd = [
        sys.executable, "-m", "edl_tpu.collective.launch",
        "--store", store_ep, "--job-id", job_id,
        "--nodes-range", f"{lo}:{hi}",
        "--checkpoint-path", os.path.join(tmp, "ckpt"),
        "--log-dir", os.path.join(tmp, "log"), "--",
        sys.executable, "-m", "edl_tpu.examples.elastic_demo",
        "--epochs", str(epochs), "--steps-per-epoch", str(steps),
        "--batch", str(args.batch), "--step-time", str(step_time),
        "--ckpt-steps", str(args.ckpt_steps or 10)]
    client = JobClient(f"127.0.0.1:{server.port}", trainer_cmd, poll=0.5)
    client_thread = threading.Thread(target=client.run, daemon=True,
                                     name="spot-demo-jobclient")

    acks: dict[tuple, dict] = {}   # (pod_id, ts) -> ack doc

    def sample_acks() -> None:
        records, _ = srv.store.get_prefix(mig.ack_prefix(job_id))
        for rec in records:
            try:
                doc = json.loads(rec.value)
                acks[(doc["pod_id"], doc["ts"])] = doc
            except (ValueError, KeyError):
                continue

    def wait_for(pred, timeout, what) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            sample_acks()
            if pred():
                return True
            time.sleep(0.25)
        log.error("spot demo: timeout waiting for %s", what)
        return False

    def world() -> int:
        c = read_cluster(srv.store, job_id)
        return c.world_size if c is not None else 0

    phases_ok = True
    complete = False
    t_notice = deadline = t_rode = None
    try:
        client_thread.start()
        # Phase 1: full world with sealed snapshots advertised — the
        # precondition for donating capacity without losing anything.
        phases_ok &= wait_for(
            lambda: world() == hi and mig.live_donors(srv.store, job_id),
            args.p2p_timeout, "world up with live donors")
        if phases_ok:
            # Phase 2: the NOTICE. From here the world has notice_s
            # seconds to quiesce-seal-donate down to the post-reclaim
            # capacity; the scheduled shrink through /resize IS the
            # riding maneuver (what PreemptiveFairSharePolicy issues
            # when a notice lands in the fleet).
            t_notice = time.time()
            deadline = t_notice + notice_s
            log.info("spot notice: %d node(s) reclaimed in %.0fs — "
                     "scheduled shrink %d -> %d", hi - lo, notice_s,
                     hi, lo)
            request_resize(f"127.0.0.1:{server.port}", lo)

            def rode() -> bool:
                return world() == lo and any(
                    d["mode"] == "adopted" and d["ts"] > t_notice
                    for d in acks.values())

            phases_ok &= wait_for(rode, notice_s,
                                  "sealed shrink inside the notice "
                                  "window")
            t_rode = time.time()
            if phases_ok and t_rode > deadline:
                phases_ok = False
                log.error("spot demo: shrink finished %.1fs AFTER the "
                          "deadline — the provider's reclaim would "
                          "have hard-killed live pods",
                          t_rode - deadline)
        if phases_ok:
            complete = wait_for(
                lambda: srv.store.get(reg.complete_key(job_id))
                is not None,
                args.p2p_timeout + epochs * steps * step_time,
                "job completion on the shrunk world")
        sample_acks()
    finally:
        client.stop()
        client_thread.join(timeout=15)
        for p in client.procs:  # belt and braces: no orphan launchers
            if p.poll() is None:
                p.kill()
        server.stop()
        srv.stop()

    adoptions = [d for d in acks.values() if d["mode"] == "adopted"
                 and t_notice is not None and d["ts"] > t_notice]
    disk_restores = [d for d in acks.values() if d["mode"] == "disk"
                     and t_notice is not None and d["ts"] > t_notice]
    gaps = [d["downtime_s"] for d in adoptions
            if d.get("downtime_s") is not None]
    rode_notice = (phases_ok and t_rode is not None
                   and deadline is not None and t_rode <= deadline)
    # zero lost progress = the survivors carried their in-memory state
    # (in-place adoption, no respawn) and nothing degraded to the disk
    # recipe after the notice; completion proves the world still trains
    ok = (rode_notice and complete and len(adoptions) >= 1
          and not disk_restores)
    summary = {
        "ok": ok, "complete": complete,
        "rode_notice": rode_notice,
        "notice_window_s": notice_s,
        "ride_margin_s": round(deadline - t_rode, 3)
        if rode_notice else None,
        "adoptions_after_notice": len(adoptions),
        "disk_restores_after_notice": len(disk_restores),
        "spot_downtime_s": round(max(gaps), 4) if gaps else None,
        "served_resizes": state.resize_log}
    log.info("spot demo done: %s", summary)
    if not ok:
        log.error("spot audit failed: rode=%s adoptions=%d disk=%d "
                  "complete=%s", rode_notice, len(adoptions),
                  len(disk_restores), complete)
    print("spot_summary=" + json.dumps(summary), flush=True)
    shutil.rmtree(tmp, ignore_errors=True)
    return 0 if ok else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=5)
    parser.add_argument("--steps-per-epoch", type=int, default=20)
    parser.add_argument("--batch", type=int, default=32)
    parser.add_argument("--step-time", type=float, default=0.0,
                        help="artificial per-step delay (resize-window test)")
    parser.add_argument("--ckpt-steps", type=int, default=None,
                        help="also checkpoint every N steps (default "
                             "$EDL_TPU_CKPT_STEPS, else epoch-end only)")
    parser.add_argument("--ckpt-sync", action="store_true",
                        help="synchronous saves (default async "
                             "snapshot-then-write)")
    # controller-driven elasticity (see module docstring)
    parser.add_argument("--scaler", action="store_true",
                        help="run the closed loop: store + JobServer + "
                             "launcher pods + utilization-driven scaler")
    parser.add_argument("--nodes-range", default="1:2",
                        help="--scaler: min:max pods on this host")
    parser.add_argument("--scaler-interval", type=float, default=1.0)
    parser.add_argument("--scaler-cooldown", type=float, default=8.0)
    parser.add_argument("--scaler-downtime", type=float, default=1.5,
                        help="measured elastic_downtime_s to amortize")
    parser.add_argument("--scaler-timeout", type=float, default=300.0)
    parser.add_argument("--journal", default=None,
                        help="--scaler: keep the decision journal here")
    # serving elasticity demo (see run_serve_scaler_demo)
    parser.add_argument("--serve-scaler", action="store_true",
                        help="run the serving loop: store + teacher "
                             "pool + load generator + SLO-driven "
                             "scaler, self-audited grow + drained "
                             "shrink")
    parser.add_argument("--serve-phase-s", type=float, default=5.0,
                        help="--serve-scaler/--serve-load: base "
                             "load-phase seconds")
    # continuous-batching + admission-control dryrun (run_serve_load_demo)
    parser.add_argument("--serve-load", action="store_true",
                        help="run the serving load dryrun: open-loop "
                             "generator vs window/continuous batching "
                             "A/B, then 2x overload + chaos teacher "
                             "kill with per-class shed/attainment "
                             "audits")
    # peer-to-peer migration demo (see run_p2p_demo)
    parser.add_argument("--resize-p2p", action="store_true",
                        help="run the live-migration loop: store + "
                             "JobServer + pods, scripted shrink/grow, "
                             "self-audited p2p adoption + peer restore")
    parser.add_argument("--p2p-timeout", type=float, default=120.0,
                        help="--resize-p2p: per-phase timeout seconds")
    # reform state-machine demo (see run_reform_demo)
    parser.add_argument("--resize-reform", action="store_true",
                        help="run the multi-host-resize-without-restart "
                             "loop: 2-device pods whose local mesh is "
                             "sized by the elastic world, scripted "
                             "shrink/grow, self-audited in-place "
                             "reforms with zero process restarts")
    # spot-capacity riding demo (see run_spot_demo)
    parser.add_argument("--spot", action="store_true",
                        help="run the spot-riding loop: live world + "
                             "preemption notice ridden as a scheduled "
                             "quiesce-seal-donate shrink inside "
                             "$EDL_TPU_SPOT_NOTICE_S; exit 1 unless "
                             "it lands before the deadline with zero "
                             "lost progress")
    parser.add_argument("--local-mesh-by-world", action="store_true",
                        help="trainer mode for --resize-reform: local "
                             "dp mesh sized by the elastic world, "
                             "reform state machine wired (per-pod ckpt "
                             "subdirs)")
    args = parser.parse_args(argv)
    if sum((args.scaler, args.resize_p2p, args.serve_scaler,
            args.serve_load, args.resize_reform, args.spot)) > 1:
        parser.error("--scaler, --serve-scaler, --serve-load, "
                     "--resize-p2p, --resize-reform and --spot are "
                     "separate demos")
    if args.spot:
        return run_spot_demo(args)
    if args.serve_load:
        return run_serve_load_demo(args)
    if args.serve_scaler:
        return run_serve_scaler_demo(args)
    if args.resize_p2p:
        return run_p2p_demo(args)
    if args.resize_reform:
        return run_reform_demo(args)
    if args.scaler:
        return run_scaler_demo(args)

    env = TrainerEnv.from_environ()
    log.info("trainer up: rank=%d world=%d cluster_v=%d", env.rank,
             env.world_size, env.cluster_version)

    model = LinearRegression(features=1)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 1)))["params"]
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=optax.sgd(0.05))

    # --local-mesh-by-world: the reform-state-machine trainer shape.
    # The local dp mesh is a FUNCTION of the elastic world (world 1 ->
    # all local devices, world w -> ndev // w), so a resize is a true
    # device-world change for every survivor: the reform_mesh hook
    # returns the new mesh and the TrainLoop walks quiesce ->
    # mesh-reform -> peer-restore -> re-jit in place (no respawn).
    # Each pod checkpoints under its own subdir — per-pod version
    # counters are per-lineage, and the reform restore is self-scoped.
    reform_kwargs: dict = {}
    if args.local_mesh_by_world:
        from jax.sharding import Mesh
        from edl_tpu.parallel import mesh as mesh_lib

        def _mesh_for(world: int) -> "Mesh":
            devices = jax.devices()
            n = len(devices) if world <= 1 \
                else max(1, len(devices) // world)
            return Mesh(np.array(devices[:n]), ("dp",))

        mesh_holder = {"mesh": _mesh_for(env.world_size)}

        def reform_mesh(rank, world, cluster):
            new = _mesh_for(world)
            if new.devices.size == mesh_holder["mesh"].devices.size:
                return None  # device world unchanged: fast adoption
            mesh_holder["mesh"] = new
            return new

        # place the INITIAL state exactly the way a reform re-places it
        # (replicated NamedSharding on the live mesh): the jit cache
        # then keys identically when a later reform revisits this
        # shape — the compile-cache-hit path the re-jit phase banks on
        state = mesh_lib.replicate_host_tree(mesh_holder["mesh"], state)
        reform_kwargs = {
            "mesh": mesh_holder["mesh"], "batch_axes": ("dp",),
            "place_state": lambda t: mesh_lib.replicate_host_tree(
                mesh_holder["mesh"], t),
            "reform_mesh": reform_mesh}

    def loss_fn(state, params, batch):
        pred = state.apply_fn({"params": params}, batch["x"])
        return mse_loss(pred, batch["y"]), {}

    step = make_train_step(loss_fn, donate=False)
    if args.step_time > 0:
        import time
        raw_step = step

        def step(s, b):  # noqa: F811 — wrapped for the resize-window test
            time.sleep(args.step_time)
            return raw_step(s, b)

    ckpt_kw = {}
    if args.ckpt_steps is not None:
        ckpt_kw["ckpt_every_steps"] = args.ckpt_steps
    if args.ckpt_sync:
        ckpt_kw["ckpt_async"] = False

    def on_reform(rank, world, cluster):
        # Live migration: a resize that keeps this pod re-enters the
        # epoch in place — re-derive the data shard for the new world
        # (make_data reads env at each data_fn call).
        env.rank, env.world_size = rank, world
        env.cluster_version = cluster.version

    ckpt_dir = env.checkpoint_path or None
    if ckpt_dir and args.local_mesh_by_world and env.pod_id:
        import os
        ckpt_dir = os.path.join(ckpt_dir, env.pod_id)
    loop = TrainLoop(step, state, config=from_env(
        LoopConfig, num_epochs=args.epochs,
        ckpt_dir=ckpt_dir,
        log_every_steps=args.steps_per_epoch, **ckpt_kw),
        on_reform=on_reform, **reform_kwargs)
    status = loop.run(lambda epoch: make_data(
        epoch, env.rank, env.world_size, args.steps_per_epoch, args.batch))

    w = float(np.asarray(loop.state.params["Dense_0"]["kernel"])[0, 0])
    b = float(np.asarray(loop.state.params["Dense_0"]["bias"])[0])
    log.info("done: epoch=%d step=%d w=%.3f b=%.3f", status.epoch,
             status.step, w, b)
    # machine-readable for the elastic-downtime bench (bench.py). A
    # graceful SIGTERM stop never reaches here: loop.run raises
    # SystemExit(143) after its donor linger (the launcher must not
    # read a stopped trainer as "training complete").
    print("ckpt_stats=" + json.dumps(loop.ckpt_stats()), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
