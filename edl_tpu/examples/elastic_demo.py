"""Elastic trainer demo — the end-to-end probe for the launcher.

Capability of the reference's `edl_demo.py` + fit_a_line fault-tolerant job
(example/demo/collective/ + example/fit_a_line/train_ft.py): a tiny linear
regression that reads the launcher's TrainerEnv, trains its data shard with
checkpoint/resume, and survives stop-resume resizes. Runs on CPU; with a
multi-pod world it shards data by rank (orchestration-level elasticity —
the same TrainLoop drives pjit models on real TPU meshes).

  python -m edl_tpu.examples.elastic_demo --epochs 5 --steps-per-epoch 20
"""

from __future__ import annotations

import argparse
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.collective.job_env import TrainerEnv
from edl_tpu.models.linear import LinearRegression, mse_loss
from edl_tpu.train.loop import LoopConfig, TrainLoop
from edl_tpu.train.state import TrainState
from edl_tpu.train.step import make_train_step
from edl_tpu.utils.config import from_env
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.examples.elastic_demo")

TRUE_W, TRUE_B = 3.0, -1.5


def make_data(epoch: int, rank: int, world: int, steps: int, batch: int):
    """Seed-per-pass + shard-by-rank (reference pass_id_as_seed recipe)."""
    rng = np.random.default_rng(1000 + epoch)
    n = steps * batch * max(1, world)
    x = rng.normal(size=(n, 1)).astype(np.float32)
    y = TRUE_W * x + TRUE_B + 0.01 * rng.normal(size=(n, 1)).astype(
        np.float32)
    shard = slice(rank * steps * batch, (rank + 1) * steps * batch)
    xs, ys = x[shard], y[shard]
    for i in range(steps):
        s = slice(i * batch, (i + 1) * batch)
        yield {"x": xs[s], "y": ys[s]}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=5)
    parser.add_argument("--steps-per-epoch", type=int, default=20)
    parser.add_argument("--batch", type=int, default=32)
    parser.add_argument("--step-time", type=float, default=0.0,
                        help="artificial per-step delay (resize-window test)")
    parser.add_argument("--ckpt-steps", type=int, default=None,
                        help="also checkpoint every N steps (default "
                             "$EDL_TPU_CKPT_STEPS, else epoch-end only)")
    parser.add_argument("--ckpt-sync", action="store_true",
                        help="synchronous saves (default async "
                             "snapshot-then-write)")
    args = parser.parse_args(argv)

    env = TrainerEnv.from_environ()
    log.info("trainer up: rank=%d world=%d cluster_v=%d", env.rank,
             env.world_size, env.cluster_version)

    model = LinearRegression(features=1)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 1)))["params"]
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=optax.sgd(0.05))

    def loss_fn(state, params, batch):
        pred = state.apply_fn({"params": params}, batch["x"])
        return mse_loss(pred, batch["y"]), {}

    step = make_train_step(loss_fn, donate=False)
    if args.step_time > 0:
        import time
        raw_step = step

        def step(s, b):  # noqa: F811 — wrapped for the resize-window test
            time.sleep(args.step_time)
            return raw_step(s, b)

    ckpt_kw = {}
    if args.ckpt_steps is not None:
        ckpt_kw["ckpt_every_steps"] = args.ckpt_steps
    if args.ckpt_sync:
        ckpt_kw["ckpt_async"] = False
    loop = TrainLoop(step, state, config=from_env(
        LoopConfig, num_epochs=args.epochs,
        ckpt_dir=env.checkpoint_path or None,
        log_every_steps=args.steps_per_epoch, **ckpt_kw))
    status = loop.run(lambda epoch: make_data(
        epoch, env.rank, env.world_size, args.steps_per_epoch, args.batch))

    w = float(np.asarray(loop.state.params["Dense_0"]["kernel"])[0, 0])
    b = float(np.asarray(loop.state.params["Dense_0"]["bias"])[0])
    log.info("done: epoch=%d step=%d w=%.3f b=%.3f", status.epoch,
             status.step, w, b)
    # machine-readable for the elastic-downtime bench (bench.py)
    print("ckpt_stats=" + json.dumps(loop.ckpt_stats()), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
