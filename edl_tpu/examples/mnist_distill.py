"""MNIST-scale distillation demo: teacher serving + DistillReader student.

Capability of the reference's minimal distill recipe
(example/distill/mnist_distill/train_with_fleet.py:134-145 and
example/distill/README.md:11-31): a student trains against teacher logits
pulled over the network from an elastic teacher pool.

Modes:
  --all-in-one          spin an in-process teacher (MLP, fixed seed) and
                        train against it — zero external services;
  --teachers h:p,h:p    fixed teacher endpoints (teacher_server CLI);
  --discovery h:p --service svc
                        dynamic discovery via the balancer daemon.

Data is synthetic (deterministic), sized like MNIST; no downloads.
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.data.pipeline import ArraySource, DataLoader
from edl_tpu.distill.reader import DistillReader
from edl_tpu.distill.teacher_server import TeacherServer
from edl_tpu.models.mlp import MLP
from edl_tpu.train.classification import (create_state, make_distill_step,
                                          make_eval_step)
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.examples.mnist_distill")

IMG_SHAPE = (28, 28, 1)
NUM_CLASSES = 10


def synthetic_mnist(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    images = rng.normal(size=(n,) + IMG_SHAPE).astype(np.float32)
    # Labels come from a fixed random projection so they are learnable.
    w = np.random.default_rng(123).normal(
        size=(int(np.prod(IMG_SHAPE)), NUM_CLASSES)).astype(np.float32)
    labels = (images.reshape(n, -1) @ w).argmax(axis=1).astype(np.int32)
    return {"image": images, "label": labels}


def make_teacher_predict(seed: int = 42):
    """Jitted forward of a fixed-weight teacher MLP."""
    model = MLP(num_classes=NUM_CLASSES, hidden=(512, 256))
    variables = jax.jit(model.init)(jax.random.PRNGKey(seed),
                                    jnp.zeros((1,) + IMG_SHAPE))

    @jax.jit
    def forward(images):
        return model.apply(variables, images, train=False)

    def predict(feeds):
        return {"teacher_logits":
                np.asarray(forward(jnp.asarray(feeds["image"])), np.float32)}

    return predict


def train(args) -> int:
    data = synthetic_mnist(args.samples, seed=args.seed)
    loader = DataLoader(ArraySource(data), args.batch_size, seed=args.seed)

    server = None
    teachers = None
    if args.all_in_one:
        server = TeacherServer(make_teacher_predict(), host="127.0.0.1",
                               max_batch=args.teacher_batch_size * 4).start()
        teachers = [f"127.0.0.1:{server.port}"]
    elif args.teachers:
        teachers = args.teachers.split(",")

    student = MLP(num_classes=NUM_CLASSES, hidden=(64,))
    tx = optax.adam(args.lr)
    state = create_state(student, jax.random.PRNGKey(args.seed),
                         (1,) + IMG_SHAPE, tx)
    step = make_distill_step(NUM_CLASSES, temperature=args.temperature,
                             hard_weight=args.hard_weight)
    eval_step = make_eval_step()

    try:
        for epoch in range(args.epochs):
            dr = DistillReader(
                lambda e=epoch: loader.epoch(e), feeds=["image"],
                predicts=["teacher_logits"], teachers=teachers,
                discovery=args.discovery or None, service=args.service,
                teacher_batch_size=args.teacher_batch_size)
            losses = []
            for batch in dr():
                state, metrics = step(state, batch)
                losses.append(float(metrics["loss"]))
            dr.close()
            ev = eval_step(state, {"image": jnp.asarray(data["image"][:512]),
                                   "label": jnp.asarray(data["label"][:512])})
            log.info("epoch %d loss=%.4f acc1=%.3f", epoch,
                     float(np.mean(losses)), float(ev["acc1"]))
        print(f"final_loss={np.mean(losses):.4f}")
        return 0
    finally:
        if server is not None:
            server.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="edl_tpu.examples.mnist_distill")
    parser.add_argument("--all-in-one", action="store_true")
    parser.add_argument("--teachers", default="")
    parser.add_argument("--discovery", default="")
    parser.add_argument("--service", default="mnist_teacher")
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--samples", type=int, default=2048)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--teacher-batch-size", type=int, default=16)
    parser.add_argument("--temperature", type=float, default=2.0)
    parser.add_argument("--hard-weight", type=float, default=0.3)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    if not (args.all_in_one or args.teachers or args.discovery):
        parser.error("pick --all-in-one, --teachers or --discovery")
    return train(args)


if __name__ == "__main__":
    sys.exit(main())
