"""Flagship classification trainer: ResNet50_vd over file-backed data.

Capability of the reference's 690-line flagship trainer
(example/collective/resnet50/train_with_fleet.py:347-658): full LR recipe
menu (piecewise/cosine + linear warmup, world-scaled), label smoothing,
mixup, weight decay, file-backed sharded input with per-epoch shuffle
(reader_cv2 pass_id_as_seed), per-epoch top-1/top-5 eval, rank-0
checkpoint per epoch, throughput logging, and benchmark-result JSON
(:642-658) — re-designed tpu-first:

- one process per TPU host; `init_from_env()` joins the launcher's world
  and a dp mesh spans every chip (fleet.init + NCCL's role);
- the jitted train step carries the gradient all-reduce (no allreduce
  calls to place); batches stream through host prefetch + device
  placement (`prefetch_to_device`, the DALI double-buffer role);
- bf16 compute via the model's dtype, fp32 params/optimizer;
- elastic: run under `edl_tpu.collective.launch` and resizes restart the
  process, which re-forms the mesh and resumes from the checkpoint
  (+ optional gs:// mirror for pods on fresh nodes).

Data: a directory of .npz shards (image (N,H,W,3) float32, label (N,)
int) — `--make-synthetic` generates a deterministic learnable stand-in
(no downloads in CI). Real ImageNet = convert your records to such
shards; the loader is format-, not dataset-, specific.

  python -m edl_tpu.examples.imagenet_train --make-synthetic 8 \\
      --data-dir /tmp/imgnet --model ResNetTiny --image-size 32 \\
      --epochs 2 --batch-size 256
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.data.pipeline import (DataLoader, FileSource,
                                   prefetch_to_device, random_crop,
                                   random_flip_lr)
from edl_tpu.parallel import distributed, mesh as mesh_lib
from edl_tpu.utils import config
from edl_tpu.train import lr as lr_lib
from edl_tpu.train.benchlog import BenchmarkLog
from edl_tpu.train.classification import (create_state,
                                          make_classification_step,
                                          make_eval_step)
from edl_tpu.train.loop import LoopConfig, TrainLoop
from edl_tpu.utils.config import from_env
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.examples.imagenet_train")


def make_synthetic_shards(data_dir: str, n_files: int, rows: int,
                          image_size: int, num_classes: int,
                          seed: int = 0, signal: float = 0.7,
                          label_noise: float = 0.0) -> None:
    """Learnable synthetic image shards + one val shard (deterministic).

    Each class is a fixed random template blended into noise — a
    template-matching task a conv net learns quickly (an argmax-of-linear
    task would be unlearnable through global average pooling).

    `label_noise` flips that fraction of RECORDED labels (train and val)
    to a different class while the image keeps its true template. A
    template task at 224px is separable at any SNR (the signal averages
    over ~150k pixels), so accuracy otherwise saturates at 1.0; label
    noise pins the val ceiling at ~1 - label_noise, giving convergence
    comparisons (e.g. the north-star <1%-over-resizes clause) a
    sub-ceiling operating point where a delta is measurable."""
    os.makedirs(data_dir, exist_ok=True)
    templates = np.random.default_rng(77).normal(
        size=(num_classes, image_size, image_size, 3)).astype(np.float32)
    for i in range(n_files + 1):  # last = validation shard
        rng = np.random.default_rng(seed * 131 + i)
        label = rng.integers(0, num_classes, size=rows).astype(np.int32)
        img = (rng.normal(size=(rows, image_size, image_size, 3))
               .astype(np.float32) + signal * templates[label])
        if label_noise > 0.0:
            flip = rng.random(rows) < label_noise
            shift = rng.integers(1, num_classes, size=rows)
            label = np.where(flip, (label + shift) % num_classes,
                             label).astype(np.int32)
        name = "val.npz" if i == n_files else f"train-{i:04d}.npz"
        # float16 on disk/wire: half the host->device bytes of fp32 (the
        # binding cost of 224px float shards), zero task fidelity loss
        # (unit-variance noise), and the model casts to its own dtype
        np.savez(os.path.join(data_dir, name),
                 image=img.astype(np.float16), label=label)


def build_schedule(args, steps_per_epoch: int, world: int) -> optax.Schedule:
    """The reference's LR menu (train_with_fleet.py:114-225).

    --batch-size is GLOBAL, so the LR is tied to the batch, not the
    world: an elastic resize keeps the same optimization (the linear
    scaling rule, edl_collective_design_doc.md:14-16, applies when the
    TOTAL batch grows with the trainer count — scale --lr yourself if
    you also scale --batch-size). The schedule horizon is
    --schedule-epochs (default --epochs) so a phase that stops early —
    an elastic segment resumed later — still follows the SAME decay
    curve as the full run."""
    base = args.lr
    warmup = args.warmup_epochs * steps_per_epoch
    horizon = args.schedule_epochs or args.epochs
    total = horizon * steps_per_epoch
    if args.lr_strategy == "cosine":
        return lr_lib.cosine_with_warmup(base, total, warmup)
    boundaries = [int(e) * steps_per_epoch for e in args.lr_boundaries]
    values = [base * (args.lr_decay ** i)
              for i in range(len(boundaries) + 1)]
    return lr_lib.piecewise_with_warmup(boundaries, values,
                                        max(warmup, 1))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="edl_tpu.examples.imagenet_train")
    parser.add_argument("--data-dir", required=True)
    parser.add_argument("--data-format", choices=("npz", "jpeg", "packed"),
                        default="npz",
                        help="npz: float shards; jpeg: a train.txt "
                             "'<path> <label>' file list of JPEGs with "
                             "host decode + random-resized-crop/flip "
                             "(the reference's reader_cv2 path) and "
                             "on-device normalization; packed: a "
                             "train.pack pre-decoded fixed-stride record "
                             "file (python -m edl_tpu.data.packed_records "
                             "pack) — the host only gathers raw bytes "
                             "and augmentation runs on device "
                             "(--augment-device default on)")
    parser.add_argument("--decode-threads", type=int,
                        default=max(1, (os.cpu_count() or 1) - 1),
                        help="JPEG decode/augment THREAD pool width "
                             "(ignored when --loader-workers > 0)")
    parser.add_argument("--loader-workers", type=int, default=None,
                        help="input-plane worker PROCESSES with "
                             "shared-memory batch hand-off — scales the "
                             "host loader past the GIL (default: "
                             "$EDL_TPU_LOADER_WORKERS, else 0 = "
                             "inline/threaded)")
    parser.add_argument("--make-synthetic", type=int, default=0,
                        help="generate N train shards (+1 val) first "
                             "(jpeg format: N random JPEGs + train.txt)")
    parser.add_argument("--rows-per-file", type=int, default=1024)
    parser.add_argument("--synthetic-signal", type=float, default=0.7,
                        help="template amplitude of the synthetic data: "
                             "lower = harder task (small-subset students "
                             "stay below the ceiling — the operating "
                             "point the distill-quality clause needs)")
    parser.add_argument("--synthetic-label-noise", type=float, default=0.0,
                        help="fraction of synthetic labels flipped (pins "
                             "the val accuracy ceiling at ~1-x; see "
                             "make_synthetic_shards)")
    parser.add_argument("--model", default="ResNet50_vd",
                        help="zoo factory: ResNet50[_vd], ResNet101, VGG16, "
                             "ResNetTiny, ...")
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--epochs", type=int, default=90,
                        help="train (or resume) up to this epoch")
    parser.add_argument("--schedule-epochs", type=int, default=0,
                        help="cosine-strategy LR horizon (default "
                             "--epochs); set to the job's TOTAL epochs "
                             "when an elastic segment stops early "
                             "(piecewise boundaries are absolute epochs "
                             "already, so it does not apply there)")
    parser.add_argument("--batch-size", type=int, default=256,
                        help="GLOBAL batch size")
    parser.add_argument("--lr", type=float, default=0.1,
                        help="base LR at world=1 (linear-scaled)")
    parser.add_argument("--lr-strategy", choices=("piecewise", "cosine"),
                        default="piecewise")
    parser.add_argument("--lr-boundaries", type=int, nargs="+",
                        default=[30, 60, 80], help="epochs")
    parser.add_argument("--lr-decay", type=float, default=0.1)
    parser.add_argument("--warmup-epochs", type=int, default=5)
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--weight-decay", type=float, default=1e-4)
    parser.add_argument("--dcn-compress", choices=("off", "topk", "int8"),
                        default=None,
                        help="cross-slice gradient wire format (default "
                             "$EDL_TPU_DCN_COMPRESS, else off): topk "
                             "ships values+indices, int8 one scale per "
                             "chip — both with error-feedback residuals "
                             "behind the loss-parity gate "
                             "(doc/design_comm.md)")
    parser.add_argument("--comm-bucket-mb", type=float, default=None,
                        help="bucket the gradient tree into N-MiB "
                             "reduction groups so late-backward buckets "
                             "overlap earlier buckets' communication "
                             "(default $EDL_TPU_COMM_BUCKET_MB, else 0 "
                             "= XLA's single fused reduction)")
    parser.add_argument("--fused-opt",
                        choices=("off", "fp32", "int8", "fp8"),
                        default=None,
                        help="fused optimizer path (train/fused_opt.py; "
                             "default $EDL_TPU_FUSED_OPT, else off): "
                             "fp32 = momentum-SGD as one kernel pass "
                             "per bucket, bitwise vs the optax chain; "
                             "int8/fp8 also hold the momentum "
                             "quantized with error-feedback residuals "
                             "(opt state and checkpoint bytes halve, "
                             "convergence-parity gated)")
    parser.add_argument("--dgc-sparsity", type=float, default=0.0,
                        help="deep gradient compression: fraction of "
                             "gradient entries dropped (0 = off; the "
                             "reference's use_dgc flag)")
    parser.add_argument("--dgc-rampup-epochs", type=int, default=1)
    parser.add_argument("--label-smoothing", type=float, default=0.1)
    parser.add_argument("--mixup-alpha", type=float, default=0.0)
    parser.add_argument("--bf16", action="store_true",
                        help="bf16 activations (fp32 params/optimizer)")
    parser.add_argument("--no-augment", action="store_true",
                        help="disable flip/crop transforms (synthetic-label "
                             "tasks are not augmentation-invariant)")
    parser.add_argument("--augment-device", type=int, default=None,
                        choices=(0, 1),
                        help="run crop/flip/normalize as a jitted program "
                             "ON DEVICE from the loader's per-step seeds "
                             "(ops/augment.py) instead of host "
                             "transforms — the host only gathers bytes. "
                             "npz/packed formats only (jpeg decode is "
                             "inherently host-side: pack it first). "
                             "Default: $EDL_TPU_AUGMENT_DEVICE, else on "
                             "for --data-format packed, off otherwise")
    parser.add_argument("--rotate", action="store_true",
                        help="jpeg mode: +-10 degree random rotation before "
                             "the crop (reference --rotate, img_tool.py)")
    parser.add_argument("--teachers", default="",
                        help="distill mode: comma-joined teacher_server "
                             "endpoints; the loss becomes temperature-KD "
                             "against served logits (reference "
                             "train_with_fleet.py soft-label path)")
    parser.add_argument("--distill-temperature", type=float, default=2.0)
    parser.add_argument("--distill-hard-weight", type=float, default=0.0,
                        help="0 = pure soft labels (the reference's "
                             "distill recipe); >0 mixes hard-label CE")
    parser.add_argument("--distill-topk", type=int, default=0,
                        help="negotiate the compressed teacher wire and "
                             "train on sparse top-K targets")
    parser.add_argument("--distill-predict-key", default="logits",
                        help="teacher fetch name (teacher_server "
                             "--output-key)")
    parser.add_argument("--ckpt-dir", default="")
    parser.add_argument("--ckpt-steps", type=int, default=None,
                        help="also checkpoint every N optimizer steps "
                             "(cheap under async saves; shrinks the "
                             "elastic replay window; default "
                             "$EDL_TPU_CKPT_STEPS, else epoch-end only)")
    parser.add_argument("--ckpt-sync", action="store_true",
                        help="synchronous saves (escape hatch; default "
                             "is async snapshot-then-write — the step "
                             "loop blocks only for the host snapshot)")
    parser.add_argument("--benchmark-log", default="")
    parser.add_argument("--profile", default="",
                        help="jax profiler trace dir; traces steps "
                             "10-15 on rank 0 (reference --profile, "
                             "train_with_fleet.py:521-530)")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    if args.rotate and (args.data_format != "jpeg" or args.no_augment):
        raise SystemExit("--rotate is a jpeg-mode augmentation (and is "
                         "incompatible with --no-augment)")
    if args.data_format == "jpeg" and args.synthetic_label_noise > 0:
        # validate flag combinations BEFORE any rank-dependent code: a
        # rank-0-only exit would strand the other ranks in the data-gen
        # barrier below
        raise SystemExit(
            "--synthetic-label-noise is only implemented for the npz "
            "synthetic generator (jpeg synthetic data is random-labeled "
            "noise already)")
    if 0 < args.schedule_epochs < args.epochs:
        raise SystemExit(
            f"--schedule-epochs {args.schedule_epochs} < --epochs "
            f"{args.epochs}: epochs past the horizon would train at "
            "LR ~0 (the horizon is the job TOTAL; the stop point is "
            "--epochs)")
    # Device-side augmentation: CLI > env > format default (on for
    # packed — the whole point of packing is a transform-free host).
    # Resolved BEFORE any rank-dependent code so bad combinations exit
    # every rank identically.
    if args.augment_device is not None:
        augment_device = bool(args.augment_device)
    else:
        env_aug = config.env_str("EDL_TPU_AUGMENT_DEVICE")
        augment_device = (env_aug.lower() in ("1", "true", "yes", "on")
                          if env_aug is not None
                          else args.data_format == "packed")
    if args.no_augment:
        augment_device = False
    if augment_device and args.data_format == "jpeg":
        raise SystemExit(
            "--augment-device needs fixed-stride pre-decoded pixels and "
            "jpeg decode is inherently host-side — pack the list first: "
            "python -m edl_tpu.data.packed_records pack --jpeg-list "
            "train.txt --root DATA --out DATA/train.pack, then "
            "--data-format packed")
    if augment_device and args.teachers:
        raise SystemExit(
            "--augment-device is not supported with --teachers (the "
            "distill reader ships the teacher the SAME pixels the "
            "student trains on; device-augmented pixels never exist on "
            "host)")
    distributed.force_platform_from_env()
    env = distributed.init_from_env()
    world = max(1, env.world_size)
    rank = max(0, env.rank)
    if args.make_synthetic and rank == 0:
        if args.data_format == "jpeg":
            from edl_tpu.data.image import make_synthetic_jpeg_dataset
            make_synthetic_jpeg_dataset(
                args.data_dir, args.make_synthetic,
                classes=args.num_classes, seed=args.seed,
                hw=(args.image_size * 3 // 2, args.image_size * 2))
        else:
            make_synthetic_shards(args.data_dir, args.make_synthetic,
                                  args.rows_per_file, args.image_size,
                                  args.num_classes, args.seed,
                                  signal=args.synthetic_signal,
                                  label_noise=args.synthetic_label_noise)
            if args.data_format == "packed":
                # pack the freshly-written float shards (dtypes
                # preserved); val stays val.npz — eval reads it directly
                from edl_tpu.data.packed_records import pack_npz
                shards = sorted(
                    os.path.join(args.data_dir, f)
                    for f in os.listdir(args.data_dir)
                    if f.startswith("train-") and f.endswith(".npz"))
                pack_npz(shards,
                         os.path.join(args.data_dir, "train.pack"))
    if args.make_synthetic and jax.process_count() > 1:
        # non-writers must not listdir a half-written data dir
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("edl_imagenet_data_gen")

    val_path = os.path.join(args.data_dir, "val.npz")
    if args.batch_size % world:
        raise SystemExit(f"global batch {args.batch_size} not divisible by "
                         f"world {world}")
    local_bs = args.batch_size // world

    ckpt_kw = {}
    if args.ckpt_steps is not None:
        ckpt_kw["ckpt_every_steps"] = args.ckpt_steps
    if args.ckpt_sync:
        ckpt_kw["ckpt_async"] = False
    loop_cfg = from_env(LoopConfig, num_epochs=args.epochs,
                        ckpt_dir=args.ckpt_dir or env.checkpoint_path
                        or None,
                        profile_dir=args.profile or None, **ckpt_kw)
    # --loader-workers wins when given; otherwise the LoopConfig (its
    # EDL_TPU_LOADER_WORKERS binding) sets the mp pool width, so the
    # loop config actually drives the input plane it runs on.
    loader_workers = (args.loader_workers
                      if args.loader_workers is not None
                      else loop_cfg.loader_workers)

    # hybrid ICI x DCN when the job is (or declares itself) multi-slice:
    # dp's major dimension crosses DCN, flat dp otherwise
    mesh = distributed.make_mesh_from_env(mesh_lib.MeshSpec({"dp": -1}),
                                          env)
    # DCN-aware gradient path: CLI > env (LoopConfig binding) > off.
    # A compressed wire implies bucketing (default 4 MiB target).
    dcn_compress = (args.dcn_compress if args.dcn_compress is not None
                    else loop_cfg.dcn_compress)
    comm_bucket_mb = (args.comm_bucket_mb
                      if args.comm_bucket_mb is not None
                      else loop_cfg.comm_bucket_mb)
    comm_cfg = None
    if dcn_compress != "off" or comm_bucket_mb > 0:
        if args.teachers:
            raise SystemExit(
                "--dcn-compress/--comm-bucket-mb are not supported "
                "with --teachers (the distill steps carry their own "
                "jit; the dp gradient wire is the student-only path)")
        from edl_tpu.train.comm import CommConfig
        comm_cfg = CommConfig(bucket_mb=comm_bucket_mb or 4.0,
                              compress=dcn_compress)
    # Fused optimizer path: CLI > env (LoopConfig binding) > off;
    # EDL_TPU_OPT_QUANT overrides just the resident-moment codec.
    fused_opt = (args.fused_opt if args.fused_opt is not None
                 else loop_cfg.fused_opt)
    if loop_cfg.opt_quant and fused_opt != "off":
        if loop_cfg.opt_quant not in ("off", "int8", "fp8"):
            raise SystemExit(f"EDL_TPU_OPT_QUANT must be off|int8|fp8, "
                             f"got {loop_cfg.opt_quant!r}")
        fused_opt = ("fp32" if loop_cfg.opt_quant == "off"
                     else loop_cfg.opt_quant)
    if fused_opt not in ("off", "fp32", "int8", "fp8"):
        raise SystemExit(f"EDL_TPU_FUSED_OPT must be off|fp32|int8|fp8, "
                         f"got {fused_opt!r}")
    if fused_opt != "off" and args.dgc_sparsity > 0:
        raise SystemExit(
            "--fused-opt and --dgc-sparsity are mutually exclusive: "
            "DGC's momentum correction REPLACES optimizer momentum "
            "inside an optax chain, while the fused path owns the "
            "whole momentum update in-kernel. Pick one compression "
            "story (DGC sparsifies the wire, fused-int8 shrinks "
            "resident state).")
    data_sharding = mesh_lib.data_sharding(mesh)
    normalize = None
    if args.data_format == "jpeg":
        from edl_tpu.data.image import (JpegFileListSource,
                                        eval_image_transform,
                                        train_image_transform)
        list_file = os.path.join(args.data_dir, "train.txt")
        if not os.path.exists(list_file):
            raise SystemExit(f"no train.txt under {args.data_dir}")
        source = JpegFileListSource(list_file, root=args.data_dir)
        # --no-augment keeps the deterministic eval-style decode (for
        # synthetic-label tasks that are not augmentation-invariant)
        sample_t = (eval_image_transform(
                        args.image_size, short=args.image_size * 8 // 7)
                    if args.no_augment
                    else train_image_transform(args.image_size,
                                               rotate=args.rotate))
        loader = DataLoader(source, local_bs, rank=rank, world=world,
                            seed=args.seed, sample_transforms=(sample_t,),
                            decode_threads=args.decode_threads,
                            num_workers=loader_workers)
        normalize = "imagenet"  # uint8 off the wire; normalize on chip
        n_files = len(source)
    else:
        if args.data_format == "packed":
            from edl_tpu.data.packed_records import PackedSource
            pack_path = os.path.join(args.data_dir, "train.pack")
            if not os.path.exists(pack_path):
                raise SystemExit(
                    f"no train.pack under {args.data_dir} (pack one: "
                    "python -m edl_tpu.data.packed_records pack)")
            source = PackedSource(pack_path)
            # pre-decoded uint8 (the jpeg-packed path) normalizes like
            # the jpeg plane; float shards were normalized at pack time
            if source.fields["image"][1] == np.uint8:
                normalize = "imagenet"
            n_files = 1
        else:
            files = sorted(os.path.join(args.data_dir, f)
                           for f in os.listdir(args.data_dir)
                           if f.startswith("train-") and f.endswith(".npz"))
            if not files:
                raise SystemExit(
                    f"no train-*.npz shards under {args.data_dir}")
            source = FileSource(files)
            n_files = len(files)
        # device augmentation replaces the host batch transforms: the
        # loader ships raw bytes + the per-step seed, and the SAME
        # crop/flip (+ normalize) runs jitted after placement
        transforms = () if (args.no_augment or augment_device) \
            else (random_flip_lr, random_crop)
        loader = DataLoader(source, local_bs, rank=rank, world=world,
                            seed=args.seed, transforms=transforms,
                            num_workers=loader_workers,
                            emit_batch_seed=augment_device)
    steps_per_epoch = loader.steps_per_epoch()
    log.info("world=%d rank=%d devices=%d format=%s shards=%d samples=%d "
             "steps/epoch=%d", world, rank, jax.device_count(),
             args.data_format, n_files, len(source), steps_per_epoch)

    from edl_tpu import models as zoo
    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model = zoo.get_model(args.model)(num_classes=args.num_classes,
                                      dtype=dtype)
    schedule = build_schedule(args, steps_per_epoch, world)
    if args.dgc_sparsity > 0:
        from edl_tpu.train.dgc import dgc
        # DGC's momentum correction REPLACES optimizer momentum, and
        # weight decay stays dense (applied after the compressor) so
        # regularization strength is uniform, not send-frequency-tied.
        tx = optax.chain(
            dgc(sparsity=args.dgc_sparsity, momentum=args.momentum,
                rampup_steps=args.dgc_rampup_epochs * steps_per_epoch),
            optax.add_decayed_weights(args.weight_decay),
            optax.sgd(schedule))
    elif fused_opt != "off":
        from edl_tpu.train.fused_opt import make_fused_tx
        # same math as the optax chain below (fp32 mode is bitwise):
        # decayed weights fold into the momentum update in-kernel
        tx = make_fused_tx("sgdm", schedule, fused_opt,
                           momentum=args.momentum,
                           weight_decay=args.weight_decay)
        log.info("fused optimizer path: sgd-m %s", fused_opt)
    else:
        tx = optax.chain(
            optax.add_decayed_weights(args.weight_decay),
            optax.sgd(schedule, momentum=args.momentum, nesterov=False))
    state = create_state(model, jax.random.PRNGKey(args.seed),
                         (1, args.image_size, args.image_size, 3), tx)
    distill_reader = None
    if args.teachers:
        from edl_tpu.distill.reader import DistillReader
        from edl_tpu.train.classification import (make_distill_step,
                                                  make_sparse_distill_step)
        if args.mixup_alpha > 0:
            raise SystemExit("--mixup-alpha is not supported with "
                             "--teachers (mixed pixels would be sent to "
                             "a teacher that expects clean inputs)")
        if normalize is not None:
            # The student normalizes ON DEVICE; the teacher receives the
            # RAW wire feeds and must apply the SAME preprocessing.
            log.warning(
                "distill on the JPEG plane ships raw uint8 feeds: start "
                "the teacher with --input-normalize %s (a mismatched "
                "teacher emits out-of-distribution logits)", normalize)
        kd_kw = dict(temperature=args.distill_temperature,
                     hard_weight=args.distill_hard_weight,
                     smoothing=args.label_smoothing,
                     predict_key=args.distill_predict_key,
                     normalize=normalize)
        step = (make_sparse_distill_step(args.num_classes, **kd_kw)
                if args.distill_topk
                else make_distill_step(args.num_classes, **kd_kw))
        # ONE reader reused across epochs: data_fn retargets its source
        # at the current epoch (seed-per-pass order preserved)
        distill_epoch = [0]
        distill_reader = DistillReader(
            lambda: loader.epoch(distill_epoch[0]), feeds=("image",),
            predicts=(args.distill_predict_key,),
            teachers=[t for t in args.teachers.split(",") if t],
            compress_topk=args.distill_topk,
            sparse_predicts=bool(args.distill_topk))
    else:
        step = make_classification_step(
            args.num_classes, smoothing=args.label_smoothing,
            mixup_alpha=args.mixup_alpha, seed=args.seed,
            # with device augmentation the augment op normalizes (one
            # fused uint8->float pass after crop/flip); the step must
            # not normalize twice
            normalize=None if augment_device else normalize,
            comm=comm_cfg, mesh=mesh,
            topology=distributed.slice_topology(env))
        if comm_cfg is not None:
            log.info("dcn-aware gradient path: bucket=%.1fMiB "
                     "compress=%s", comm_cfg.bucket_mb,
                     comm_cfg.compress)
    eval_step = make_eval_step(normalize=normalize)
    augment = None
    if augment_device:
        from edl_tpu.ops.augment import make_device_augment
        augment = make_device_augment(pad=4, base_seed=args.seed,
                                      normalize=normalize)
        log.info("device-side augmentation: crop(pad=4)+flip+normalize "
                 "jitted on device from loader-emitted per-step seeds")

    # eval_batches: None, or a zero-arg callable yielding {'image',
    # 'label'} host batches of local_bs (streamed — a 50k-image val set
    # must not be decoded serially into one giant resident array)
    eval_batches = None
    val_pack = os.path.join(args.data_dir, "val.pack")
    if args.data_format == "packed" and os.path.exists(val_pack):
        from edl_tpu.data.packed_records import PackedSource
        vsrc = PackedSource(val_pack)
        if len(vsrc) >= local_bs:
            def _packed_eval_batches():
                for lo in range(0, len(vsrc) - local_bs + 1, local_bs):
                    yield vsrc.batch(np.arange(lo, lo + local_bs))

            eval_batches = _packed_eval_batches
        else:
            log.warning("val.pack has %d < batch %d rows — eval off",
                        len(vsrc), local_bs)
    elif args.data_format == "jpeg":
        val_list = os.path.join(args.data_dir, "val.txt")
        if os.path.exists(val_list):
            vsrc = JpegFileListSource(val_list, root=args.data_dir)
            if len(vsrc) >= local_bs:
                vloader = DataLoader(
                    vsrc, local_bs, shuffle=False,
                    sample_transforms=(eval_image_transform(
                        args.image_size,
                        short=args.image_size * 8 // 7),),
                    decode_threads=args.decode_threads)
                eval_batches = lambda: vloader.epoch(0)  # noqa: E731
            else:
                log.warning("val.txt has %d < batch %d images — eval off",
                            len(vsrc), local_bs)
    elif os.path.exists(val_path):
        with np.load(val_path) as z:
            eval_data = {"image": z["image"], "label": z["label"]}

        def _npz_eval_batches():
            for lo in range(0, len(eval_data["label"]) - local_bs + 1,
                            local_bs):
                yield {k: v[lo:lo + local_bs]
                       for k, v in eval_data.items()}

        eval_batches = _npz_eval_batches

    blog = BenchmarkLog(args.model, batch_size=args.batch_size,
                        world_size=world)
    epoch_t0 = [time.perf_counter()]

    def eval_fn(state, epoch):
        elapsed = time.perf_counter() - epoch_t0[0]
        # per-trainer rate (this rank consumed local_bs per step);
        # benchlog multiplies its max by world_size for the global figure
        rate = steps_per_epoch * local_bs / max(elapsed, 1e-9)
        results = {"examples_per_sec": rate}
        if eval_batches is not None:
            accs, n = {"acc1": 0.0, "acc5": 0.0}, 0
            for hb in eval_batches():
                ev = eval_step(state, {"image": jnp.asarray(hb["image"]),
                                       "label": jnp.asarray(hb["label"])})
                for k in accs:
                    accs[k] += float(ev[k])
                n += 1
            results.update({k: v / max(n, 1) for k, v in accs.items()})
        blog.epoch(epoch, **results)
        epoch_t0[0] = time.perf_counter()
        return results

    # Single-process: the augment applies inside prefetch_to_device's
    # staging thread (dispatched under the running step). Multi-process:
    # batches reach TrainLoop._place as host arrays (form_global_batch),
    # so the loop pops the seed and augments after forming the global
    # batch — exactly one of the two paths owns the seed.
    loop = TrainLoop(
        step, state, mesh=mesh, config=loop_cfg, eval_fn=eval_fn,
        place_state=lambda t: mesh_lib.replicate_host_tree(mesh, t),
        augment_fn=augment if jax.process_count() > 1 else None)

    def data_fn(epoch):
        if distill_reader is not None:
            distill_epoch[0] = epoch
            it = distill_reader()
        else:
            it = loader.epoch(epoch)
        return prefetch_to_device(it, data_sharding, augment=augment) \
            if jax.process_count() == 1 else it

    # TrainLoop closes the data plane it drives (decode pool / mp
    # workers + shm ring) when the run ends, crash paths included
    data_fn.close = loader.close

    try:
        status = loop.run(data_fn)
    finally:
        # close on the deadman/error path too (discovery client thread)
        if distill_reader is not None:
            distill_reader.close()
    blog.extra(**loop.ckpt_stats())  # save-stall / restore accounting
    if comm_cfg is not None:
        blog.extra(**step.stats())  # bucket plan + DCN wire accounting
    if rank == 0 and args.benchmark_log:
        blog.write(args.benchmark_log, rank)
    final = blog.finalize().get("final", {})
    log.info("done: epoch=%d step=%d %s", status.epoch, status.step,
             {k: round(v, 4) for k, v in final.items()})
    if final:
        print(f"final_acc1={final.get('acc1', float('nan')):.4f}")
    distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
