"""DistillReader format demo: all three reference reader formats.

Capability of the reference's reader demo
(example/distill/reader_demo/distill_reader_demo.py): the SAME data
source expressed as a sample generator, a sample-list generator, and a
batch generator, each wrapped by DistillReader and verified to come back
in its ORIGINAL structure with the teacher's prediction slot appended.

By default spins an in-process teacher over a real TCP socket (the
reference needed an external Paddle Serving teacher); pass
``--teachers h:p,...`` to use external teacher_server processes instead.

Run:  python -m edl_tpu.examples.reader_demo [--format all]
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from edl_tpu.distill.reader import DistillReader
from edl_tpu.distill.teacher_server import TeacherServer
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.examples.reader_demo")

BATCH_NUM = 10
BATCH_SIZE = 16
IMG_SHAPE = (1, 28, 28)
NUM_CLASSES = 10


def get_random_images_and_labels(rng):
    image = rng.random(size=IMG_SHAPE).astype(np.float32)
    label = rng.integers(0, NUM_CLASSES, size=(1,)).astype(np.int64)
    return image, label


def sample_generator_creator():
    """Yields ONE (image, label) sample per iteration."""
    def __reader__():
        rng = np.random.default_rng(0)
        for _ in range(BATCH_NUM * BATCH_SIZE):
            yield get_random_images_and_labels(rng)

    return __reader__


def sample_list_generator_creator():
    """Yields a LIST of BATCH_SIZE samples per iteration."""
    def __reader__():
        rng = np.random.default_rng(0)
        for _ in range(BATCH_NUM):
            yield [get_random_images_and_labels(rng)
                   for _ in range(BATCH_SIZE)]

    return __reader__


def batch_generator_creator():
    """Yields stacked (images, labels) arrays per iteration."""
    def __reader__():
        rng = np.random.default_rng(0)
        for _ in range(BATCH_NUM):
            images = rng.random(
                size=(BATCH_SIZE,) + IMG_SHAPE).astype(np.float32)
            labels = rng.integers(
                0, NUM_CLASSES, size=(BATCH_SIZE, 1)).astype(np.int64)
            yield images, labels

    return __reader__


def make_teacher_predict(seed: int = 42):
    """Deterministic linear 'teacher': logits from a fixed projection."""
    w = np.random.default_rng(seed).normal(
        size=(int(np.prod(IMG_SHAPE)), NUM_CLASSES)).astype(np.float32)

    def predict(feeds):
        images = feeds["img"].reshape(feeds["img"].shape[0], -1)
        return {"fc_0.tmp_2": images @ w}

    return predict


def make_reader(teachers, fmt: str) -> DistillReader:
    dr = DistillReader(ins=["img", None], predicts=["fc_0.tmp_2"],
                       teacher_batch_size=BATCH_SIZE)
    dr.set_fixed_teacher(teachers)
    if fmt == "sample_generator":
        dr.set_sample_generator(sample_generator_creator())
    elif fmt == "sample_list_generator":
        dr.set_sample_list_generator(sample_list_generator_creator())
    elif fmt == "batch_generator":
        dr.set_batch_generator(batch_generator_creator())
    else:
        raise ValueError(f"unsupported data format {fmt!r}")
    return dr


def run_format(teachers, fmt: str) -> None:
    train_reader = make_reader(teachers, fmt)
    if fmt == "sample_generator":
        step = 0
        for img, label, prediction in train_reader():
            assert img.shape == IMG_SHAPE
            assert label.shape == (1,)
            assert prediction.shape == (NUM_CLASSES,)
            step += 1
        assert step == BATCH_NUM * BATCH_SIZE
        log.info("sample_generator: %d samples, last prediction[:3]=%s",
                 step, prediction[:3])
    elif fmt == "sample_list_generator":
        n = 0
        for sample_list in train_reader():
            assert len(sample_list) == BATCH_SIZE
            for img, label, prediction in sample_list:
                assert img.shape == IMG_SHAPE
                assert label.shape == (1,)
                assert prediction.shape == (NUM_CLASSES,)
            n += 1
        assert n == BATCH_NUM
        log.info("sample_list_generator: %d lists of %d", n, BATCH_SIZE)
    else:
        n = 0
        for img, label, prediction in train_reader():
            assert img.shape == (BATCH_SIZE,) + IMG_SHAPE
            assert label.shape == (BATCH_SIZE, 1)
            assert prediction.shape == (BATCH_SIZE, NUM_CLASSES)
            n += 1
        assert n == BATCH_NUM
        log.info("batch_generator: %d batches of %d", n, BATCH_SIZE)
    train_reader.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="edl_tpu.examples.reader_demo")
    parser.add_argument("--teachers", default="",
                        help="external teacher endpoints h:p,... "
                             "(default: in-process teacher)")
    parser.add_argument("--format", default="all",
                        choices=("all", "sample_generator",
                                 "sample_list_generator",
                                 "batch_generator"))
    args = parser.parse_args(argv)

    server = None
    if args.teachers:
        teachers = args.teachers
    else:
        server = TeacherServer(make_teacher_predict(),
                               host="127.0.0.1").start()
        teachers = f"127.0.0.1:{server.port}"
    formats = (("sample_generator", "sample_list_generator",
                "batch_generator") if args.format == "all"
               else (args.format,))
    try:
        for fmt in formats:
            run_format(teachers, fmt)
    finally:
        if server is not None:
            server.stop()
    print(f"ok formats={','.join(formats)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
