"""Elastic CTR training: DeepFM over task-dispensed Criteo-style files.

Capability of the reference's CTR path (example/ctr/ctr/train.py —
Criteo DNN fed by a file-list dataset, dispensed by the Go master's
GetTask/TaskFinished lease loop, pkg/master/service.go:95-208; trained
async on an elastic trainer set), tpu-native: the PS/async world becomes
data-parallel DeepFM on a device mesh, and elasticity lives entirely in
the data plane — every trainer leases file-shard tasks from the
`TaskMaster` table in the coordination store, so trainers can join/leave
mid-epoch and a dead trainer's shards are re-dispensed after the lease
timeout with no record lost or doubled.

Modes:
  default             in-process store, one trainer — smoke/bench run;
  --store h:p         shared store: run N copies of this CLI (distinct
                      --trainer-id) against one store for elastic multi-
                      trainer dispensing; the first to start installs the
                      epoch's task table.

Data: --data-dir of .npz files (keys: dense (B,13) f32, sparse (B,26)
int32, label (B,) f32); --make-synthetic N generates them (deterministic,
learnable: label depends on a fixed projection of features).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.coord.store import InMemStore
from edl_tpu.data.task_loader import TaskDataLoader, npz_loader
from edl_tpu.data.task_master import TaskMaster, file_list_specs
from edl_tpu.models.deepfm import DeepFM, auc, bce_with_logits
from edl_tpu.train.benchlog import BenchmarkLog
from edl_tpu.train.state import TrainState
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.examples.ctr_train")

VOCAB = 10000
N_DENSE, N_SPARSE = 13, 26


def make_synthetic_files(data_dir: str, n_files: int, rows_per_file: int,
                         seed: int = 0) -> list[str]:
    """Deterministic learnable CTR shards (one .npz per 'day part')."""
    os.makedirs(data_dir, exist_ok=True)
    proj = np.random.default_rng(999)
    w_dense = proj.normal(size=(N_DENSE,)).astype(np.float32)
    w_sparse = proj.normal(size=(VOCAB,)).astype(np.float32) * 0.3
    files = []
    for i in range(n_files):
        rng = np.random.default_rng(seed * 10007 + i)
        dense = rng.normal(size=(rows_per_file, N_DENSE)).astype(np.float32)
        sparse = rng.integers(0, VOCAB, size=(rows_per_file, N_SPARSE),
                              dtype=np.int32)
        score = dense @ w_dense + w_sparse[sparse].sum(axis=1)
        label = (score + 0.5 * rng.normal(size=rows_per_file)
                 > 0).astype(np.float32)
        path = os.path.join(data_dir, f"part-{i:03d}.npz")
        np.savez(path, dense=dense, sparse=sparse, label=label)
        files.append(path)
    return files


def make_train_step(model: DeepFM):
    @jax.jit
    def step(state, batch):
        def loss_fn(params):
            logits = model.apply({"params": params}, batch["dense"],
                                 batch["sparse"], train=True)
            return bce_with_logits(logits, batch["label"])

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), {"loss": loss}

    return step


def make_eval_forward(model):
    """Jitted eval forward, built ONCE (jit caches on the fn object — a
    fresh lambda per eval would recompile every epoch)."""
    return jax.jit(lambda p, d, s: model.apply({"params": p}, d, s))


def evaluate(forward, state, files: list[str], batch_size: int) -> dict:
    """AUC + loss over a held-out shard list."""
    scores, labels, losses = [], [], []
    for f in files:
        arrays = npz_loader({"file": f})
        n = len(arrays["label"])
        if n < batch_size:
            raise SystemExit(
                f"eval shard {f} has {n} rows < batch size {batch_size}")
        for lo in range(0, n, batch_size):
            hi = lo + batch_size
            if hi > n:
                break  # static shapes: drop ragged tail
            logits = forward(state.params,
                             jnp.asarray(arrays["dense"][lo:hi]),
                             jnp.asarray(arrays["sparse"][lo:hi]))
            losses.append(float(bce_with_logits(
                logits, jnp.asarray(arrays["label"][lo:hi]))))
            scores.append(np.asarray(jax.nn.sigmoid(logits)).reshape(-1))
            labels.append(arrays["label"][lo:hi])
    return {"auc": auc(np.concatenate(scores), np.concatenate(labels)),
            "loss": float(np.mean(losses))}


def train(args) -> int:
    if args.make_synthetic:
        files = make_synthetic_files(args.data_dir, args.make_synthetic,
                                     args.rows_per_file, seed=args.seed)
    else:
        files = sorted(
            os.path.join(args.data_dir, f) for f in os.listdir(args.data_dir)
            if f.endswith(".npz"))
    if len(files) < 2:
        raise SystemExit("need >= 2 data files (last one is held out)")
    train_files, eval_files = files[:-1], files[-1:]

    if args.store:
        from edl_tpu.coord.client import StoreClient
        store = StoreClient(args.store)
    else:
        store = InMemStore()
    master = TaskMaster(store, args.job_id, args.trainer_id,
                        lease_timeout=args.lease_timeout)
    loader = TaskDataLoader(master, npz_loader, args.batch_size,
                            drop_remainder=True, seed=args.seed)

    model = DeepFM(vocab_size=VOCAB, embed_dim=args.embed_dim,
                   hidden=tuple(args.hidden))
    params = model.init(jax.random.PRNGKey(args.seed),
                        jnp.zeros((1, N_DENSE), jnp.float32),
                        jnp.zeros((1, N_SPARSE), jnp.int32))["params"]
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=optax.adam(args.lr))
    step = make_train_step(model)

    eval_forward = make_eval_forward(model)
    blog = BenchmarkLog("deepfm_ctr", batch_size=args.batch_size,
                        world_size=1, trainer_id=args.trainer_id)
    for epoch in range(args.epochs):
        # any trainer may install the table; init_epoch is idempotent
        master.init_epoch(epoch, file_list_specs(train_files))
        t0, n = time.perf_counter(), 0
        done0, lost0 = loader.tasks_completed, loader.tasks_lost
        losses = []
        for batch in loader.epoch(epoch):
            state, metrics = step(state, batch)
            losses.append(metrics["loss"])  # device scalar; sync at epoch end
            n += len(batch["label"])
        rate = n / max(time.perf_counter() - t0, 1e-9)
        ev = evaluate(eval_forward, state, eval_files, args.batch_size)
        log.info("epoch %d: train_loss=%.4f eval_loss=%.4f auc=%.4f "
                 "(%.0f ex/s, %d tasks, %d lost)", epoch,
                 float(np.mean([float(l) for l in losses])), ev["loss"],
                 ev["auc"], rate, loader.tasks_completed - done0,
                 loader.tasks_lost - lost0)
        blog.epoch(epoch, examples_per_sec=rate, **ev)
    final = blog.finalize()["final"]
    if args.benchmark_log:
        blog.write(args.benchmark_log)
    print(f"final_auc={final['auc']:.4f}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="edl_tpu.examples.ctr_train")
    parser.add_argument("--data-dir", default="./ctr_data")
    parser.add_argument("--make-synthetic", type=int, default=0,
                        help="generate N synthetic .npz shards first")
    parser.add_argument("--rows-per-file", type=int, default=4096)
    parser.add_argument("--store", default="",
                        help="shared store host:port (elastic multi-trainer)")
    parser.add_argument("--job-id", default="ctr")
    parser.add_argument("--trainer-id", default=f"trainer-{os.getpid()}")
    parser.add_argument("--lease-timeout", type=float, default=30.0)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=512)
    parser.add_argument("--embed-dim", type=int, default=10)
    parser.add_argument("--hidden", type=int, nargs="+",
                        default=[400, 400, 400])
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--benchmark-log", default="",
                        help="dir for benchmark_logs JSON (train/benchlog.py)")
    return train(parser.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
