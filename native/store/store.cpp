#include "store.hpp"

#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstring>
#include <fstream>
#include <tuple>

namespace edl {

namespace {
std::string path_join(const std::string& dir, const char* name) {
  return dir + "/" + name;
}
}  // namespace

Store::Store(std::string data_dir, bool fsync, size_t max_events,
             size_t snapshot_every)
    : max_events_(max_events),
      data_dir_(std::move(data_dir)),
      fsync_(fsync),
      snapshot_every_(snapshot_every) {
  if (!data_dir_.empty()) {
    ::mkdir(data_dir_.c_str(), 0755);  // EEXIST is fine
    load();
    wal_ = std::fopen(path_join(data_dir_, "wal.log").c_str(), "ab");
    if (!wal_)
      throw std::runtime_error("cannot open WAL: " +
                               std::string(std::strerror(errno)));
  }
}

Store::~Store() {
  if (wal_) std::fclose(wal_);
}

// ---- watchers -------------------------------------------------------------

void Watcher::push(const Event& ev) {
  if (ev.key.compare(0, prefix_.size(), prefix_) != 0) return;
  std::lock_guard<std::mutex> lk(*wmu_);
  if (cancelled_) return;
  if (pending_.size() >= max_pending_) {
    // lagging consumer: drop everything, force a resync
    pending_.clear();
    compacted_ = true;
    compacted_rev_ = ev.revision;
  } else {
    pending_.push_back(ev);
  }
  cv_.notify_all();
}

std::optional<WatchBatch> Watcher::wait_batch(double timeout_s) {
  std::unique_lock<std::mutex> lk(*wmu_);
  // wait_until on system_clock, NOT wait_for: libstdc++'s wait_for
  // takes the pthread_cond_clockwait path, which older libtsan does
  // not intercept — the wait's internal mutex release then becomes
  // invisible and every later lock reports as a phantom "double lock".
  // A clock jump at worst stretches one heartbeat; correctness only
  // depends on the predicate.
  auto deadline = std::chrono::system_clock::now() +
                  std::chrono::duration_cast<std::chrono::milliseconds>(
                      std::chrono::duration<double>(timeout_s));
  cv_.wait_until(lk, deadline, [&] {
    return cancelled_ || compacted_ || !pending_.empty();
  });
  if (compacted_) {
    // the compacted signal outranks anything queued after the overflow
    compacted_ = false;
    pending_.clear();
    WatchBatch batch;
    batch.compacted = true;
    batch.revision = compacted_rev_;
    return batch;
  }
  if (!pending_.empty()) {
    WatchBatch batch;
    batch.events.assign(pending_.begin(), pending_.end());
    pending_.clear();
    batch.revision = batch.events.back().revision;
    return batch;
  }
  return std::nullopt;  // timeout or cancelled
}

bool Watcher::cancelled() {
  std::lock_guard<std::mutex> lk(*wmu_);
  return cancelled_;
}

std::shared_ptr<Watcher> Store::watch(const std::string& prefix,
                                      int64_t start_revision) {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
  auto w = std::make_shared<Watcher>();
  {
    std::lock_guard<std::mutex> lk(watch_mu_);
    w->wmu_ = &watch_mu_;
    w->prefix_ = prefix;
    w->created_revision = revision_;
    if (start_revision >= 0) {
      if (start_revision + 1 < first_event_rev_) {
        w->compacted_ = true;
        w->compacted_rev_ = revision_;
      } else {
        for (const auto& ev : events_)
          if (ev.revision > start_revision &&
              ev.key.compare(0, prefix.size(), prefix) == 0)
            w->pending_.push_back(ev);
      }
    }
  }
  watchers_.push_back(w);
  return w;
}

void Store::watch_cancel(const std::shared_ptr<Watcher>& w) {
  {
    std::lock_guard<std::mutex> lock(mu_);
    watchers_.erase(std::remove(watchers_.begin(), watchers_.end(), w),
                    watchers_.end());
  }
  std::lock_guard<std::mutex> lk(watch_mu_);
  w->cancelled_ = true;
  w->cv_.notify_all();
}

std::optional<int64_t> Store::watch_progress(
    const std::shared_ptr<Watcher>& w) {
  std::lock_guard<std::mutex> lock(mu_);
  std::lock_guard<std::mutex> lk(watch_mu_);
  if (!w->pending_.empty() || w->compacted_ || w->cancelled_)
    return std::nullopt;
  return revision_;
}

// ---- unlocked internals ---------------------------------------------------

void Store::emit(Event ev) {
  for (auto& w : watchers_) w->push(ev);
  events_.push_back(std::move(ev));
  if (events_.size() > max_events_) {
    size_t drop = events_.size() - max_events_;
    first_event_rev_ = events_[drop].revision;
    events_.erase(events_.begin(),
                  events_.begin() + static_cast<ptrdiff_t>(drop));
  }
}

void Store::expire() {
  auto now = Clock::now();
  std::vector<int64_t> dead;
  for (auto& kv : leases_)
    if (kv.second.deadline <= now) dead.push_back(kv.first);
  for (int64_t id : dead) lease_revoke_unlocked(id, /*log=*/true);
}

void Store::check_lease(int64_t lease) {
  if (lease != 0 && leases_.find(lease) == leases_.end())
    throw LeaseExpiredError(lease);
}

void Store::detach(const std::string& key, const Record& rec) {
  if (rec.lease != 0) {
    auto it = leases_.find(rec.lease);
    if (it != leases_.end()) it->second.keys.erase(key);
  }
}

int64_t Store::put_unlocked(const std::string& key, const std::string& value,
                            int64_t lease, bool log) {
  check_lease(lease);
  auto old = data_.find(key);
  if (old != data_.end()) detach(key, old->second);
  int64_t rev = bump();
  data_[key] = Record{key, value, rev, lease};
  if (lease != 0) leases_[lease].keys.insert(key);
  emit(Event{"PUT", key, value, rev});
  if (log)
    wal_append(JsonObject{{"o", Json("put")},
               {"k", Json(key)},
               {"v", Json(value)},
               {"l", Json(lease)}});
  return rev;
}

bool Store::del_unlocked(const std::string& key, bool log) {
  auto it = data_.find(key);
  if (it == data_.end()) return false;
  Record rec = it->second;
  data_.erase(it);
  detach(key, rec);
  emit(Event{"DELETE", key, rec.value, bump()});
  if (log)
    wal_append(JsonObject{{"o", Json("del")}, {"k", Json(key)}});
  return true;
}

int64_t Store::lease_grant_unlocked(double ttl, int64_t forced_id, bool log) {
  int64_t id = forced_id > 0 ? forced_id : next_lease_;
  if (id >= next_lease_) next_lease_ = id + 1;
  Lease lease;
  lease.id = id;
  lease.ttl = ttl;
  lease.deadline =
      Clock::now() + std::chrono::duration_cast<Clock::duration>(
                         std::chrono::duration<double>(ttl));
  leases_[id] = std::move(lease);
  if (log)
    wal_append(JsonObject{
        {"o", Json("lg")}, {"id", Json(id)}, {"ttl", Json(ttl)}});
  return id;
}

bool Store::lease_revoke_unlocked(int64_t lease, bool log) {
  auto it = leases_.find(lease);
  if (it == leases_.end()) return false;
  // Copy: del_unlocked detaches from the live set while we iterate.
  std::set<std::string> keys = it->second.keys;
  leases_.erase(it);
  for (const auto& key : keys) {
    auto rec = data_.find(key);
    if (rec != data_.end()) {
      Record copy = rec->second;
      data_.erase(rec);
      emit(Event{"DELETE", key, copy.value, bump()});
    }
  }
  if (log)
    wal_append(JsonObject{{"o", Json("lr")}, {"id", Json(lease)}});
  return true;
}

// ---- public API -----------------------------------------------------------

int64_t Store::put(const std::string& key, const std::string& value,
                   int64_t lease) {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
  int64_t rev = put_unlocked(key, value, lease, /*log=*/true);
  maybe_snapshot();
  return rev;
}

std::optional<Record> Store::get(const std::string& key) {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
  auto it = data_.find(key);
  if (it == data_.end()) return std::nullopt;
  return it->second;
}

std::pair<std::vector<Record>, int64_t> Store::get_prefix(
    const std::string& prefix) {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
  std::vector<Record> out;
  // std::map is key-ordered: range-scan from lower_bound.
  for (auto it = data_.lower_bound(prefix); it != data_.end(); ++it) {
    if (it->first.compare(0, prefix.size(), prefix) != 0) break;
    out.push_back(it->second);
  }
  return {out, revision_};
}

bool Store::del(const std::string& key) {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
  bool deleted = del_unlocked(key, /*log=*/true);
  maybe_snapshot();
  return deleted;
}

int64_t Store::delete_prefix(const std::string& prefix) {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
  std::vector<std::string> keys;
  for (auto it = data_.lower_bound(prefix); it != data_.end(); ++it) {
    if (it->first.compare(0, prefix.size(), prefix) != 0) break;
    keys.push_back(it->first);
  }
  for (const auto& key : keys) del_unlocked(key, /*log=*/true);
  maybe_snapshot();
  return static_cast<int64_t>(keys.size());
}

bool Store::put_if_absent(const std::string& key, const std::string& value,
                          int64_t lease) {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
  if (data_.count(key)) return false;
  check_lease(lease);
  put_unlocked(key, value, lease, /*log=*/true);
  maybe_snapshot();
  return true;
}

bool Store::compare_and_swap(const std::string& key,
                             const std::optional<std::string>& expect,
                             const std::string& value, int64_t lease) {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
  auto cur = data_.find(key);
  if (!expect.has_value()) {
    if (cur != data_.end()) return false;
  } else if (cur == data_.end() || cur->second.value != *expect) {
    return false;
  }
  put_unlocked(key, value, lease, /*log=*/true);
  maybe_snapshot();
  return true;
}

int64_t Store::lease_grant(double ttl) {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
  int64_t id = lease_grant_unlocked(ttl, 0, /*log=*/true);
  maybe_snapshot();
  return id;
}

bool Store::lease_keepalive(int64_t lease) {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
  auto it = leases_.find(lease);
  if (it == leases_.end()) return false;
  it->second.deadline =
      Clock::now() + std::chrono::duration_cast<Clock::duration>(
                         std::chrono::duration<double>(it->second.ttl));
  // Keepalives are deliberately NOT logged: replayed leases restart with a
  // full TTL anyway, and logging 1/s per lease would bloat the WAL.
  return true;
}

bool Store::lease_revoke(int64_t lease) {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
  bool revoked = lease_revoke_unlocked(lease, /*log=*/true);
  maybe_snapshot();
  return revoked;
}

std::tuple<std::vector<Event>, int64_t, bool> Store::events_since(
    int64_t revision, const std::string& prefix) {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
  if (revision + 1 < first_event_rev_) return {{}, revision_, true};
  std::vector<Event> out;
  for (const auto& ev : events_) {
    if (ev.revision > revision &&
        ev.key.compare(0, prefix.size(), prefix) == 0)
      out.push_back(ev);
  }
  return {out, revision_, false};
}

void Store::sweep() {
  std::lock_guard<std::mutex> lock(mu_);
  expire();
}

// ---- persistence ----------------------------------------------------------

void Store::wal_append(JsonObject op) {
  if (!wal_ || replaying_) return;
  op.emplace("s", Json(++seq_));
  std::string line = Json(std::move(op)).dump();
  line += '\n';
  if (std::fwrite(line.data(), 1, line.size(), wal_) != line.size())
    throw std::runtime_error("WAL write failed");
  std::fflush(wal_);
  if (fsync_) ::fdatasync(::fileno(wal_));
  ++wal_lines_;
}

void Store::maybe_snapshot() {
  if (!wal_ || replaying_ || wal_lines_ < snapshot_every_) return;
  write_snapshot();
}

void Store::write_snapshot() {
  // Snapshot = full dump + truncated WAL; tmp-then-rename atomicity (the
  // same contract as checkpoints, doc/fault_tolerance.md style).
  JsonArray recs;
  for (const auto& kv : data_)
    recs.push_back(Json(JsonArray{Json(kv.second.key), Json(kv.second.value),
                                  Json(kv.second.revision),
                                  Json(kv.second.lease)}));
  JsonArray leases;
  for (const auto& kv : leases_)
    leases.push_back(
        Json(JsonArray{Json(kv.second.id), Json(kv.second.ttl)}));
  Json snap(JsonObject{{"revision", Json(revision_)},
                       {"seq", Json(seq_)},
                       {"next_lease", Json(next_lease_)},
                       {"records", Json(std::move(recs))},
                       {"leases", Json(std::move(leases))}});
  std::string tmp = path_join(data_dir_, "snapshot.json.tmp");
  std::string final_path = path_join(data_dir_, "snapshot.json");
  {
    std::ofstream out(tmp, std::ios::trunc | std::ios::binary);
    out << snap.dump();
    out.flush();
    if (!out) throw std::runtime_error("snapshot write failed");
  }
  if (std::rename(tmp.c_str(), final_path.c_str()) != 0)
    throw std::runtime_error("snapshot rename failed");
  if (wal_) std::fclose(wal_);
  wal_ = std::fopen(path_join(data_dir_, "wal.log").c_str(), "wb");
  if (!wal_) throw std::runtime_error("WAL reopen failed");
  if (fsync_) ::fdatasync(::fileno(wal_));
  wal_lines_ = 0;
}

void Store::load() {
  replaying_ = true;
  std::ifstream snap_in(path_join(data_dir_, "snapshot.json"),
                        std::ios::binary);
  if (snap_in) {
    std::string text((std::istreambuf_iterator<char>(snap_in)),
                     std::istreambuf_iterator<char>());
    if (!text.empty()) {
      Json snap = Json::parse(text);
      revision_ = snap["revision"].as_int();
      seq_ = snap["seq"].as_int(0);
      next_lease_ = snap["next_lease"].as_int(1);
      for (const auto& lease : snap["leases"].as_array()) {
        const auto& arr = lease.as_array();
        lease_grant_unlocked(arr[1].as_double(), arr[0].as_int(),
                             /*log=*/false);
      }
      for (const auto& rec : snap["records"].as_array()) {
        const auto& arr = rec.as_array();
        Record r{arr[0].as_string(), arr[1].as_string(), arr[2].as_int(),
                 arr[3].as_int()};
        if (r.lease != 0) {
          // A record with a vanished lease is dropped (its lease died with
          // the previous process; keeping it would fake liveness).
          auto it = leases_.find(r.lease);
          if (it == leases_.end()) continue;
          it->second.keys.insert(r.key);
        }
        data_[r.key] = r;
      }
    }
  }
  std::ifstream wal_in(path_join(data_dir_, "wal.log"), std::ios::binary);
  if (wal_in) {
    std::string line;
    while (std::getline(wal_in, line)) {
      if (line.empty()) continue;
      try {
        Json op = Json::parse(line);
        // A crash between snapshot rename and WAL truncation leaves the
        // whole old WAL behind a snapshot that already contains it; the
        // seq stamp tells us which ops those are.
        if (op.has("s") && op["s"].as_int() <= seq_) continue;
        replay_op(op);
        if (op.has("s")) seq_ = op["s"].as_int();
      } catch (const std::exception&) {
        // Torn tail write (crash mid-append): stop replaying here.
        break;
      }
    }
  }
  // Event history does not survive restarts; watchers see compacted=True
  // and fall back to a full get_prefix (the documented contract).
  first_event_rev_ = revision_ + 1;
  events_.clear();
  replaying_ = false;
}

void Store::replay_op(const Json& op) {
  const std::string& kind = op["o"].as_string();
  if (kind == "put") {
    try {
      put_unlocked(op["k"].as_string(), op["v"].as_string(),
                   op["l"].as_int(), /*log=*/false);
    } catch (const LeaseExpiredError&) {
      // Lease was revoked later in the WAL than this put was written —
      // impossible in order; but a lease dropped at snapshot load can
      // orphan a put. Skip: the key would have died with the lease.
    }
  } else if (kind == "del") {
    del_unlocked(op["k"].as_string(), /*log=*/false);
  } else if (kind == "lg") {
    lease_grant_unlocked(op["ttl"].as_double(), op["id"].as_int(),
                         /*log=*/false);
  } else if (kind == "lr") {
    lease_revoke_unlocked(op["id"].as_int(), /*log=*/false);
  } else {
    throw std::runtime_error("unknown WAL op: " + kind);
  }
}

}  // namespace edl
