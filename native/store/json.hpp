// Minimal JSON value + parser + serializer for the edl-store wire protocol.
//
// Self-contained (no external deps; the toolchain contract forbids
// pip/apt installs). Supports the full JSON grammar the Python side can
// produce via json.dumps: null/bool/number/string/object/array, with
// \uXXXX escapes (incl. surrogate pairs) -> UTF-8.
//
// Capability note: the reference ships Go+protobuf native components
// (pkg/master, SURVEY.md §2.2); our native plane speaks the framework's
// framed-JSON store protocol (edl_tpu/coord/wire.py) instead.

#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace edl {

class Json;
using JsonObject = std::map<std::string, Json>;
using JsonArray = std::vector<Json>;

class Json {
 public:
  enum class Type { Null, Bool, Int, Double, String, Object, Array };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(int v) : type_(Type::Int), int_(v) {}
  Json(int64_t v) : type_(Type::Int), int_(v) {}
  Json(uint64_t v) : type_(Type::Int), int_(static_cast<int64_t>(v)) {}
  Json(double v) : type_(Type::Double), double_(v) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonObject o) : type_(Type::Object),
                       obj_(std::make_shared<JsonObject>(std::move(o))) {}
  Json(JsonArray a) : type_(Type::Array),
                      arr_(std::make_shared<JsonArray>(std::move(a))) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_string() const { return type_ == Type::String; }
  bool is_object() const { return type_ == Type::Object; }

  bool as_bool(bool fallback = false) const {
    return type_ == Type::Bool ? bool_ : fallback;
  }
  int64_t as_int(int64_t fallback = 0) const {
    if (type_ == Type::Int) return int_;
    if (type_ == Type::Double) return static_cast<int64_t>(double_);
    return fallback;
  }
  double as_double(double fallback = 0.0) const {
    if (type_ == Type::Double) return double_;
    if (type_ == Type::Int) return static_cast<double>(int_);
    return fallback;
  }
  const std::string& as_string() const {
    static const std::string empty;
    return type_ == Type::String ? str_ : empty;
  }
  const JsonObject& as_object() const {
    static const JsonObject empty;
    return type_ == Type::Object && obj_ ? *obj_ : empty;
  }
  const JsonArray& as_array() const {
    static const JsonArray empty;
    return type_ == Type::Array && arr_ ? *arr_ : empty;
  }

  // Object field access (Null if absent).
  const Json& operator[](const std::string& key) const {
    static const Json null_value;
    if (type_ != Type::Object || !obj_) return null_value;
    auto it = obj_->find(key);
    return it == obj_->end() ? null_value : it->second;
  }
  bool has(const std::string& key) const {
    return type_ == Type::Object && obj_ && obj_->count(key) > 0;
  }

  std::string dump() const {
    std::string out;
    dump_to(out);
    return out;
  }

  static Json parse(const std::string& text);

 private:
  void dump_to(std::string& out) const;
  static void escape_to(const std::string& s, std::string& out);

  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  double double_ = 0.0;
  std::string str_;
  std::shared_ptr<JsonObject> obj_;
  std::shared_ptr<JsonArray> arr_;
};

struct JsonParseError : std::runtime_error {
  explicit JsonParseError(const std::string& msg)
      : std::runtime_error("json parse error: " + msg) {}
};

namespace detail {

class Parser {
 public:
  Parser(const char* begin, const char* end) : p_(begin), end_(end) {}

  Json parse_document() {
    Json v = parse_value();
    skip_ws();
    if (p_ != end_) throw JsonParseError("trailing data");
    return v;
  }

 private:
  void skip_ws() {
    while (p_ != end_ && (*p_ == ' ' || *p_ == '\t' || *p_ == '\n' ||
                          *p_ == '\r'))
      ++p_;
  }
  char peek() {
    skip_ws();
    if (p_ == end_) throw JsonParseError("unexpected end");
    return *p_;
  }
  char next() {
    char c = peek();
    ++p_;
    return c;
  }
  void expect(const char* lit) {
    for (const char* q = lit; *q; ++q) {
      if (p_ == end_ || *p_ != *q) throw JsonParseError("bad literal");
      ++p_;
    }
  }

  Json parse_value() {
    char c = peek();
    switch (c) {
      case '{': return parse_object();
      case '[': return parse_array();
      case '"': return Json(parse_string());
      case 't': expect("true"); return Json(true);
      case 'f': expect("false"); return Json(false);
      case 'n': expect("null"); return Json(nullptr);
      default: return parse_number();
    }
  }

  Json parse_object() {
    next();  // '{'
    JsonObject obj;
    if (peek() == '}') { ++p_; return Json(std::move(obj)); }
    while (true) {
      if (peek() != '"') throw JsonParseError("expected key");
      std::string key = parse_string();
      if (next() != ':') throw JsonParseError("expected ':'");
      obj.emplace(std::move(key), parse_value());
      char c = next();
      if (c == '}') break;
      if (c != ',') throw JsonParseError("expected ',' or '}'");
    }
    return Json(std::move(obj));
  }

  Json parse_array() {
    next();  // '['
    JsonArray arr;
    if (peek() == ']') { ++p_; return Json(std::move(arr)); }
    while (true) {
      arr.push_back(parse_value());
      char c = next();
      if (c == ']') break;
      if (c != ',') throw JsonParseError("expected ',' or ']'");
    }
    return Json(std::move(arr));
  }

  std::string parse_string() {
    if (next() != '"') throw JsonParseError("expected string");
    std::string out;
    while (true) {
      if (p_ == end_) throw JsonParseError("unterminated string");
      unsigned char c = static_cast<unsigned char>(*p_++);
      if (c == '"') break;
      if (c == '\\') {
        if (p_ == end_) throw JsonParseError("bad escape");
        char e = *p_++;
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            unsigned cp = parse_hex4();
            if (cp >= 0xD800 && cp <= 0xDBFF) {  // surrogate pair
              if (p_ + 1 >= end_ || p_[0] != '\\' || p_[1] != 'u')
                throw JsonParseError("lone high surrogate");
              p_ += 2;
              unsigned lo = parse_hex4();
              if (lo < 0xDC00 || lo > 0xDFFF)
                throw JsonParseError("bad low surrogate");
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            append_utf8(out, cp);
            break;
          }
          default: throw JsonParseError("bad escape char");
        }
      } else {
        out += static_cast<char>(c);
      }
    }
    return out;
  }

  unsigned parse_hex4() {
    unsigned v = 0;
    for (int i = 0; i < 4; ++i) {
      if (p_ == end_) throw JsonParseError("bad \\u escape");
      char c = *p_++;
      v <<= 4;
      if (c >= '0' && c <= '9') v |= static_cast<unsigned>(c - '0');
      else if (c >= 'a' && c <= 'f') v |= static_cast<unsigned>(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F') v |= static_cast<unsigned>(c - 'A' + 10);
      else throw JsonParseError("bad hex digit");
    }
    return v;
  }

  static void append_utf8(std::string& out, unsigned cp) {
    if (cp < 0x80) {
      out += static_cast<char>(cp);
    } else if (cp < 0x800) {
      out += static_cast<char>(0xC0 | (cp >> 6));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      out += static_cast<char>(0xE0 | (cp >> 12));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else {
      out += static_cast<char>(0xF0 | (cp >> 18));
      out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    }
  }

  Json parse_number() {
    const char* start = p_;
    if (p_ != end_ && *p_ == '-') ++p_;
    bool is_double = false;
    while (p_ != end_ && ((*p_ >= '0' && *p_ <= '9') || *p_ == '.' ||
                          *p_ == 'e' || *p_ == 'E' || *p_ == '+' ||
                          *p_ == '-')) {
      if (*p_ == '.' || *p_ == 'e' || *p_ == 'E') is_double = true;
      ++p_;
    }
    std::string num(start, static_cast<size_t>(p_ - start));
    if (num.empty() || num == "-") throw JsonParseError("bad number");
    try {
      if (!is_double) return Json(static_cast<int64_t>(std::stoll(num)));
      return Json(std::stod(num));
    } catch (const std::out_of_range&) {
      return Json(std::stod(num));
    } catch (const std::invalid_argument&) {
      throw JsonParseError("bad number " + num);
    }
  }

  const char* p_;
  const char* end_;
};

}  // namespace detail

inline Json Json::parse(const std::string& text) {
  detail::Parser parser(text.data(), text.data() + text.size());
  return parser.parse_document();
}

inline void Json::escape_to(const std::string& s, std::string& out) {
  out += '"';
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += static_cast<char>(c);  // UTF-8 bytes pass through
        }
    }
  }
  out += '"';
}

inline void Json::dump_to(std::string& out) const {
  switch (type_) {
    case Type::Null: out += "null"; break;
    case Type::Bool: out += bool_ ? "true" : "false"; break;
    case Type::Int: out += std::to_string(int_); break;
    case Type::Double: {
      char buf[32];
      std::snprintf(buf, sizeof(buf), "%.17g", double_);
      out += buf;
      break;
    }
    case Type::String: escape_to(str_, out); break;
    case Type::Object: {
      out += '{';
      bool first = true;
      for (const auto& kv : *obj_) {
        if (!first) out += ',';
        first = false;
        escape_to(kv.first, out);
        out += ':';
        kv.second.dump_to(out);
      }
      out += '}';
      break;
    }
    case Type::Array: {
      out += '[';
      bool first = true;
      for (const auto& v : *arr_) {
        if (!first) out += ',';
        first = false;
        v.dump_to(out);
      }
      out += ']';
      break;
    }
  }
}

}  // namespace edl
