// edl-store: native coordination-store daemon.
//
// Speaks the framework's framed-JSON protocol (edl_tpu/coord/wire.py:
// 4-byte magic "EDL1" + u32 big-endian length + JSON body) with the exact
// InMemStore semantics, so the Python StoreClient and every test works
// against either server. Thread-per-connection + a lease sweeper thread
// (TTL expiry generates DELETE events even with no traffic), mirroring
// edl_tpu/coord/server.py. Adds what the Python dev server lacks:
// WAL+snapshot durability (--data-dir).
//
//   edl-store --port 2379 --data-dir /var/lib/edl-store

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <iostream>
#include <thread>

#include "store.hpp"

namespace edl {

constexpr char kMagic[4] = {'E', 'D', 'L', '1'};
constexpr uint32_t kMaxBody = 64 * 1024 * 1024;

static bool recv_exact(int fd, char* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, buf + got, n - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

static bool send_all(int fd, const char* buf, size_t n) {
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

static bool recv_msg(int fd, Json* out) {
  char header[8];
  if (!recv_exact(fd, header, sizeof(header))) return false;
  if (std::memcmp(header, kMagic, 4) != 0) return false;
  uint32_t len;
  std::memcpy(&len, header + 4, 4);
  len = ntohl(len);
  if (len > kMaxBody) return false;
  std::string body(len, '\0');
  if (!recv_exact(fd, body.data(), len)) return false;
  try {
    *out = Json::parse(body);
  } catch (const JsonParseError&) {
    return false;
  }
  return true;
}

static bool send_msg(int fd, const Json& msg) {
  std::string body = msg.dump();
  uint32_t len = htonl(static_cast<uint32_t>(body.size()));
  std::string frame(kMagic, 4);
  frame.append(reinterpret_cast<char*>(&len), 4);
  frame += body;
  return send_all(fd, frame.data(), frame.size());
}

static Json ok(JsonObject fields = {}) {
  fields.emplace("ok", Json(true));
  return Json(std::move(fields));
}

static Json err(const std::string& message) {
  return Json(JsonObject{{"ok", Json(false)}, {"error", Json(message)}});
}

static Json record_json(const Record& rec) {
  return Json(JsonArray{Json(rec.key), Json(rec.value), Json(rec.revision),
                        Json(rec.lease)});
}

static Json dispatch(Store& store, const Json& req) {
  const std::string& op = req["op"].as_string();
  if (op == "put") {
    int64_t rev = store.put(req["key"].as_string(), req["value"].as_string(),
                            req["lease"].as_int());
    return ok({{"revision", Json(rev)}});
  }
  if (op == "get") {
    auto rec = store.get(req["key"].as_string());
    if (!rec) return ok({{"record", Json(nullptr)}});
    return ok({{"record", record_json(*rec)}});
  }
  if (op == "get_prefix") {
    auto [recs, rev] = store.get_prefix(req["prefix"].as_string());
    JsonArray arr;
    for (const auto& rec : recs) arr.push_back(record_json(rec));
    return ok({{"revision", Json(rev)}, {"records", Json(std::move(arr))}});
  }
  if (op == "delete")
    return ok({{"deleted", Json(store.del(req["key"].as_string()))}});
  if (op == "delete_prefix")
    return ok(
        {{"count", Json(store.delete_prefix(req["prefix"].as_string()))}});
  if (op == "put_if_absent") {
    bool won = store.put_if_absent(req["key"].as_string(),
                                   req["value"].as_string(),
                                   req["lease"].as_int());
    return ok({{"won", Json(won)}});
  }
  if (op == "cas") {
    std::optional<std::string> expect;
    if (req.has("expect") && !req["expect"].is_null())
      expect = req["expect"].as_string();
    bool won = store.compare_and_swap(req["key"].as_string(), expect,
                                      req["value"].as_string(),
                                      req["lease"].as_int());
    return ok({{"won", Json(won)}});
  }
  if (op == "lease_grant")
    return ok({{"lease", Json(store.lease_grant(req["ttl"].as_double()))}});
  if (op == "lease_keepalive")
    return ok({{"alive", Json(store.lease_keepalive(req["lease"].as_int()))}});
  if (op == "lease_revoke")
    return ok({{"revoked", Json(store.lease_revoke(req["lease"].as_int()))}});
  if (op == "events_since") {
    auto [events, rev, compacted] = store.events_since(
        req["revision"].as_int(), req["prefix"].as_string());
    JsonArray arr;
    for (const auto& ev : events)
      arr.push_back(Json(JsonArray{Json(ev.type), Json(ev.key),
                                   Json(ev.value), Json(ev.revision)}));
    return ok({{"revision", Json(rev)},
               {"compacted", Json(compacted)},
               {"events", Json(std::move(arr))}});
  }
  if (op == "ping") return ok();
  return err("unknown op '" + op + "'");
}

// The long-lived half of the protocol (edl_tpu/coord/wire.py): ack with
// the creation revision, then push event frames as mutations land, with
// empty heartbeat frames while idle. The heartbeat's failed send is how
// a dead client is detected, so a watcher never outlives its peer by
// more than ~2 heartbeat periods.
static void serve_watch(Store* store, int fd, const Json& req) {
  std::string prefix;
  if (req.has("prefix") && !req["prefix"].is_null())
    prefix = req["prefix"].as_string();
  int64_t start = -1;
  if (req.has("start_revision") && !req["start_revision"].is_null())
    start = req["start_revision"].as_int();
  double heartbeat = 2.0;
  if (req.has("heartbeat") && !req["heartbeat"].is_null()) {
    heartbeat = req["heartbeat"].as_double();
    if (heartbeat <= 0) heartbeat = 2.0;
  }
  auto w = store->watch(prefix, start);
  if (!send_msg(fd, ok({{"watching", Json(true)},
                        {"revision", Json(w->created_revision)}}))) {
    store->watch_cancel(w);
    return;
  }
  while (true) {
    auto batch = w->wait_batch(heartbeat);
    Json msg;
    if (batch) {
      JsonArray arr;
      for (const auto& ev : batch->events)
        arr.push_back(Json(JsonArray{Json(ev.type), Json(ev.key),
                                     Json(ev.value), Json(ev.revision)}));
      msg = ok({{"events", Json(std::move(arr))},
                {"revision", Json(batch->revision)},
                {"compacted", Json(batch->compacted)}});
    } else {
      if (w->cancelled()) break;
      auto rev = store->watch_progress(w);
      if (!rev) continue;  // an event raced in: deliver it next loop
      msg = ok({{"events", Json(JsonArray{})},
                {"revision", Json(*rev)},
                {"compacted", Json(false)}});
    }
    if (!send_msg(fd, msg)) break;
  }
  store->watch_cancel(w);
}

static void serve_connection(Store* store, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  Json req;
  while (recv_msg(fd, &req)) {
    bool is_watch = false;
    try {
      is_watch = req.has("op") && req["op"].as_string() == "watch";
    } catch (const std::exception&) {
      is_watch = false;
    }
    if (is_watch) {
      // the connection becomes a push stream; it ends when the client
      // disconnects (there is no cancel op)
      serve_watch(store, fd, req);
      break;
    }
    Json resp;
    try {
      resp = dispatch(*store, req);
    } catch (const LeaseExpiredError& e) {
      resp = err(std::string("EdlLeaseExpired: ") + e.what());
    } catch (const std::exception& e) {
      resp = err(std::string("InternalError: ") + e.what());
    }
    if (!send_msg(fd, resp)) break;
  }
  ::close(fd);
}

}  // namespace edl

static std::atomic<bool> g_stop{false};
static void on_signal(int) { g_stop = true; }

int main(int argc, char** argv) {
  std::string host = "0.0.0.0";
  int port = 2379;
  std::string data_dir;
  double sweep_interval = 0.5;
  bool fsync = true;
  long snapshot_every = 8192;
  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    auto need = [&](const char* flag) -> std::string {
      if (i + 1 >= argc) {
        std::cerr << flag << " needs a value\n";
        std::exit(2);
      }
      return argv[++i];
    };
    if (arg == "--host") host = need("--host");
    else if (arg == "--port") port = std::stoi(need("--port"));
    else if (arg == "--data-dir") data_dir = need("--data-dir");
    else if (arg == "--sweep-interval")
      sweep_interval = std::stod(need("--sweep-interval"));
    else if (arg == "--no-fsync") fsync = false;
    else if (arg == "--snapshot-every")
      snapshot_every = std::stol(need("--snapshot-every"));
    else {
      std::cerr << "usage: edl-store [--host H] [--port P] [--data-dir D]"
                   " [--sweep-interval S] [--snapshot-every N] [--no-fsync]\n";
      return 2;
    }
  }

  ::signal(SIGINT, on_signal);
  ::signal(SIGTERM, on_signal);
  ::signal(SIGPIPE, SIG_IGN);

  edl::Store store(data_dir, fsync, /*max_events=*/4096,
                   static_cast<size_t>(snapshot_every));

  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    std::cerr << "bad host " << host << "\n";
    return 2;
  }
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    std::cerr << "bind failed: " << std::strerror(errno) << "\n";
    return 1;
  }
  if (::listen(lfd, 128) != 0) {
    std::cerr << "listen failed: " << std::strerror(errno) << "\n";
    return 1;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
  std::cerr << "edl-store listening on " << host << ":"
            << ntohs(addr.sin_port)
            << (data_dir.empty() ? " (ephemeral)" : " (durable: " + data_dir + ")")
            << std::endl;

  std::thread sweeper([&] {
    while (!g_stop) {
      std::this_thread::sleep_for(std::chrono::duration<double>(sweep_interval));
      store.sweep();
    }
  });

  // Accept loop with a timeout so SIGTERM is honored promptly.
  timeval tv{0, 200000};
  ::setsockopt(lfd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  while (!g_stop) {
    int cfd = ::accept(lfd, nullptr, nullptr);
    if (cfd < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) continue;
      break;
    }
    std::thread(edl::serve_connection, &store, cfd).detach();
  }
  ::close(lfd);
  sweeper.join();
  return 0;
}
