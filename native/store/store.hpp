// In-memory coordination store engine with WAL+snapshot durability.
//
// Semantics mirror the Python InMemStore (edl_tpu/coord/store.py) exactly:
// global revision, TTL leases with lazy expiry + sweeper, bounded event
// history with compaction, CAS/put-if-absent primitives. The native daemon
// is the production flavor standing in for the reference's external etcd
// dependency (docker/Dockerfile:28-30) and the Go master's etcd state store
// (pkg/master/etcd_client.go:49-176) — with its own durability (WAL +
// snapshot) so a coordinator restart does not kill the job.

#pragma once

#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <set>
#include <stdexcept>
#include <string>
#include <vector>

#include "json.hpp"

namespace edl {

struct Record {
  std::string key;
  std::string value;
  int64_t revision = 0;
  int64_t lease = 0;
};

struct Event {
  std::string type;  // "PUT" | "DELETE"
  std::string key;
  std::string value;
  int64_t revision = 0;
};

// Server-side typed error; the name prefix crosses the wire and is
// re-hydrated by the Python client (coord/client.py _typed_error).
struct LeaseExpiredError : std::runtime_error {
  explicit LeaseExpiredError(int64_t lease)
      : std::runtime_error("lease " + std::to_string(lease) +
                           " unknown or expired") {}
};

// One watch delivery (mirrors Python WatchBatch): events in revision
// order, the resume anchor, and the compacted flag (history compaction
// or queue overflow -> the consumer must resync via get_prefix).
struct WatchBatch {
  std::vector<Event> events;
  int64_t revision = 0;
  bool compacted = false;
};

// One subscriber's stream state. The Store fans events out on its
// mutation path (Store::emit, store mutex held) into this queue; queue
// state is guarded by the STORE-owned watch mutex (shared across
// watchers on purpose: a per-watcher std::mutex is never
// pthread_mutex_destroy'd by libstdc++, and watcher churn then recycles
// heap addresses with stale TSAN lock state — the shared mutex lives as
// long as the store, so the tsan build stays clean; contention is
// negligible at control-plane rates). Each watcher keeps its own
// condition variable. Lock order: store.mu_ -> watch_mu_, never the
// reverse.
class Watcher {
 public:
  // Blocks up to timeout for the next batch; nullopt on timeout or
  // cancellation. A non-empty pending queue drains as ONE batch.
  std::optional<WatchBatch> wait_batch(double timeout_s);
  bool cancelled();

  int64_t created_revision = 0;  // resume anchor for from-now watches

 private:
  friend class Store;
  void push(const Event& ev);  // caller holds the STORE mutex

  std::mutex* wmu_ = nullptr;  // Store::watch_mu_ (outlives the watcher)
  std::string prefix_;
  size_t max_pending_ = 4096;
  std::condition_variable cv_;
  std::deque<Event> pending_;
  bool compacted_ = false;
  int64_t compacted_rev_ = 0;
  bool cancelled_ = false;
};

class Store {
 public:
  using Clock = std::chrono::steady_clock;

  // data_dir == "" -> ephemeral (no persistence).
  explicit Store(std::string data_dir = "", bool fsync = true,
                 size_t max_events = 4096, size_t snapshot_every = 8192);
  ~Store();

  int64_t put(const std::string& key, const std::string& value,
              int64_t lease);
  std::optional<Record> get(const std::string& key);
  std::pair<std::vector<Record>, int64_t> get_prefix(
      const std::string& prefix);
  bool del(const std::string& key);
  int64_t delete_prefix(const std::string& prefix);
  bool put_if_absent(const std::string& key, const std::string& value,
                     int64_t lease);
  // expect==nullopt -> key must be absent (mirrors Python expect=None).
  bool compare_and_swap(const std::string& key,
                        const std::optional<std::string>& expect,
                        const std::string& value, int64_t lease);
  int64_t lease_grant(double ttl);
  bool lease_keepalive(int64_t lease);
  bool lease_revoke(int64_t lease);
  // returns (events, current_revision, compacted)
  std::tuple<std::vector<Event>, int64_t, bool> events_since(
      int64_t revision, const std::string& prefix);
  void sweep();

  // Subscribe to PUT/DELETE events under prefix. start_revision < 0
  // means "from now"; otherwise history after that revision is queued
  // first (a compacted batch when the window no longer covers it).
  std::shared_ptr<Watcher> watch(const std::string& prefix,
                                 int64_t start_revision);
  void watch_cancel(const std::shared_ptr<Watcher>& w);
  // Heartbeat anchor: the current revision iff the watcher's queue is
  // drained (atomic with emit — both hold mu_), else nullopt.
  std::optional<int64_t> watch_progress(const std::shared_ptr<Watcher>& w);

 private:
  struct Lease {
    int64_t id = 0;
    double ttl = 0.0;
    Clock::time_point deadline;
    std::set<std::string> keys;
  };

  // unlocked internals ------------------------------------------------
  int64_t bump() { return ++revision_; }
  void emit(Event ev);
  void expire();
  void check_lease(int64_t lease);
  void detach(const std::string& key, const Record& rec);
  int64_t put_unlocked(const std::string& key, const std::string& value,
                       int64_t lease, bool log);
  bool del_unlocked(const std::string& key, bool log);
  int64_t lease_grant_unlocked(double ttl, int64_t forced_id, bool log);
  bool lease_revoke_unlocked(int64_t lease, bool log);

  // persistence -------------------------------------------------------
  void wal_append(JsonObject op);
  void load();
  void replay_op(const Json& op);
  void maybe_snapshot();  // caller holds mutex
  void write_snapshot();

  std::mutex mu_;
  std::map<std::string, Record> data_;
  std::map<int64_t, Lease> leases_;
  int64_t revision_ = 0;
  int64_t next_lease_ = 1;
  std::vector<Event> events_;
  size_t max_events_;
  int64_t first_event_rev_ = 1;
  std::vector<std::shared_ptr<Watcher>> watchers_;
  std::mutex watch_mu_;  // guards every watcher's queue state

  std::string data_dir_;
  bool fsync_ = true;
  size_t snapshot_every_;
  size_t wal_lines_ = 0;
  // Monotonic op sequence stamped onto every WAL line and recorded in the
  // snapshot, so replay can skip ops the snapshot already contains (the
  // crash window between snapshot rename and WAL truncation would
  // otherwise re-apply the whole old WAL and re-bump revisions).
  int64_t seq_ = 0;
  std::FILE* wal_ = nullptr;
  bool replaying_ = false;
};

}  // namespace edl
