// In-memory coordination store engine with WAL+snapshot durability.
//
// Semantics mirror the Python InMemStore (edl_tpu/coord/store.py) exactly:
// global revision, TTL leases with lazy expiry + sweeper, bounded event
// history with compaction, CAS/put-if-absent primitives. The native daemon
// is the production flavor standing in for the reference's external etcd
// dependency (docker/Dockerfile:28-30) and the Go master's etcd state store
// (pkg/master/etcd_client.go:49-176) — with its own durability (WAL +
// snapshot) so a coordinator restart does not kill the job.

#pragma once

#include <chrono>
#include <cstdio>
#include <map>
#include <mutex>
#include <optional>
#include <set>
#include <stdexcept>
#include <string>
#include <vector>

#include "json.hpp"

namespace edl {

struct Record {
  std::string key;
  std::string value;
  int64_t revision = 0;
  int64_t lease = 0;
};

struct Event {
  std::string type;  // "PUT" | "DELETE"
  std::string key;
  std::string value;
  int64_t revision = 0;
};

// Server-side typed error; the name prefix crosses the wire and is
// re-hydrated by the Python client (coord/client.py _typed_error).
struct LeaseExpiredError : std::runtime_error {
  explicit LeaseExpiredError(int64_t lease)
      : std::runtime_error("lease " + std::to_string(lease) +
                           " unknown or expired") {}
};

class Store {
 public:
  using Clock = std::chrono::steady_clock;

  // data_dir == "" -> ephemeral (no persistence).
  explicit Store(std::string data_dir = "", bool fsync = true,
                 size_t max_events = 4096, size_t snapshot_every = 8192);
  ~Store();

  int64_t put(const std::string& key, const std::string& value,
              int64_t lease);
  std::optional<Record> get(const std::string& key);
  std::pair<std::vector<Record>, int64_t> get_prefix(
      const std::string& prefix);
  bool del(const std::string& key);
  int64_t delete_prefix(const std::string& prefix);
  bool put_if_absent(const std::string& key, const std::string& value,
                     int64_t lease);
  // expect==nullopt -> key must be absent (mirrors Python expect=None).
  bool compare_and_swap(const std::string& key,
                        const std::optional<std::string>& expect,
                        const std::string& value, int64_t lease);
  int64_t lease_grant(double ttl);
  bool lease_keepalive(int64_t lease);
  bool lease_revoke(int64_t lease);
  // returns (events, current_revision, compacted)
  std::tuple<std::vector<Event>, int64_t, bool> events_since(
      int64_t revision, const std::string& prefix);
  void sweep();

 private:
  struct Lease {
    int64_t id = 0;
    double ttl = 0.0;
    Clock::time_point deadline;
    std::set<std::string> keys;
  };

  // unlocked internals ------------------------------------------------
  int64_t bump() { return ++revision_; }
  void emit(Event ev);
  void expire();
  void check_lease(int64_t lease);
  void detach(const std::string& key, const Record& rec);
  int64_t put_unlocked(const std::string& key, const std::string& value,
                       int64_t lease, bool log);
  bool del_unlocked(const std::string& key, bool log);
  int64_t lease_grant_unlocked(double ttl, int64_t forced_id, bool log);
  bool lease_revoke_unlocked(int64_t lease, bool log);

  // persistence -------------------------------------------------------
  void wal_append(JsonObject op);
  void load();
  void replay_op(const Json& op);
  void maybe_snapshot();  // caller holds mutex
  void write_snapshot();

  std::mutex mu_;
  std::map<std::string, Record> data_;
  std::map<int64_t, Lease> leases_;
  int64_t revision_ = 0;
  int64_t next_lease_ = 1;
  std::vector<Event> events_;
  size_t max_events_;
  int64_t first_event_rev_ = 1;

  std::string data_dir_;
  bool fsync_ = true;
  size_t snapshot_every_;
  size_t wal_lines_ = 0;
  // Monotonic op sequence stamped onto every WAL line and recorded in the
  // snapshot, so replay can skip ops the snapshot already contains (the
  // crash window between snapshot rename and WAL truncation would
  // otherwise re-apply the whole old WAL and re-bump revisions).
  int64_t seq_ = 0;
  std::FILE* wal_ = nullptr;
  bool replaying_ = false;
};

}  // namespace edl
