"""Fleet-scale scheduler plane: simulator, preemptive fair share, spot.

The tournament contract (doc/design_scaler.md, fleet section) is
seed-exact: every number in a tournament table is a pure function of
(trace seed, ladder, policy) — no wall clocks, no unseeded RNGs; the
sim-determinism lint row holds the transitive import closure to that.
These tests pin the contract (sha256 of a fixed tournament), the gang
and capacity invariants, the revocation pass, and the spot-notice
riding that the live `preempt` chaos class drills end-to-end.
"""

import json

import pytest

from edl_tpu.scaler.fleet import (LEGACY, MEASURED, DowntimeLadder,
                                  FleetSim, FleetTrace, run_fleet,
                                  tournament)
from edl_tpu.scaler.fleet_policy import (GreedyRebalancePolicy,
                                         PreemptiveFairSharePolicy,
                                         default_policies)
from edl_tpu.scaler.policy import FairSharePolicy

KW = dict(cooldown_s=15.0, horizon_s=60.0)
SMALL = dict(n_jobs=24, n_pools=5, ticks=100)

# The replay contract, pinned: this exact (trace, ladders, policies)
# grid hashed to this table when the test was written. Any diff means
# a sim or policy behavior change — rev it DELIBERATELY, with the
# change that moved it called out in the commit.
PINNED_GRID_FP = \
    "25ca3a601c0cec424613ed3cb2bdf4cf15b578873c1354b22ef61eee81d1a0b3"


def _pinned_tournament():
    return tournament(
        traces=[FleetTrace.generate("pin", 11, spot_fraction=0.25,
                                    **SMALL)],
        ladders=[MEASURED, LEGACY],
        policies={"fair": lambda: FairSharePolicy(1, **KW),
                  "preemptive":
                      lambda: PreemptiveFairSharePolicy(1, **KW)})


def test_tournament_fingerprint_is_pinned():
    assert _pinned_tournament()["fingerprint"] == PINNED_GRID_FP


def test_tournament_same_seed_identical_tables():
    t1, t2 = _pinned_tournament(), _pinned_tournament()
    assert t1["fingerprint"] == t2["fingerprint"]
    assert t1["rows"] == t2["rows"]


def _job_key(spec):
    # curves hold lambdas (not value-comparable); the curve NAME plus
    # the scheduling facts is the seed-exact surface
    return (spec.job_id, spec.curve.name, spec.tier, spec.gang,
            spec.min_nodes, spec.max_nodes, spec.arrive_tick,
            spec.depart_tick, spec.noise)


def test_trace_generation_is_seed_exact_and_seed_sensitive():
    a = FleetTrace.generate("t", 3, **SMALL)
    b = FleetTrace.generate("t", 3, **SMALL)
    c = FleetTrace.generate("t", 4, **SMALL)
    assert [_job_key(j) for j in a.jobs] == [_job_key(j) for j in b.jobs]
    assert [(p.service, p.tenant, p.slo_p95_ms, p.arrive_tick)
            for p in a.pools] == \
           [(p.service, p.tenant, p.slo_p95_ms, p.arrive_tick)
            for p in b.pools]
    assert a.preemptions == b.preemptions
    assert [_job_key(j) for j in a.jobs] != [_job_key(j) for j in c.jobs]


def test_gang_legal_allocations_throughout():
    trace = FleetTrace.generate("gang", 5, **SMALL)
    sim = FleetSim(trace)
    run_fleet(sim, PreemptiveFairSharePolicy(sim.capacity(), **KW))
    for job in sim.jobs.values():
        nodes = job.sim.nodes
        assert nodes == 0 or (nodes % job.spec.gang == 0
                              and nodes >= job.spec.min_nodes), \
            f"{job.spec.job_id}: {nodes} nodes vs gang {job.spec.gang}"


def test_force_evict_enforces_capacity_and_bills_lost_rows():
    # capacity enforcement is trainer-side: pools are the protected
    # tier and are never force-evicted, so the guarantee after every
    # enforcement pass is allocated <= capacity OR no trainer holds a
    # node (the pool tier alone can exceed a collapsed capacity)
    trace = FleetTrace.generate("cap", 6, **SMALL)
    sim = FleetSim(trace)
    for _ in range(10):
        sim.tick()
    assert sim.allocated() > 0
    sim._capacity = max(0, sim.allocated() - 5)
    sim._force_evict()
    trainer_nodes = sum(j.sim.nodes for j in sim.jobs.values())
    assert sim.allocated() <= sim.capacity() or trainer_nodes == 0
    assert sim.forced_evictions > 0
    # a forced eviction is a HARD stop: stop-resume downtime was
    # billed and the victims' unsealed rows are gone
    assert sim.downtime_paid_s >= sim.ladder.stop_resume_s
    assert sim.resizes_by_kind["stop-resume"] == sim.forced_evictions
    assert sim.lost_rows > 0
    # every surviving allocation is still gang-legal after eviction
    for job in sim.jobs.values():
        nodes = job.sim.nodes
        assert nodes == 0 or (nodes % job.spec.gang == 0
                              and nodes >= job.spec.min_nodes)


def test_revocation_pass_fires_and_is_tier_ordered():
    # a surging fleet: serving pools breach, the preemptive policy
    # must revoke from batch-tier trainers (never online tier first)
    trace = FleetTrace.generate("surge", 7, **SMALL)
    policy = PreemptiveFairSharePolicy(1, **KW)
    run_fleet(FleetSim(trace), policy)
    stats = policy.stats()
    assert stats["revocations"] > 0
    tiers = {r.get("tier", "batch") for r in policy.revocations
             if r["for"] == "slo"}
    # SLO-relief revocations come from the preemptible tiers, lowest
    # first — never the prod tier (capacity enforcement at a spot
    # deadline is the only pass allowed to touch anyone)
    assert tiers <= {"best-effort", "batch"}, tiers


def test_preemptive_beats_fair_share_on_slo_at_goodput():
    trace = FleetTrace.generate("surge", 7, **SMALL)
    base = run_fleet(FleetSim(trace), FairSharePolicy(1, **KW))
    pre = run_fleet(FleetSim(trace),
                    PreemptiveFairSharePolicy(1, **KW))
    assert pre["slo_attainment"] >= base["slo_attainment"]
    assert pre["goodput_rows_per_s"] >= 0.98 * base["goodput_rows_per_s"]


def test_spot_notice_riding_vs_blind_baseline():
    spot = FleetTrace.generate("spot", 9, spot_fraction=0.5, **SMALL)
    blind = run_fleet(FleetSim(spot), FairSharePolicy(1, **KW))
    aware = run_fleet(FleetSim(spot),
                      PreemptiveFairSharePolicy(1, **KW))
    assert blind["forced_evictions"] > 0
    assert aware["forced_evictions"] < blind["forced_evictions"]
    assert aware["notices_ridden"] > blind["notices_ridden"]
    assert aware["lost_rows"] <= blind["lost_rows"]


def test_ladder_classify_and_costs():
    assert MEASURED.classify(4, 2) == "adopt"
    assert MEASURED.classify(2, 4) == "reform"
    assert MEASURED.cost("adopt") < MEASURED.cost("reform") \
        < MEASURED.cost("stop-resume")
    # legacy prices every action like a stop-resume
    assert LEGACY.cost("adopt") == LEGACY.cost("reform") \
        == LEGACY.cost("stop-resume")


def test_ladder_from_artifact(tmp_path):
    art = tmp_path / "bench.json"
    art.write_text(json.dumps({"extras": {
        "elastic_downtime_p2p_s": 0.05,
        "elastic_downtime_multihost_s": 0.2,
        "elastic_downtime_s": 1.5}}))
    ladder = DowntimeLadder.from_artifact(str(art))
    assert ladder is not None
    assert ladder.cost("adopt") == pytest.approx(0.05)
    assert ladder.cost("reform") == pytest.approx(0.2)
    assert ladder.cost("stop-resume") == pytest.approx(1.5)
    assert DowntimeLadder.from_artifact(str(tmp_path / "no")) is None


def test_cheap_ladder_flips_a_policy_race():
    # the point of pricing per action: under legacy costs the greedy
    # rebalancer's constant reshuffling is ruinous; under measured
    # costs it competes — the ladder must be able to change a winner
    policies = default_policies()
    assert {"fair-share", "preemptive-fair-share",
            "greedy-rebalance"} <= set(policies)
    trace = FleetTrace.generate("noisy", 16, noise=0.25, **SMALL)
    greedy_m = run_fleet(FleetSim(trace, ladder=MEASURED),
                         GreedyRebalancePolicy(1, **KW))
    greedy_l = run_fleet(FleetSim(trace, ladder=LEGACY),
                         GreedyRebalancePolicy(1, **KW))
    assert greedy_l["downtime_paid_s"] > greedy_m["downtime_paid_s"]


def test_metrics_shape_and_notice_accounting():
    spot = FleetTrace.generate("spot", 9, spot_fraction=0.5, **SMALL)
    out = run_fleet(FleetSim(spot),
                    PreemptiveFairSharePolicy(1, **KW))
    for key in ("goodput_rows_per_s", "jain_fairness", "slo_attainment",
                "downtime_paid_s", "forced_evictions", "notices_issued",
                "notices_ridden", "lost_rows", "spot_fraction"):
        assert key in out, key
    assert 0.0 < out["jain_fairness"] <= 1.0
    assert 0.0 <= out["slo_attainment"] <= 1.0
    assert out["notices_ridden"] <= out["notices_issued"]
