"""Student training through the distill pipeline: the KD loss consumer
(make_distill_step) fed by DistillReader against a real teacher server.

Done-criterion from the round-1 verdict: "a student training run consuming
it via make_distill_step"."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.data.pipeline import ArraySource, DataLoader
from edl_tpu.distill.reader import DistillReader
from edl_tpu.distill.teacher_server import TeacherServer
from edl_tpu.models.mlp import MLP
from edl_tpu.train.classification import create_state, make_distill_step


def test_student_learns_from_served_teacher():
    # Teacher: fixed-weight MLP; data labeled BY the teacher so the KD
    # objective is learnable.
    teacher = MLP(num_classes=10, hidden=(32,))
    tvars = jax.jit(teacher.init)(jax.random.PRNGKey(42),
                                  jnp.zeros((1, 16)))

    @jax.jit
    def tforward(x):
        return teacher.apply(tvars, x, train=False)

    def predict(feeds):
        return {"teacher_logits":
                np.asarray(tforward(jnp.asarray(feeds["image"])), np.float32)}

    rng = np.random.default_rng(0)
    images = rng.normal(size=(512, 16)).astype(np.float32)
    labels = np.asarray(tforward(jnp.asarray(images))).argmax(1).astype(np.int32)
    loader = DataLoader(ArraySource({"image": images, "label": labels}), 64,
                        seed=0)

    student = MLP(num_classes=10, hidden=(32,))
    state = create_state(student, jax.random.PRNGKey(0), (1, 16),
                         optax.adam(1e-2))
    step = make_distill_step(10, temperature=2.0, hard_weight=0.0)

    with TeacherServer(predict, host="127.0.0.1") as srv:
        accs = []
        for epoch in range(16):
            dr = DistillReader(lambda e=epoch: loader.epoch(e),
                               feeds=["image"], predicts=["teacher_logits"],
                               teachers=[f"127.0.0.1:{srv.port}"],
                               teacher_batch_size=16)
            for batch in dr():
                state, metrics = step(state, batch)
                accs.append(float(metrics["acc1"]))
    # The KD loss has a constant floor (soft-CE includes teacher entropy),
    # so progress is measured as student->teacher agreement: labels here
    # ARE the teacher's argmax.
    first, last = np.mean(accs[:8]), np.mean(accs[-8:])
    assert last > max(0.5, first + 0.2), \
        f"no learning: agreement {first:.3f} -> {last:.3f}"


def test_soft_labels_beat_hard_labels_on_same_budget():
    """The distill QUALITY claim at unit scale (the reference's acc1
    77.1->79.0 story, /root/reference/README.md:70-72): a student given
    the teacher's soft labels must beat the SAME student trained on hard
    labels with an IDENTICAL budget — same subset, same epochs/LR/batch,
    same init seed; only the loss target differs. The teacher knows the
    full training set; the students see a 1/16 subset. Flagship-scale
    analogue: tools/distill_quality_tpu.py -> DISTILL_QUALITY_r5.json."""
    from edl_tpu.train.classification import (make_classification_step,
                                              make_eval_step)

    K, D, SIG = 6, 64, 0.22
    templates = np.random.default_rng(3).normal(size=(K, D)) \
        .astype(np.float32)

    def make(n, seed):
        r = np.random.default_rng(seed)
        y = r.integers(0, K, size=n).astype(np.int32)
        x = (r.normal(size=(n, D)).astype(np.float32)
             + SIG * templates[y])
        return x.reshape(n, 8, 8, 1), y

    x_full, y_full = make(3072, 10)
    x_sub, y_sub = x_full[:192], y_full[:192]
    x_val, y_val = make(512, 99)

    def train(hidden, x, y, apply_step, epochs, seed):
        model = MLP(num_classes=K, hidden=hidden)
        st = create_state(model, jax.random.PRNGKey(seed), (1, 8, 8, 1),
                          optax.adam(1e-2))
        r = np.random.default_rng(0)
        for _ in range(epochs):
            perm = r.permutation(len(y))
            for lo in range(0, len(y) - 64 + 1, 64):
                sel = perm[lo:lo + 64]
                st = apply_step(st, {"image": x[sel], "label": y[sel]})
        return st, model

    ev = make_eval_step()

    def acc(st):
        return float(ev(st, {"image": jnp.asarray(x_val),
                             "label": jnp.asarray(y_val)})["acc1"])

    cstep = make_classification_step(K, donate=False)
    teacher_state, teacher = train((128,), x_full, y_full,
                                   lambda s, b: cstep(s, b)[0],
                                   epochs=20, seed=0)
    teacher_fwd = jax.jit(lambda x: teacher.apply(
        {"params": teacher_state.params}, x, train=False))

    alone_state, _ = train((64,), x_sub, y_sub,
                           lambda s, b: cstep(s, b)[0], epochs=60, seed=1)

    dstep = make_distill_step(K, temperature=2.0, hard_weight=0.0,
                              donate=False)

    def distill_apply(st, batch):
        batch = dict(batch)
        batch["teacher_logits"] = np.asarray(
            teacher_fwd(jnp.asarray(batch["image"])))
        return dstep(st, batch)[0]

    distilled_state, _ = train((64,), x_sub, y_sub, distill_apply,
                               epochs=60, seed=1)

    teacher_acc, alone, distilled = acc(teacher_state), \
        acc(alone_state), acc(distilled_state)
    assert teacher_acc > alone, (teacher_acc, alone)  # worth distilling
    assert distilled > alone + 0.03, \
        f"soft labels did not beat hard: {distilled:.3f} vs {alone:.3f}"
