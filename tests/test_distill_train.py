"""Student training through the distill pipeline: the KD loss consumer
(make_distill_step) fed by DistillReader against a real teacher server.

Done-criterion from the round-1 verdict: "a student training run consuming
it via make_distill_step"."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.data.pipeline import ArraySource, DataLoader
from edl_tpu.distill.reader import DistillReader
from edl_tpu.distill.teacher_server import TeacherServer
from edl_tpu.models.mlp import MLP
from edl_tpu.train.classification import create_state, make_distill_step


def test_student_learns_from_served_teacher():
    # Teacher: fixed-weight MLP; data labeled BY the teacher so the KD
    # objective is learnable.
    teacher = MLP(num_classes=10, hidden=(32,))
    tvars = jax.jit(teacher.init)(jax.random.PRNGKey(42),
                                  jnp.zeros((1, 16)))

    @jax.jit
    def tforward(x):
        return teacher.apply(tvars, x, train=False)

    def predict(feeds):
        return {"teacher_logits":
                np.asarray(tforward(jnp.asarray(feeds["image"])), np.float32)}

    rng = np.random.default_rng(0)
    images = rng.normal(size=(512, 16)).astype(np.float32)
    labels = np.asarray(tforward(jnp.asarray(images))).argmax(1).astype(np.int32)
    loader = DataLoader(ArraySource({"image": images, "label": labels}), 64,
                        seed=0)

    student = MLP(num_classes=10, hidden=(32,))
    state = create_state(student, jax.random.PRNGKey(0), (1, 16),
                         optax.adam(1e-2))
    step = make_distill_step(10, temperature=2.0, hard_weight=0.0)

    with TeacherServer(predict, host="127.0.0.1") as srv:
        accs = []
        for epoch in range(16):
            dr = DistillReader(lambda e=epoch: loader.epoch(e),
                               feeds=["image"], predicts=["teacher_logits"],
                               teachers=[f"127.0.0.1:{srv.port}"],
                               teacher_batch_size=16)
            for batch in dr():
                state, metrics = step(state, batch)
                accs.append(float(metrics["acc1"]))
    # The KD loss has a constant floor (soft-CE includes teacher entropy),
    # so progress is measured as student->teacher agreement: labels here
    # ARE the teacher's argmax.
    first, last = np.mean(accs[:8]), np.mean(accs[-8:])
    assert last > max(0.5, first + 0.2), \
        f"no learning: agreement {first:.3f} -> {last:.3f}"
