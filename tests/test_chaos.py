"""Chaos plane: schedule replay, injectors, integrity, audits.

The full soak is a CI step (``python -m edl_tpu.chaos soak``); these
tests pin the pieces fast: seed-exact schedules, the wire fault hook
and stall deadline at both wire seams, the checkpoint corruptor vs the
crc integrity path (ckpt_io AND the jax CheckpointManager fallback),
the worker's seal/verify/quarantine rig, and the auditor's judgment on
synthetic artifacts.
"""

import json
import os
import random
import socket
import threading

import numpy as np
import pytest

from edl_tpu.chaos.audit import ChaosReport, InvariantAuditor, load_jsonl
from edl_tpu.chaos.faults import (CheckpointCorruptor, WireChaos,
                                  _npy_data_offset)
from edl_tpu.chaos.schedule import FAULT_CLASSES, ChaosSchedule
from edl_tpu.coord import wire
from edl_tpu.data import tensor_wire
from edl_tpu.train import ckpt_io
from edl_tpu.utils.exceptions import EdlCheckpointCorrupt


# -- schedule ---------------------------------------------------------------

def test_schedule_is_seed_exact():
    a = ChaosSchedule.generate(7, 30, tick_s=1.0, pods=3)
    b = ChaosSchedule.generate(7, 30, tick_s=1.0, pods=3)
    assert a.fingerprint() == b.fingerprint()
    assert [e.to_dict() for e in a] == [e.to_dict() for e in b]
    c = ChaosSchedule.generate(8, 30, tick_s=1.0, pods=3)
    assert c.fingerprint() != a.fingerprint()


def test_schedule_head_spans_every_class():
    sched = ChaosSchedule.generate(1, len(FAULT_CLASSES), pods=2)
    assert sched.classes() == set(FAULT_CLASSES)
    # times strictly ordered and non-negative
    times = [e.t for e in sched]
    assert times == sorted(times) and times[0] > 0


# -- wire fault hook --------------------------------------------------------

@pytest.fixture
def sock_pair():
    a, b = socket.socketpair()
    yield a, b
    a.close()
    b.close()


def test_wire_chaos_drop_and_garble(sock_pair):
    a, b = sock_pair
    chaos = WireChaos(seed=1, modes=("drop",), rate=1.0)
    with chaos:
        with pytest.raises(ConnectionError):
            wire.send_msg(a, {"op": "ping"})
    # uninstalled: the same send goes through and garble-on-read only
    # fires while a garbling hook is installed
    wire.send_msg(a, {"op": "ping"})
    assert wire.recv_msg(b) == {"op": "ping"}
    with WireChaos(seed=2, modes=("garble",), rate=1.0):
        wire.send_msg(a, {"op": "ping"})
        with pytest.raises(wire.WireError, match="malformed"):
            wire.recv_msg(b)


def test_tensor_wire_garble_is_typed(sock_pair):
    a, b = sock_pair
    arr = np.arange(16, dtype=np.float32)
    with WireChaos(seed=3, modes=("garble",), rate=1.0):
        tensor_wire.send_tensors(a, {"op": "x"}, {"t": arr})
        # a garbled frame poisons the CONNECTION (consumers drop it and
        # reconnect — exactly what real corruption would force)
        with pytest.raises(tensor_wire.TensorWireError):
            tensor_wire.recv_tensors(b)
    # a fresh connection with the hook gone is clean
    c, d = socket.socketpair()
    try:
        tensor_wire.send_tensors(c, {"op": "x"}, {"t": arr})
        meta, tensors = tensor_wire.recv_tensors(d)
        assert meta == {"op": "x"}
        np.testing.assert_array_equal(tensors["t"], arr)
    finally:
        c.close()
        d.close()


def test_wire_stall_deadline_unwedges_mid_frame(sock_pair, monkeypatch):
    monkeypatch.setenv("EDL_TPU_WIRE_STALL_S", "0.3")
    a, b = sock_pair
    body = json.dumps({"op": "ping"}).encode()
    # half a frame, then silence: the reader must become a typed error,
    # not a wedged thread
    a.sendall(wire.MAGIC + len(body).to_bytes(4, "big") + body[:3])
    with pytest.raises(wire.WireError, match="stalled mid-frame"):
        wire.recv_msg(b)
    # idle socket (no bytes at all) keeps its own timeout policy
    b.settimeout(0.2)
    with pytest.raises(TimeoutError):
        wire.recv_msg(b)


def test_tensor_wire_stall_deadline(sock_pair, monkeypatch):
    monkeypatch.setenv("EDL_TPU_WIRE_STALL_S", "0.3")
    a, b = sock_pair
    a.sendall(tensor_wire.MAGIC + (64).to_bytes(4, "big") + b"{")
    with pytest.raises(tensor_wire.TensorWireError,
                       match="stalled mid-frame"):
        tensor_wire.recv_tensors(b)


# -- checkpoint integrity (ckpt_io + corruptor) -----------------------------

def _seal(tmp_path, arrays: dict) -> str:
    leaves, chunks = [], []
    for i, name in enumerate(sorted(arrays)):
        arr = arrays[name]
        fname = ckpt_io.chunk_name(i, tuple(0 for _ in arr.shape))
        chunks.append((fname, arr))
        leaves.append({"key": name, "shape": list(arr.shape),
                       "dtype": str(arr.dtype),
                       "chunks": [{"offset": [0] * arr.ndim,
                                   "shape": list(arr.shape),
                                   "file": fname}]})
    d = os.path.join(tmp_path, "ckpt-0")
    ckpt_io.write_snapshot(d, {"leaves": leaves, "chunks": chunks,
                               "process_index": 0})
    return d


def _read_all(d):
    merged = ckpt_io.read_merged_index(d)
    files = ckpt_io.ChunkFiles(d, crcs=ckpt_io.checksum_map(merged))
    try:
        return {k: np.array(ckpt_io.read_region(
            files.load, e, tuple(slice(0, s) for s in e["shape"])))
            for k, e in merged.items()}
    finally:
        files.close()


def test_write_snapshot_records_crcs_and_roundtrips(tmp_path):
    arrays = {"w": np.random.default_rng(0).standard_normal((8, 4)),
              "step": np.int64(7).reshape(())}
    d = _seal(str(tmp_path), arrays)
    merged = ckpt_io.read_merged_index(d)
    crcs = ckpt_io.checksum_map(merged)
    assert len(crcs) == 2 and all(isinstance(v, int) for v in crcs.values())
    out = _read_all(d)
    np.testing.assert_array_equal(out["w"], arrays["w"])
    assert out["step"] == 7


def test_bitflip_below_npy_header_is_caught_by_crc_only(tmp_path):
    arrays = {"w": np.ones((32, 8), np.float32)}
    d = _seal(str(tmp_path), arrays)
    rec = CheckpointCorruptor.corrupt(str(tmp_path), random.Random(0),
                                      mode="bitflip")
    assert rec is not None and rec["version"] == 0
    path = os.path.join(d, rec["file"])
    assert rec["offset"] >= _npy_data_offset(path)
    # np.load itself is oblivious — the corruption is silent...
    assert np.load(path).shape == (32, 8)
    # ...and ONLY the crc catches it, as a typed error
    with pytest.raises(EdlCheckpointCorrupt, match="integrity"):
        _read_all(d)


def test_truncated_chunk_is_typed_even_without_crcs(tmp_path):
    arrays = {"w": np.ones((64, 8), np.float32)}
    d = _seal(str(tmp_path), arrays)
    rec = CheckpointCorruptor.corrupt(str(tmp_path), random.Random(0),
                                      mode="truncate")
    merged = ckpt_io.read_merged_index(d)
    files = ckpt_io.ChunkFiles(d, crcs=None)  # no checksums at all
    with pytest.raises(EdlCheckpointCorrupt):
        files.load(rec["file"])
    files.close()


def test_verify_off_lets_bitflip_through(tmp_path, monkeypatch):
    arrays = {"w": np.ones((32, 8), np.float32)}
    d = _seal(str(tmp_path), arrays)
    CheckpointCorruptor.corrupt(str(tmp_path), random.Random(0),
                                mode="bitflip")
    monkeypatch.setenv("EDL_TPU_CKPT_VERIFY", "0")
    out = _read_all(d)  # no raise: garbage sails through...
    assert not np.array_equal(out["w"], arrays["w"])  # ...demonstrably


def test_manager_restore_falls_back_past_corrupt_version(tmp_path):
    jax = pytest.importorskip("jax")
    from edl_tpu.train.checkpoint import CheckpointManager
    from edl_tpu.train.state import TrainStatus

    state = {"w": jax.numpy.arange(128, dtype=jax.numpy.float32),
             "b": jax.numpy.ones((4,), jax.numpy.float32)}
    mgr = CheckpointManager(str(tmp_path), sharded=True, max_to_keep=4)
    mgr.save(state, TrainStatus(epoch=0, step=10))
    state2 = {"w": state["w"] + 1, "b": state["b"] + 1}
    mgr.save(state2, TrainStatus(epoch=0, step=20))
    rec = CheckpointCorruptor.corrupt(str(tmp_path), random.Random(1),
                                      mode="bitflip")
    assert rec["version"] == 1
    target = {"w": np.zeros(128, np.float32), "b": np.zeros(4, np.float32)}
    restored, status = mgr.restore(target)
    # fell back to ckpt-0, loudly, instead of loading garbage
    assert status.step == 10
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(128, dtype=np.float32))
    # an EXPLICIT version surfaces the corruption to the caller
    with pytest.raises(EdlCheckpointCorrupt):
        mgr.restore(target, version=1)


def test_snapshot_host_tree_manifests_carry_crcs():
    pytest.importorskip("jax")
    from edl_tpu.train import sharded_checkpoint as sc
    snap = sc.snapshot_host_tree({"w": np.ones((4, 4), np.float32)})
    crcs = [c.get("crc32") for leaf in snap["leaves"]
            for c in leaf["chunks"]]
    assert crcs and all(isinstance(c, int) for c in crcs)


# -- worker checkpoint rig --------------------------------------------------

def test_worker_rig_detects_quarantines_and_falls_back(tmp_path):
    from edl_tpu.chaos.worker import CheckpointRig, Reporter
    report_path = str(tmp_path / "report.jsonl")
    rig = CheckpointRig(str(tmp_path / "ckpt"), slot=0,
                        report=Reporter(report_path))
    rig.seal()
    rig.seal()
    rec = CheckpointCorruptor.corrupt(str(tmp_path / "ckpt"),
                                      random.Random(0), mode="bitflip")
    assert rec["version"] == 1
    rig.verify_all()
    records = load_jsonl(report_path)
    kinds = [r["kind"] for r in records]
    assert "ckpt_corrupt_detected" in kinds
    fb = next(r for r in records if r["kind"] == "ckpt_fallback")
    assert fb["bad"] == 1 and fb["to"] == 0
    assert rig.versions() == [0]  # quarantined out of the version set
    assert os.path.isdir(tmp_path / "ckpt" / "corrupt-1")
    # seal/restore digests agree for the surviving version
    seals = {r["version"]: r["digest"] for r in records
             if r["kind"] == "seal"}
    rig.verify_all()
    restores = [r for r in load_jsonl(report_path)
                if r["kind"] == "restore"]
    assert restores and all(r["digest"] == seals[r["version"]]
                            for r in restores)


# -- auditor ----------------------------------------------------------------

def _auditor(**overrides):
    base = dict(
        injections=[{"t": 1.0, "fault": "wire", "target": "wire:all",
                     "resolution": {"recovered": True}}],
        worker_reports={}, probe={"acked": {}, "seen": {},
                                  "duplicates": 0, "final_values": []},
        scaler_journal=[], job_resize_log=[], pool_journal=[],
        pool_resize_log=[], drain_log=[], drain_deadline_s=5.0)
    base.update(overrides)
    return InvariantAuditor(**base)


def test_audit_clean_run_is_ok():
    rep = _auditor().audit()
    assert rep.ok and rep.stats["faults_survived"] == 1


def test_audit_catches_lost_and_duplicate_marks():
    rep = _auditor(probe={"acked": {"m1": 5, "m2": 6},
                          "seen": {5: "m1"}, "duplicates": 2,
                          "final_values": []}).audit()
    assert any("duplicate" in b for b in rep.breaches)
    assert any("m2" in b for b in rep.breaches)
    # visible after resync = not lost
    rep2 = _auditor(probe={"acked": {"m2": 6}, "seen": {},
                           "duplicates": 0,
                           "final_values": ["m2"]}).audit()
    assert rep2.ok


def test_audit_catches_journal_mismatch():
    rep = _auditor(
        scaler_journal=[{"action": "resize", "applied": 3}],
        job_resize_log=[{"to": 2, "source": "resize"}]).audit()
    assert any(b.startswith("I2") for b in rep.breaches)
    # fault-injected resizes are injections, not scaler decisions
    rep2 = _auditor(
        scaler_journal=[{"action": "resize", "applied": 3}],
        job_resize_log=[{"to": 4, "source": "fault"},
                        {"to": 3, "source": "resize"}]).audit()
    assert rep2.ok


def test_audit_catches_silent_restore_divergence():
    reports = {"pod0": [
        {"kind": "seal", "version": 1, "digest": "aaa", "ts": 1},
        {"kind": "restore", "version": 1, "digest": "bbb", "ts": 2},
        {"kind": "restore", "version": 1, "digest": "bbb", "ts": 3}]}
    rep = _auditor(worker_reports=reports).audit()
    assert sum(1 for b in rep.breaches if b.startswith("I3")) == 1
    # a DETECTED corruption is the contract working, not a breach
    reports["pod0"].insert(1, {"kind": "ckpt_corrupt_detected",
                               "version": 1, "ts": 1.5})
    assert _auditor(worker_reports=reports).audit().ok


def test_audit_catches_early_hard_kill_and_unresolved_fault():
    rep = _auditor(
        drain_log=[{"endpoint": "t0", "hard_killed": True,
                    "wait_s": 1.0}],
        injections=[{"t": 1.0, "fault": "process-kill",
                     "target": "pod:0", "resolution": None}]).audit()
    assert any(b.startswith("I4") for b in rep.breaches)
    assert any(b.startswith("I5") for b in rep.breaches)
    # a hard kill AT the deadline is the documented fallback
    rep2 = _auditor(drain_log=[{"endpoint": "t0", "hard_killed": True,
                                "wait_s": 5.0}]).audit()
    assert rep2.ok


def test_chaos_report_roundtrip():
    rep = ChaosReport()
    rep.breach("x")
    doc = rep.to_dict()
    assert doc["ok"] is False and doc["breaches"] == ["x"]


# -- I6: reform ladders + the branch-anomaly pin (r20) ----------------------


def test_audit_reform_paired_start_done_is_ok():
    reports = {"pod0": [
        {"kind": "reform_start", "generation": 3, "ts": 1.0},
        {"kind": "restore", "version": 2, "digest": "d", "ts": 1.1},
        {"kind": "reform_done", "generation": 3, "result": "in-place",
         "restore": "peers", "ts": 1.2},
        {"kind": "reform_start", "generation": 4, "ts": 2.0},
        {"kind": "reform_done", "generation": 4,
         "result": "stop-resume", "ts": 2.3},
        {"kind": "registered", "rank": 0, "ts": 2.5},
    ]}
    rep = _auditor(worker_reports=reports).audit()
    assert rep.ok, rep.breaches
    assert rep.stats["reforms_started"] == 2
    assert rep.stats["reforms_completed"] == 2
    assert rep.stats["reform_downgrades"] == 1


def test_audit_reform_wedge_is_a_breach():
    # the worker moved on (published util, consumed watches) with the
    # ladder still open: neither completed nor degraded = torn world
    reports = {"pod0": [
        {"kind": "reform_start", "generation": 3, "ts": 1.0},
        {"kind": "watch", "revisions": [9], "ts": 1.5},
    ]}
    rep = _auditor(worker_reports=reports).audit()
    assert any("I6" in b and "wedged" in b or "torn" in b
               for b in rep.breaches), rep.breaches


def test_audit_reform_death_midladder_is_not_a_wedge():
    # a SIGKILL mid-ladder shows as a fresh incarnation ("started"):
    # that is a process fault the respawn covers, not an I6 breach
    reports = {"pod0": [
        {"kind": "reform_start", "generation": 3, "ts": 1.0},
        {"kind": "started", "pod_id": "pod0-1", "ts": 2.0},
        {"kind": "registered", "rank": 0, "ts": 2.2},
    ]}
    rep = _auditor(worker_reports=reports).audit()
    assert rep.ok, rep.breaches
    assert rep.stats["reforms_died_midladder"] == 1


def test_audit_reform_unknown_result_is_a_breach():
    reports = {"pod0": [
        {"kind": "reform_start", "generation": 3, "ts": 1.0},
        {"kind": "reform_done", "generation": 3, "result": "wedged?",
         "ts": 1.2},
    ]}
    rep = _auditor(worker_reports=reports).audit()
    assert any("I6" in b and "unknown result" in b for b in rep.breaches)


def _preempt_injection(**over):
    inj = {"t": 3.0, "fault": "preempt", "target": "pod:0", "slot": 0,
           "duration": 2.5, "wall": 100.0, "kill_wall": 102.5,
           "pod_id": "pod0-0", "resolution": {"recovered": True}}
    inj.update(over)
    return inj


def test_audit_preempt_ridden_is_ok():
    # notice at wall=100, deadline 102.5: the worker seals ckpt-3,
    # reports preempt_ready inside the window, dies at the deadline,
    # and the respawned incarnation restores ckpt-3 — I7 rides
    reports = {"pod0": [
        {"kind": "seal", "version": 3, "digest": "d3", "ts": 100.4},
        {"kind": "preempt_ready", "margin_s": 2.0, "ts": 100.5},
        {"kind": "started", "pod_id": "pod0-1", "ts": 103.0},
        {"kind": "restore", "version": 3, "digest": "d3", "ts": 103.2},
    ]}
    rep = _auditor(injections=[_preempt_injection()],
                   worker_reports=reports).audit()
    assert rep.ok, rep.breaches
    assert rep.stats["preempts_noticed"] == 1
    assert rep.stats["preempts_ridden"] == 1


def test_audit_preempt_unhonored_notice_is_breach():
    # hard kill landed with no preempt_ready in the window: the
    # worker ignored the notice (the --weaken-preempt control)
    reports = {"pod0": [
        {"kind": "seal", "version": 3, "digest": "d3", "ts": 100.4},
        {"kind": "started", "pod_id": "pod0-1", "ts": 103.0},
        {"kind": "restore", "version": 3, "digest": "d3", "ts": 103.2},
    ]}
    rep = _auditor(injections=[_preempt_injection()],
                   worker_reports=reports).audit()
    assert any("I7" in b and "not honored" in b for b in rep.breaches), \
        rep.breaches
    assert rep.stats["preempts_ridden"] == 0


def test_audit_preempt_early_kill_is_breach():
    # killed 2s before the 2.5s deadline: the window is a contract
    reports = {"pod0": [
        {"kind": "seal", "version": 3, "digest": "d3", "ts": 100.3},
        {"kind": "preempt_ready", "margin_s": 2.1, "ts": 100.4},
        {"kind": "restore", "version": 3, "digest": "d3", "ts": 101.5},
    ]}
    rep = _auditor(injections=[_preempt_injection(kill_wall=100.5)],
                   worker_reports=reports).audit()
    assert any("I7" in b and "BEFORE the notice deadline" in b
               for b in rep.breaches), rep.breaches


def test_audit_preempt_lost_progress_is_breach():
    # the respawn restored ckpt-2 < the preempt seal ckpt-3: acked
    # progress lost across a NOTICED preemption
    reports = {"pod0": [
        {"kind": "seal", "version": 3, "digest": "d3", "ts": 100.3},
        {"kind": "preempt_ready", "margin_s": 2.1, "ts": 100.4},
        {"kind": "started", "pod_id": "pod0-1", "ts": 103.0},
        {"kind": "restore", "version": 2, "digest": "d2", "ts": 103.2},
    ]}
    rep = _auditor(injections=[_preempt_injection()],
                   worker_reports=reports).audit()
    assert any("I7" in b and "acked" in b for b in rep.breaches), \
        rep.breaches
    # ...and a donated seal that nobody ever read is equally a breach
    reports2 = {"pod0": [
        {"kind": "seal", "version": 3, "digest": "d3", "ts": 100.3},
        {"kind": "preempt_ready", "margin_s": 2.1, "ts": 100.4},
    ]}
    rep2 = _auditor(injections=[_preempt_injection()],
                    worker_reports=reports2).audit()
    assert any("I7" in b and "unread" in b for b in rep2.breaches), \
        rep2.breaches


def test_audit_preempt_skipped_and_retired_are_not_breaches():
    # a notice skipped by the injector (dead pod / already noticed)
    # is not audited; a pod retired by a shrink after donating needs
    # no restore — its seal was adopted by the survivors
    rep = _auditor(injections=[_preempt_injection(
        resolution={"skipped": "pod0 dead at notice"})]).audit()
    assert rep.ok, rep.breaches
    assert rep.stats["preempts_noticed"] == 0
    reports = {"pod0": [
        {"kind": "seal", "version": 3, "digest": "d3", "ts": 100.3},
        {"kind": "preempt_ready", "margin_s": 2.1, "ts": 100.4},
    ]}
    rep2 = _auditor(
        injections=[_preempt_injection(
            resolution={"recovered": True,
                        "detail": "slot retired by resize"})],
        worker_reports=reports).audit()
    assert rep2.ok, rep2.breaches
    assert rep2.stats["preempts_ridden"] == 1


def test_audit_branch_anomalies_pinned_to_zero():
    # commit-gated fan-out (r20) turned the documented r18 stat into a
    # hard invariant: any observed uncommitted suffix fails the soak
    probe = {"acked": {}, "seen": {}, "duplicates": 0,
             "final_values": [], "branch_anomalies": 1}
    rep = _auditor(probe=probe).audit()
    assert any("branch anomalies" in b for b in rep.breaches)
    assert rep.stats["branch_anomalies"] == 1


def test_schedule_reform_class_compounds_a_resize():
    sched = ChaosSchedule.generate(5, 3 * len(FAULT_CLASSES), pods=2)
    reforms = [e for e in sched if e.fault == "reform"]
    assert reforms, "reform class missing from a full-mix schedule"
    for e in reforms:
        assert e.params["sub"] in ("kill-donor", "pause-survivor",
                                   "partition-store")
        assert e.target == "job"
