"""Typed config env overlay."""

import dataclasses

from edl_tpu.utils.config import describe, field, from_env


@dataclasses.dataclass
class Cfg:
    name: str = field("job", env="T_NAME")
    nproc: int = field(1, env="T_NPROC")
    lr: float = field(0.1, env="T_LR")
    debug: bool = field(False, env="T_DEBUG")
    port: int | None = field(None, env="T_PORT")
    hosts: list[str] = field(env="T_HOSTS", default_factory=list)


def test_defaults():
    cfg = from_env(Cfg)
    assert cfg == Cfg()


def test_env_overlay(monkeypatch):
    monkeypatch.setenv("T_NPROC", "8")
    monkeypatch.setenv("T_DEBUG", "true")
    monkeypatch.setenv("T_PORT", "2379")
    monkeypatch.setenv("T_HOSTS", "a:1, b:2")
    cfg = from_env(Cfg)
    assert cfg.nproc == 8
    assert cfg.debug is True
    assert cfg.port == 2379 and isinstance(cfg.port, int)  # PEP 604 Optional
    assert cfg.hosts == ["a:1", "b:2"]


def test_overrides_beat_env(monkeypatch):
    monkeypatch.setenv("T_LR", "0.5")
    cfg = from_env(Cfg, lr=0.9)
    assert cfg.lr == 0.9


def test_describe():
    out = describe(Cfg())
    assert "nproc: 1" in out and "Cfg" in out
