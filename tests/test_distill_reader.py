"""DistillReader pipeline: ordering, exactly-once, teacher churn.

The analogue of the reference's distill_reader_test.py (whole multiprocess
pipeline with fake teachers, zero network, SURVEY.md §4) plus real-TCP
teacher-server integration and a mid-epoch teacher kill.
"""

import threading
import time

import numpy as np
import pytest

from edl_tpu.distill.reader import (DistillReader, EdlDistillError,
                                    _NopTeacherClient)
from edl_tpu.distill.teacher_server import (Batcher, TeacherClient,
                                            TeacherServer, pad_to_bucket)


def make_batches(n_batches=6, rows=32, feat=8, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        out.append({
            "image": rng.normal(size=(rows, feat)).astype(np.float32),
            "label": rng.integers(0, 10, size=(rows,)).astype(np.int32),
        })
    return out


def ref_logits(images: np.ndarray) -> np.ndarray:
    # Deterministic per-row function: catches slicing/reassembly bugs by
    # value, not just by shape.
    return np.stack([images.sum(axis=1), images.max(axis=1)], axis=1)


class _FnTeacherClient:
    """In-process fake teacher computing ref_logits (value-checkable)."""

    def __init__(self, endpoint, delay=0.0, fail_every=0):
        self.endpoint = endpoint
        self.delay = delay
        self.fail_every = fail_every
        self.calls = 0

    def predict(self, feeds):
        self.calls += 1
        if self.fail_every and self.calls % self.fail_every == 0:
            raise ConnectionError("injected teacher failure")
        if self.delay:
            time.sleep(self.delay)
        return {"teacher_logits": ref_logits(feeds["image"])}

    def close(self):
        pass


def check_epoch(batches, got):
    assert len(got) == len(batches)                       # D4
    for want, out in zip(batches, got):                   # D2 order
        np.testing.assert_array_equal(out["image"], want["image"])
        np.testing.assert_array_equal(out["label"], want["label"])
        np.testing.assert_allclose(out["teacher_logits"],
                                   ref_logits(want["image"]), rtol=1e-6)


def test_nop_pipeline_shapes_and_order():
    batches = make_batches()
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["p"], teachers=["t0", "t1"],
                       teacher_batch_size=8,
                       client_factory=lambda ep: _NopTeacherClient(ep, ("p",)))
    got = list(dr())
    assert len(got) == len(batches)
    for want, out in zip(batches, got):
        np.testing.assert_array_equal(out["image"], want["image"])
        assert out["p"].shape == (32, 1)


def test_values_reassembled_in_row_order():
    batches = make_batches(n_batches=5, rows=30)  # ragged tail slice (30/8)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"], teachers=["t0", "t1", "t2"],
                       teacher_batch_size=8,
                       client_factory=lambda ep: _FnTeacherClient(ep))
    check_epoch(batches, list(dr()))


def test_out_of_order_replies_still_ordered():
    # Teachers with very different latencies force out-of-order completion.
    delays = {"fast": 0.0, "slow": 0.03}
    batches = make_batches(n_batches=8, rows=16)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"],
                       teachers=["fast", "slow"], teacher_batch_size=4,
                       client_factory=lambda ep: _FnTeacherClient(
                           ep, delay=delays[ep]))
    check_epoch(batches, list(dr()))


def test_multiple_epochs_reuse():
    batches = make_batches(n_batches=3)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"], teachers=["t0"],
                       teacher_batch_size=16,
                       client_factory=lambda ep: _FnTeacherClient(ep))
    for _ in range(3):   # reference runs 300 epochs; 3 exercise re-init
        check_epoch(batches, list(dr()))


def test_flaky_teacher_requeues_nothing_lost():
    # One teacher fails every 3rd call: its in-flight task must be re-queued
    # and re-served (D3) with no losses/duplicates; worker is recreated by
    # the manage thread, so the epoch still completes.
    batches = make_batches(n_batches=10, rows=16)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"],
                       teachers=["good", "flaky"], teacher_batch_size=4,
                       manage_interval=0.05,
                       client_factory=lambda ep: _FnTeacherClient(
                           ep, fail_every=3 if ep == "flaky" else 0))
    check_epoch(batches, list(dr()))


def test_all_teachers_failing_raises():
    batches = make_batches(n_batches=2, rows=8)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"], teachers=["bad"],
                       teacher_batch_size=4, max_retries=2,
                       manage_interval=0.05,
                       client_factory=lambda ep: _FnTeacherClient(
                           ep, fail_every=1))
    with pytest.raises(EdlDistillError):
        list(dr())


def test_connect_dead_teacher_trips_deadman_fast():
    """A fixed teacher whose CONNECT always fails used to hang the epoch
    forever (worker popped + re-created every manage tick, queued tasks
    never served); the deadman must raise instead, naming the teacher
    (invariant D6). The reference hangs in exactly this case."""
    def refuse(ep):
        raise ConnectionRefusedError(f"connection to {ep} refused")

    batches = make_batches(n_batches=2, rows=8)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"],
                       teachers=["203.0.113.9:9999"],
                       teacher_batch_size=4, manage_interval=0.05,
                       deadman_timeout=1.0, client_factory=refuse)
    t0 = time.monotonic()
    with pytest.raises(EdlDistillError) as ei:
        list(dr())
    assert time.monotonic() - t0 < 10.0  # fails fast, not an epoch hang
    assert "deadman" in str(ei.value)
    assert "203.0.113.9:9999" in str(ei.value)  # names the dead teacher


def test_slow_but_live_teacher_does_not_trip_deadman():
    """A connected teacher serving slowly must never be mistaken for a
    dead pool, even with per-predict latency above deadman_timeout."""
    batches = make_batches(n_batches=2, rows=8)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"], teachers=["slow"],
                       teacher_batch_size=8, manage_interval=0.05,
                       deadman_timeout=0.2,
                       client_factory=lambda ep: _FnTeacherClient(
                           ep, delay=0.3))
    check_epoch(batches, list(dr()))


def test_empty_discovery_pool_waits_instead_of_tripping():
    """Scale-to-zero: a discovery pool with NO teachers (and none
    known-dead) must keep waiting past deadman_timeout — the balancer
    will reassign. A teacher arriving later completes the epoch."""
    batches = make_batches(n_batches=2, rows=8)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"],
                       discovery="unused:0", service="svc",
                       teacher_batch_size=4, manage_interval=0.05,
                       deadman_timeout=0.3,
                       client_factory=lambda ep: _FnTeacherClient(ep))
    start = time.monotonic()
    # empty pool well past deadman_timeout, then one teacher appears
    dr._get_servers = lambda: ([] if time.monotonic() - start < 1.0
                               else ["t0"])
    check_epoch(batches, list(dr()))


def test_missing_feeds_rejected_up_front():
    dr = DistillReader(lambda: iter([]), predicts=["p"], teachers=["t"])
    with pytest.raises(EdlDistillError, match="feeds"):
        next(iter(dr()))


def test_deadman_recovers_when_teacher_arrives_late():
    """Teachers that appear BEFORE the deadman window elapses rescue the
    epoch: the clock resets on any live connected worker."""
    batches = make_batches(n_batches=3, rows=8)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"], teachers=["late"],
                       teacher_batch_size=4, manage_interval=0.05,
                       deadman_timeout=2.0)
    start = time.monotonic()

    def late_factory(ep):
        if time.monotonic() - start < 0.5:
            raise ConnectionRefusedError("not up yet")
        return _FnTeacherClient(ep)

    dr._client_factory = late_factory
    check_epoch(batches, list(dr()))


class TestSlotFormats:
    """The reference's three positional reader formats
    (distill_reader.py:313-374): each must round-trip the ORIGINAL
    structure value-exactly with predict slots appended."""

    N_BATCHES, BATCH, FEAT = 4, 10, 6

    def _samples(self):
        rng = np.random.default_rng(7)
        return [(rng.normal(size=(self.FEAT,)).astype(np.float32),
                 np.int64(i % 3))
                for i in range(self.N_BATCHES * self.BATCH)]

    def _reader(self, ins=("image", None), **kw):
        kw.setdefault("teachers", ["t0", "t1"])
        kw.setdefault("teacher_batch_size", 4)
        kw.setdefault("client_factory", lambda ep: _FnTeacherClient(ep))
        return DistillReader(ins=list(ins),
                             predicts=["teacher_logits"], **kw)

    def test_sample_generator_roundtrip(self):
        samples = self._samples()
        dr = self._reader().set_sample_generator(lambda: iter(samples))
        got = list(dr())
        assert len(got) == len(samples)
        for (img, label), out in zip(samples, got):
            assert len(out) == 3  # (img, label, prediction)
            np.testing.assert_array_equal(out[0], img)
            np.testing.assert_array_equal(out[1], label)
            np.testing.assert_allclose(
                out[2], ref_logits(img[None])[0], rtol=1e-6)

    def test_sample_list_generator_roundtrip(self):
        samples = self._samples()
        lists = [samples[i * self.BATCH:(i + 1) * self.BATCH]
                 for i in range(self.N_BATCHES)]
        dr = self._reader().set_sample_list_generator(lambda: iter(lists))
        got = list(dr())
        assert len(got) == self.N_BATCHES
        for want, out in zip(lists, got):
            assert len(out) == self.BATCH  # original list length restored
            for (img, label), sample in zip(want, out):
                np.testing.assert_array_equal(sample[0], img)
                np.testing.assert_array_equal(sample[1], label)
                np.testing.assert_allclose(
                    sample[2], ref_logits(img[None])[0], rtol=1e-6)

    def test_batch_generator_roundtrip(self):
        rng = np.random.default_rng(8)
        batches = [(rng.normal(size=(self.BATCH, self.FEAT))
                    .astype(np.float32),
                    rng.integers(0, 3, size=(self.BATCH, 1)))
                   for _ in range(self.N_BATCHES)]
        dr = self._reader().set_batch_generator(lambda: iter(batches))
        got = list(dr())
        assert len(got) == self.N_BATCHES
        for (img, label), out in zip(batches, got):
            assert len(out) == 3
            np.testing.assert_array_equal(out[0], img)  # value-exact
            np.testing.assert_array_equal(out[1], label)
            np.testing.assert_allclose(out[2], ref_logits(img),
                                       rtol=1e-6)

    def test_reference_construction_order(self):
        """The reference flow: construct with ins, bind teachers by
        comma string AFTER, then set the reader — and reuse epochs."""
        samples = self._samples()[:8]
        dr = DistillReader(ins=["image", None],
                           predicts=["teacher_logits"],
                           teacher_batch_size=4,
                           client_factory=lambda ep: _FnTeacherClient(ep))
        dr.set_fixed_teacher("t0,t1")
        dr.set_sample_generator(lambda: iter(samples))
        for _ in range(2):
            assert len(list(dr())) == len(samples)

    def test_slot_reader_requires_ins(self):
        dr = DistillReader(predicts=["p"], teachers=["t0"])
        with pytest.raises(EdlDistillError, match="ins"):
            dr.set_sample_generator(lambda: iter([]))

    def test_double_set_reader_rejected(self):
        dr = self._reader()
        dr.set_sample_generator(lambda: iter([]))
        with pytest.raises(EdlDistillError, match="already"):
            dr.set_batch_generator(lambda: iter([]))

    def test_no_reader_raises(self):
        dr = DistillReader(ins=["x"], predicts=["p"], teachers=["t"])
        with pytest.raises(EdlDistillError, match="reader"):
            next(iter(dr()))

    def test_reader_demo_example_all_formats(self):
        """The reference reader-demo equivalent runs end-to-end over a
        real TCP teacher (example/distill/reader_demo/
        distill_reader_demo.py)."""
        from edl_tpu.examples.reader_demo import main
        assert main(["--format", "all"]) == 0


def test_pad_to_bucket():
    assert pad_to_bucket(1, (1, 2, 4)) == 1
    assert pad_to_bucket(3, (1, 2, 4)) == 4
    assert pad_to_bucket(9, (1, 2, 4)) == 9   # beyond largest: exact


def test_batcher_coalesces_concurrent_requests():
    calls = []

    def predict(feeds):
        calls.append(feeds["x"].shape[0])
        return {"y": feeds["x"] * 2.0}

    b = Batcher(predict, max_batch=64, max_wait=0.05).start()
    try:
        reqs = []

        def submit(i):
            reqs.append((i, b.submit(
                {"x": np.full((4, 2), float(i), np.float32)})))

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(4)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        for i, req in reqs:
            req.done.wait(5.0)
            assert req.error is None
            np.testing.assert_allclose(req.result["y"],
                                       np.full((4, 2), 2.0 * i))
        # All 16 rows within max_wait: fewer device calls than requests,
        # each a bucket size.
        assert sum(calls) >= 16
        assert len(calls) < 4 or all(c in (4, 8, 16) for c in calls)
    finally:
        b.stop()


@pytest.fixture
def real_teacher():
    def predict(feeds):
        return {"teacher_logits": ref_logits(feeds["image"])}
    with TeacherServer(predict, host="127.0.0.1", max_wait=0.001) as srv:
        yield f"127.0.0.1:{srv.port}"


def test_teacher_client_roundtrip(real_teacher):
    client = TeacherClient(real_teacher)
    try:
        assert client.ping()
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = client.predict({"image": x})
        np.testing.assert_allclose(out["teacher_logits"], ref_logits(x))
    finally:
        client.close()


def test_reader_against_real_server(real_teacher):
    batches = make_batches(n_batches=4, rows=24)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"], teachers=[real_teacher],
                       teacher_batch_size=8)
    check_epoch(batches, list(dr()))


def test_teacher_killed_mid_epoch_survivor_finishes():
    def predict(feeds):
        time.sleep(0.01)   # slow enough that the kill lands mid-epoch
        return {"teacher_logits": ref_logits(feeds["image"])}

    s1 = TeacherServer(predict, host="127.0.0.1").start()
    s2 = TeacherServer(predict, host="127.0.0.1").start()
    eps = [f"127.0.0.1:{s1.port}", f"127.0.0.1:{s2.port}"]
    batches = make_batches(n_batches=12, rows=16)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"], teachers=eps,
                       teacher_batch_size=4, manage_interval=0.05)
    got = []
    it = dr()
    try:
        got.append(next(it))
        s2.stop()          # kill one teacher mid-epoch
        for item in it:
            got.append(item)
        check_epoch(batches, got)
    finally:
        s1.stop()
