"""Watch relay tier (coord/relay.py): per-prefix upstream coalescing,
the revision-resume fence, relay-death-equals-restart, and the
range-batched frame path the relay rides on.

The relay's contract is the store watch contract, unchanged through an
extra hop — so these tests drive it with the same StoreClient the
fleet uses, over real sockets.
"""

import time

import pytest

from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.relay import RelayServer, WatchRelay
from edl_tpu.coord.server import StoreServer
from edl_tpu.coord.store import InMemStore


@pytest.fixture
def server():
    with StoreServer(port=0, host="127.0.0.1") as srv:
        yield srv


@pytest.fixture
def relay_server(server):
    rs = RelayServer(f"127.0.0.1:{server.port}", port=0,
                     host="127.0.0.1").start()
    yield rs
    rs.stop()


@pytest.fixture
def store(server):
    c = StoreClient(f"127.0.0.1:{server.port}", timeout=5.0)
    yield c
    c.close()


def _drain(watch, want, timeout=10.0):
    evs = []
    deadline = time.monotonic() + timeout
    while len(evs) < want and time.monotonic() < deadline:
        batch = watch.get(timeout=0.25)
        if batch is not None:
            evs.extend(batch.events)
    return evs


def test_relay_fans_out_one_upstream_per_prefix(server, relay_server,
                                                store):
    relay_ep = f"127.0.0.1:{relay_server.port}"
    downs = [StoreClient(relay_ep, timeout=5.0) for _ in range(3)]
    w1 = downs[0].watch("/a/", via_relay=False)
    w2 = downs[1].watch("/a/", via_relay=False)
    wb = downs[2].watch("/b/", via_relay=False)
    try:
        revs = [store.put(f"/a/{i}", str(i)) for i in range(5)]
        store.put("/b/x", "y")
        assert [e.revision for e in _drain(w1, 5)] == revs
        assert [e.revision for e in _drain(w2, 5)] == revs
        got_b = _drain(wb, 1)
        assert [e.key for e in got_b] == ["/b/x"]
        stats = relay_server.relay.stats()
        # 3 downstream streams, but only 2 distinct prefixes upstream
        assert stats["relay_upstream_streams"] == 2
        assert stats["relay_downstreams"] == 3
    finally:
        for w in (w1, w2, wb):
            w.cancel()
        for d in downs:
            d.close()


def test_relay_min_revision_fence(server, relay_server, store):
    relay_ep = f"127.0.0.1:{relay_server.port}"
    revs = [store.put(f"/f/{i}", str(i)) for i in range(8)]
    c = StoreClient(relay_ep, timeout=5.0)
    # resume mid-history: nothing at or below the anchor re-delivers
    w = c.watch("/f/", start_revision=revs[4], via_relay=False)
    try:
        got = _drain(w, 3)
        assert [e.revision for e in got] == revs[5:]
    finally:
        w.cancel()
        c.close()


def test_relay_stale_resume_answers_compacted(server, store):
    for i in range(6):
        store.put(f"/s/{i}", str(i))
    relay = WatchRelay(f"127.0.0.1:{server.port}", buffer=64)
    try:
        anchored = relay.attach("/s/")          # pins the stream window
        stale = relay.attach("/s/", start_revision=0)
        batch = stale.get(timeout=5.0)
        assert batch is not None and batch.compacted
        anchored.cancel()
        stale.cancel()
    finally:
        relay.close()


def test_relay_restart_resumes_zero_lost_zero_dup(server, store):
    ep = f"127.0.0.1:{server.port}"
    rs = RelayServer(ep, port=0, host="127.0.0.1").start()
    relay_ep = f"127.0.0.1:{rs.port}"
    c = StoreClient(relay_ep, timeout=5.0)
    w = c.watch("/k/", via_relay=False)
    try:
        revs1 = [store.put(f"/k/{i}", str(i)) for i in range(4)]
        assert [e.revision for e in _drain(w, 4)] == revs1
        port = rs.port
        rs.stop()                       # the relay dies mid-stream
        revs2 = [store.put(f"/k/{i}", str(i)) for i in range(4, 8)]
        rs = RelayServer(ep, port=port, host="127.0.0.1").start()
        # downstream reconnects + resumes by revision: exactly the gap
        got = _drain(w, 4, timeout=20.0)
        assert [e.revision for e in got] == revs2
    finally:
        w.cancel()
        c.close()
        rs.stop()


def test_relay_endpoints_env_reroutes_watch(server, relay_server, store,
                                            monkeypatch):
    monkeypatch.setenv("EDL_TPU_RELAY_ENDPOINTS",
                       f"127.0.0.1:{relay_server.port}")
    c = StoreClient(f"127.0.0.1:{server.port}", timeout=5.0)
    w = c.watch("/r/")  # via_relay defaults True -> dials the relay
    try:
        rev = store.put("/r/x", "1")
        got = _drain(w, 1)
        assert [e.revision for e in got] == [rev]
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if relay_server.relay.stats()["relay_downstreams"] == 1:
                break
            time.sleep(0.05)
        assert relay_server.relay.stats()["relay_downstreams"] == 1
    finally:
        w.cancel()
        c.close()


def test_watch_replay_is_one_batched_frame():
    # Range-batched frames: a watch replay carries every queued event
    # under ONE revision header, not one frame per event.
    s = InMemStore()
    revs = [s.put(f"/b/{i:02d}", str(i)) for i in range(10)]
    w = s.watch("/b/", start_revision=0)
    batch = w.get(timeout=1.0)
    assert batch is not None
    assert len(batch.events) == 10
    assert batch.revision == revs[-1]
    w.cancel()


def test_delete_prefix_emits_one_batch():
    s = InMemStore()
    for i in range(6):
        s.put(f"/d/{i}", str(i))
    w = s.watch("/d/")
    s.delete_prefix("/d/")
    batch = w.get(timeout=1.0)
    assert batch is not None
    assert len(batch.events) == 6
    assert all(e.type == "DELETE" for e in batch.events)
    w.cancel()


def test_client_watch_reconnect_backs_off(server):
    # A dead endpoint must be re-dialed through the jittered backoff,
    # not hammered: count dials over a fixed window.
    c = StoreClient(f"127.0.0.1:{server.port}", timeout=1.0,
                    connect_retries=1, retry_interval=0.01)
    w = c.watch("/bo/", heartbeat=5.0)
    dials = []
    orig = c._connect_once

    def spy(*a, **k):
        dials.append(time.monotonic())
        return orig(*a, **k)

    c._connect_once = spy
    server.stop()
    time.sleep(1.5)
    w.cancel()
    c.close()
    # a hammer loop would dial hundreds of times in 1.5s; backoff keeps
    # it to a handful
    assert 1 <= len(dials) <= 20
