"""Sharded checkpoint save/restore with resharding across mesh shapes.

The capability the reference delegates to fleet save/load_check_point
(doc/fault_tolerance.md, train_with_fleet.py:422-434) scaled to sharded
states: per-shard chunk files + index, restore re-places per the TARGET
state's shardings — including onto a different device count. The headline
assertion: an fsdp x tp transformer state saved on 8 devices restores onto
a 4-device mesh with an identical next-step loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.models.transformer import (Transformer, TransformerConfig,
                                        lm_loss_fn)
from edl_tpu.parallel import mesh as mesh_lib, sharding as shd
from edl_tpu.train import sharded_checkpoint as sc
from edl_tpu.train.checkpoint import CheckpointManager
from edl_tpu.train.state import TrainState, TrainStatus
from edl_tpu.train.step import make_train_step

VOCAB = 64


def build_state(mesh):
    cfg = TransformerConfig(vocab_size=VOCAB, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_len=64,
                            dtype=jnp.float32, mesh=mesh)
    model = Transformer(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, VOCAB)
    variables = shd.init_sharded(
        lambda: model.init(jax.random.PRNGKey(0), toks, train=False), mesh)
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"],
                              tx=optax.adamw(1e-3))
    return state, toks


def make_batch(mesh, toks):
    # default batch axes = whichever of dp/fsdp the mesh actually has
    return {"tokens": mesh_lib.shard_batch(mesh, toks)}


def host_tree(t):
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), t)


def trees_equal(a, b):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def mesh_8():
    return mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 2, "fsdp": 2,
                                                 "tp": 2}))


def mesh_4():
    return mesh_lib.make_mesh(mesh_lib.MeshSpec({"fsdp": 2, "tp": 2}),
                              n_devices=4)


def test_roundtrip_same_mesh_exact(tmp_path):
    mesh = mesh_8()
    state, toks = build_state(mesh)
    step = make_train_step(lm_loss_fn, donate=False)
    state, _ = step(state, make_batch(mesh, toks))

    sc.save_sharded(str(tmp_path / "s"), state)
    fresh, _ = build_state(mesh)
    restored = sc.restore_sharded(str(tmp_path / "s"), fresh)
    trees_equal(host_tree(state), host_tree(restored))


def test_reshard_8_to_4_devices_identical_next_loss(tmp_path):
    # Train one step on the 8-device world, checkpoint, ...
    big = mesh_8()
    state8, toks = build_state(big)
    step = make_train_step(lm_loss_fn, donate=False)
    state8, _ = step(state8, make_batch(big, toks))
    sc.save_sharded(str(tmp_path / "s"), state8)

    # ... continue a step on the 8-device world, ...
    cont8, m8 = step(state8, make_batch(big, toks))
    loss8 = float(m8["loss"])

    # ... and separately restore onto a 4-device fsdp x tp mesh and take
    # the same next step there.
    small = mesh_4()
    fresh4, _ = build_state(small)
    emb = fresh4.params["tok_embed"]["embedding"]
    assert emb.sharding.mesh.devices.size == 4  # genuinely resharded
    restored4 = sc.restore_sharded(str(tmp_path / "s"), fresh4)
    trees_equal(host_tree(state8), host_tree(restored4))
    step4 = make_train_step(lm_loss_fn, donate=False)
    _, m4 = step4(restored4, make_batch(small, toks))
    assert abs(float(m4["loss"]) - loss8) < 1e-5


def test_reshard_4_to_8_grow(tmp_path):
    small = mesh_4()
    state4, toks = build_state(small)
    sc.save_sharded(str(tmp_path / "s"), state4)
    big = mesh_8()
    fresh8, _ = build_state(big)
    restored8 = sc.restore_sharded(str(tmp_path / "s"), fresh8)
    trees_equal(host_tree(state4), host_tree(restored8))


def test_manager_sharded_versioning_and_autodetect(tmp_path):
    mesh = mesh_8()
    state, toks = build_state(mesh)
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2, sharded=True)
    v0 = mgr.save(state, TrainStatus(epoch=0, step=10))
    assert v0 == 0
    step = make_train_step(lm_loss_fn, donate=False)
    state2, _ = step(state, make_batch(mesh, toks))
    assert mgr.save(state2, TrainStatus(epoch=1, step=20)) == 1

    # Restore latest onto a DIFFERENT mesh via the manager (auto-detected
    # sharded format re-places per the target's shardings).
    small = mesh_4()
    fresh, _ = build_state(small)
    restored, status = mgr.restore(fresh)
    assert status.epoch == 1 and status.step == 20
    trees_equal(host_tree(state2), host_tree(restored))

    # GC respects max_to_keep over sharded dirs too.
    state3, _ = step(state2, make_batch(mesh, toks))
    assert mgr.save(state3, TrainStatus(epoch=2, step=30)) == 2
    assert mgr.versions() == [1, 2]


def test_replicated_and_sharded_formats_coexist(tmp_path):
    # An elastic restart can move from a replicated checkpoint to sharded
    # saves in the same directory: restore auto-detects per version.
    mesh = mesh_8()
    state, toks = build_state(mesh)
    rep = CheckpointManager(str(tmp_path))
    assert rep.save(state, TrainStatus(epoch=0, step=1)) == 0
    shd_mgr = CheckpointManager(str(tmp_path), sharded=True)
    step = make_train_step(lm_loss_fn, donate=False)
    state2, _ = step(state, make_batch(mesh, toks))
    assert shd_mgr.save(state2, TrainStatus(epoch=1, step=2)) == 1

    fresh, _ = build_state(mesh)
    restored, status = shd_mgr.restore(fresh, version=1)
    assert status.epoch == 1
    trees_equal(host_tree(state2), host_tree(restored))
    restored0, status0 = shd_mgr.restore(fresh, version=0)
    assert status0.epoch == 0


def test_incomplete_coverage_raises(tmp_path):
    mesh = mesh_8()
    state, _ = build_state(mesh)
    sc.save_sharded(str(tmp_path / "s"), state)
    # Delete one chunk file: restore must fail loudly, not zero-fill.
    # Since the integrity plane (r18) a missing chunk is the TYPED
    # EdlCheckpointCorrupt — what lets CheckpointManager.restore fall
    # back to the previous sealed version instead of dying raw.
    import os

    from edl_tpu.utils.exceptions import EdlCheckpointCorrupt
    chunks = [n for n in os.listdir(tmp_path / "s") if n.endswith(".npy")]
    biggest = max(chunks, key=lambda n: os.path.getsize(tmp_path / "s" / n))
    os.unlink(tmp_path / "s" / biggest)
    fresh, _ = build_state(mesh)
    with pytest.raises((ValueError, EdlCheckpointCorrupt)):
        sc.restore_sharded(str(tmp_path / "s"), fresh)
