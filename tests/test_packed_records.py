"""Packed pre-decoded records + device-side augmentation
(data/packed_records.py + ops/augment.py).

The acceptance surface of the zero-host-transform feed path:
- pack -> read round-trip is byte-identical to the source,
- random access is O(1) over an mmap (construction reads only the
  header; sample pages are read lazily at access time),
- `PackedSource` flows through every DataLoader execution mode with
  bit-identical streams, including the emitted device-augment seed,
- the mid-epoch replay cursor works from a packed file,
- host and device augmentation are equivalent at the transform level
  (same decisions -> bit-identical pixels) and each pipeline is exactly
  replayable from its seed,
- truncated/corrupt files raise a clear error instead of garbage
  batches,
- `place_array` skips the defensive copy for owned arrays and keeps it
  for borrowed ring views; no per-sample Python loop runs for a packed
  batch.
"""

import contextlib
import os
import signal

import numpy as np
import pytest

from edl_tpu.data import packed_records as pr
from edl_tpu.data.pipeline import (DataLoader, FileSource, materialize_batch,
                                   pop_augment_seed, prefetch_to_device,
                                   random_crop, random_flip_lr)
from edl_tpu.utils.exceptions import EdlDataError


@contextlib.contextmanager
def deadline(seconds: int):
    """Fail (don't hang) if the block exceeds `seconds`."""

    def fire(signum, frame):
        raise TimeoutError(f"test exceeded {seconds}s deadline")

    old = signal.signal(signal.SIGALRM, fire)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def copy_stream(it):
    return [{k: np.array(v) for k, v in b.items()} for b in it]


def assert_streams_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert set(x) == set(y)
        for k in x:
            np.testing.assert_array_equal(x[k], y[k])


@pytest.fixture(scope="module")
def npz_dataset(tmp_path_factory):
    d = tmp_path_factory.mktemp("packed_npz")
    rng = np.random.default_rng(0)
    files = []
    for i in range(3):
        path = str(d / f"train-{i}.npz")
        np.savez(path,
                 image=rng.integers(0, 256, size=(20, 10, 10, 3),
                                    dtype=np.uint8),
                 label=rng.integers(0, 10, size=20).astype(np.int32))
        files.append(path)
    return files


@pytest.fixture(scope="module")
def packed_file(npz_dataset, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("packed") / "train.pack")
    pr.pack_npz(npz_dataset, out, batch_size=13)
    return out


class TestFormat:
    def test_pack_roundtrip_byte_equality(self, npz_dataset, packed_file):
        src = pr.PackedSource(packed_file)
        ref = FileSource(npz_dataset)
        assert len(src) == len(ref) == 60
        idx = np.random.default_rng(1).permutation(60)
        got, want = src.batch(idx), ref.batch(idx)
        assert set(got) == set(want)
        for k in want:
            np.testing.assert_array_equal(got[k], want[k])
            assert got[k].dtype == want[k].dtype

    def test_jpeg_pack_matches_eval_decode(self, tmp_path):
        """Packing a jpeg list bakes exactly the deterministic eval
        geometry (decode -> resize-short -> center-crop)."""
        cv2 = pytest.importorskip("cv2")  # noqa: F841 — decode engine
        from edl_tpu.data.image import (JpegFileListSource,
                                        eval_image_transform,
                                        make_synthetic_jpeg_dataset)
        d = str(tmp_path)
        list_file = make_synthetic_jpeg_dataset(d, 10, classes=5,
                                                hw=(40, 50), seed=3)
        out = os.path.join(d, "t.pack")
        pr.pack_jpeg_list(list_file, d, out, size=16, batch_size=4)
        src = pr.PackedSource(out)
        assert src.fields["image"] == ((16, 16, 3), np.dtype(np.uint8))
        jsrc = JpegFileListSource(list_file, root=d)
        t = eval_image_transform(16, short=16 * 8 // 7)
        idx = np.array([7, 0, 3])
        want = [t(s, None) for s in jsrc.samples(idx)]
        got = src.batch(idx)
        for i in range(len(idx)):
            np.testing.assert_array_equal(got["image"][i], want[i]["image"])
            assert got["label"][i] == want[i]["label"]

    def test_random_access_is_lazy_mmap(self, tmp_path):
        """Construction reads only the header: bytes rewritten on disk
        AFTER the source is built are what a later batch() returns —
        proof the sample tables are faulted in lazily, not preloaded."""
        out = str(tmp_path / "t.pack")
        img = np.arange(8 * 4 * 4 * 3, dtype=np.uint8).reshape(8, 4, 4, 3)
        with pr.PackedWriter(out, 8, {"image": ((4, 4, 3), np.uint8),
                                      "label": ((), np.int32)}) as w:
            w.add({"image": img, "label": np.arange(8, dtype=np.int32)})
        src = pr.PackedSource(out)
        header = pr.read_header(out)
        row = int(np.prod(img.shape[1:]))
        with open(out, "r+b") as f:  # rewrite row 5 behind the mmap
            f.seek(header["fields"]["image"]["offset"] + 5 * row)
            f.write(b"\xff" * row)
        got = src.batch(np.array([5, 2]))
        np.testing.assert_array_equal(
            got["image"][0], np.full((4, 4, 3), 255, np.uint8))
        np.testing.assert_array_equal(got["image"][1], img[2])

    def test_batch_owns_contiguous_memory(self, packed_file):
        b = pr.PackedSource(packed_file).batch(np.array([3, 1, 59]))
        for v in b.values():
            assert v.flags["OWNDATA"] and v.flags["C_CONTIGUOUS"]
            assert type(v) is np.ndarray  # not a memmap subclass

    def test_empty_index_gives_empty_typed_batch(self, packed_file):
        b = pr.PackedSource(packed_file).batch(np.array([], dtype=np.intp))
        assert b["image"].shape == (0, 10, 10, 3)
        assert b["label"].dtype == np.int32


class TestCorruption:
    def test_not_a_packed_file(self, tmp_path):
        p = str(tmp_path / "x.pack")
        with open(p, "wb") as f:
            f.write(b"definitely not a packed file")
        with pytest.raises(EdlDataError, match="bad magic"):
            pr.PackedSource(p)

    def test_truncated_tables(self, packed_file, tmp_path):
        p = str(tmp_path / "trunc.pack")
        with open(packed_file, "rb") as f, open(p, "wb") as g:
            g.write(f.read(pr.HEADER_BLOCK + 64))
        with pytest.raises(EdlDataError, match="truncated"):
            pr.PackedSource(p)

    def test_corrupt_header_json(self, packed_file, tmp_path):
        p = str(tmp_path / "garbage.pack")
        with open(packed_file, "rb") as f:
            blob = bytearray(f.read())
        blob[16:32] = b"\xff" * 16  # stomp the JSON
        with open(p, "wb") as g:
            g.write(blob)
        with pytest.raises(EdlDataError, match="corrupt"):
            pr.PackedSource(p)

    def test_writer_refuses_underfill_and_overflow(self, tmp_path):
        fields = {"x": ((2,), np.float32)}
        w = pr.PackedWriter(str(tmp_path / "a.pack"), 4, fields)
        w.add({"x": np.zeros((2, 2), np.float32)})
        with pytest.raises(EdlDataError, match="closed at 2/4"):
            w.close()
        w = pr.PackedWriter(str(tmp_path / "b.pack"), 2, fields)
        with pytest.raises(EdlDataError, match="overflow"):
            w.add({"x": np.zeros((3, 2), np.float32)})
        with pytest.raises(EdlDataError, match="fixed-stride"):
            w.add({"x": np.zeros((1, 5), np.float32)})


class TestLoaderModes:
    """One packed file, three executors, one bit-identical stream —
    including the emitted device-augment seed."""

    @pytest.mark.parametrize("mode", [dict(decode_threads=2),
                                      dict(num_workers=1),
                                      dict(num_workers=2)])
    def test_stream_bit_identical_with_seeds(self, packed_file, mode):
        src = pr.PackedSource(packed_file)
        with deadline(120):
            with DataLoader(src, 8, seed=5, emit_batch_seed=True) as ld:
                want = copy_stream(ld.epoch(2))
            with DataLoader(src, 8, seed=5, emit_batch_seed=True,
                            **mode) as ld:
                got = copy_stream(ld.epoch(2))
        assert "augment_seed" in want[0]
        assert want[0]["augment_seed"].shape == ()
        assert want[0]["augment_seed"].dtype == np.uint32
        assert_streams_equal(want, got)

    def test_mid_epoch_cursor_replays_remainder(self, packed_file):
        src = pr.PackedSource(packed_file)
        with deadline(120):
            with DataLoader(src, 8, seed=9, emit_batch_seed=True) as ld:
                full = copy_stream(ld.epoch(3))
            with DataLoader(src, 8, seed=9, emit_batch_seed=True,
                            num_workers=2) as ld:
                it = ld.epoch(3)
                head = [{k: np.array(v) for k, v in next(it).items()}
                        for _ in range(2)]
                it.close()  # stop-resume abandons mid-epoch
                tail = copy_stream(ld.epoch(3, start_step=2))
        assert_streams_equal(head + tail, full)

    def test_seed_stream_matches_host_transform_draws(self, packed_file):
        """The emitted seed IS the draw host transforms would consume:
        same generator, same step order (truncated to uint32)."""
        src = pr.PackedSource(packed_file)
        with DataLoader(src, 8, seed=4, emit_batch_seed=True) as ld:
            seeds = [int(b["augment_seed"]) for b in ld.epoch(1)]
            descs = ld._epoch_descriptors(1, 0)
        assert seeds == [b & 0xFFFFFFFF for _, _, _, b in descs]

    def test_no_per_sample_python_loop_for_packed(self, packed_file):
        """materialize_batch must pass a packed batch straight through:
        one source.batch() call, no samples()/np.stack re-collation."""
        src = pr.PackedSource(packed_file)
        calls = []

        class Spy:
            def __len__(self):
                return len(src)

            def batch(self, idx):
                calls.append(len(idx))
                return src.batch(idx)
            # no .samples attribute: a per-sample path would AttributeError

        out = materialize_batch(Spy(), np.arange(8), [], [], None,
                                12345, emit_seed=True)
        assert calls == [8]
        assert out["image"].flags["OWNDATA"]
        assert int(out["augment_seed"]) == 12345


class TestFileSourceFastPath:
    def test_single_shard_batch_identical_to_multi(self, npz_dataset):
        src = FileSource(npz_dataset)
        within = np.array([5, 19, 0, 7])       # all inside shard 0
        across = np.array([5, 25, 41, 0])      # spans all three shards
        with np.load(npz_dataset[0]) as z:
            ref0 = {k: z[k][within] for k in z.files}
        got = src.batch(within)
        for k in ref0:
            np.testing.assert_array_equal(got[k], ref0[k])
        # the general path still collates correctly across shards
        whole = FileSource(npz_dataset).batch(np.arange(60))
        got2 = src.batch(across)
        for k in whole:
            np.testing.assert_array_equal(got2[k], whole[k][across])


class TestDeviceAugment:
    def test_host_device_transform_equivalence(self, npz_dataset):
        """The contract the design doc documents: given the SAME
        decisions, host transforms and device appliers produce
        bit-identical pixels — and host_crop_flip_decisions replays
        exactly the host pipeline's per-step draws."""
        from edl_tpu.ops.augment import (apply_crop, apply_flip_lr,
                                         host_crop_flip_decisions)
        batch = FileSource(npz_dataset).batch(np.arange(12))
        bseed = 987654321
        brng = np.random.default_rng(bseed)
        want = random_crop(random_flip_lr(batch, brng), brng, pad=4)
        flip, ys, xs = host_crop_flip_decisions(bseed, 12, pad=4)
        got = np.asarray(apply_crop(
            apply_flip_lr(batch["image"], flip), ys, xs, 4))
        np.testing.assert_array_equal(got, want["image"])
        assert got.dtype == want["image"].dtype

    def test_jitted_augment_deterministic_and_seed_sensitive(
            self, npz_dataset):
        import jax.numpy as jnp
        from edl_tpu.ops.augment import make_device_augment
        batch = FileSource(npz_dataset).batch(np.arange(8))
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        aug = make_device_augment(pad=3, normalize="imagenet", base_seed=1)
        a = np.asarray(aug(jb, np.uint32(7))["image"])
        b = np.asarray(aug(jb, np.uint32(7))["image"])
        c = np.asarray(aug(jb, np.uint32(8))["image"])
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)
        assert a.dtype == np.float32  # normalized on device
        # labels ride through untouched
        np.testing.assert_array_equal(
            np.asarray(aug(jb, np.uint32(7))["label"]), batch["label"])

    def test_normalize_matches_step_constants(self):
        import jax.numpy as jnp
        from edl_tpu.ops.augment import normalize_image
        from edl_tpu.train import classification as cls
        x = jnp.asarray(np.random.default_rng(0).integers(
            0, 256, size=(2, 4, 4, 3), dtype=np.uint8))
        np.testing.assert_allclose(
            np.asarray(normalize_image(x, "imagenet")),
            np.asarray(cls.normalize_image(x, "imagenet")))
        assert cls.IMAGENET_MEAN[0] == pytest.approx(0.485 * 255.0)

    def test_prefetch_to_device_pops_seed_and_augments(self, packed_file):
        import jax
        from edl_tpu.ops.augment import make_device_augment
        from edl_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 8}))
        sharding = mesh_lib.data_sharding(mesh)
        src = pr.PackedSource(packed_file)
        aug = make_device_augment(pad=2, normalize="unit", base_seed=0)
        with deadline(120), DataLoader(src, 8, seed=1,
                                       emit_batch_seed=True,
                                       num_workers=2) as ld:
            got = [jax.device_get(b) for b in prefetch_to_device(
                ld.epoch(0), sharding, augment=aug)]
        assert got and all("augment_seed" not in b for b in got)
        assert all(b["image"].dtype == np.float32 for b in got)
        # replay: the same (seed, epoch) stream augments identically
        with deadline(120), DataLoader(src, 8, seed=1,
                                       emit_batch_seed=True) as ld:
            again = [jax.device_get(b) for b in prefetch_to_device(
                ld.epoch(0), sharding, augment=aug)]
        assert_streams_equal(got, again)

    def test_wiring_errors_are_clear(self, packed_file):
        from edl_tpu.ops.augment import make_device_augment
        src = pr.PackedSource(packed_file)
        aug = make_device_augment()
        with pytest.raises(EdlDataError, match="no device augment fn"):
            pop_augment_seed({"image": np.zeros(1),
                              "augment_seed": np.uint32(0)}, None)
        with pytest.raises(EdlDataError, match="emit_batch_seed"):
            pop_augment_seed({"image": np.zeros(1)}, aug)
        # and through the real pipeline: seed emitted, no augment given
        from edl_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 8}))
        with DataLoader(src, 8, seed=1, emit_batch_seed=True) as ld:
            it = prefetch_to_device(ld.epoch(0),
                                    mesh_lib.data_sharding(mesh))
            with pytest.raises(EdlDataError, match="augment_seed"):
                next(it)
            it.close()


class TestPlacementCopyPolicy:
    """Satellite: place_array skips the defensive copy for arrays that
    OWN their memory and keeps it for borrowed (ring-view) arrays."""

    def _capture_device_put(self, monkeypatch):
        from edl_tpu.data import pipeline
        seen = []

        def fake_put(x, sharding):
            seen.append(x)
            return x

        monkeypatch.setattr(pipeline.jax, "device_put", fake_put)
        return seen

    def test_owned_array_places_without_copy(self, monkeypatch,
                                             packed_file):
        from edl_tpu.data.pipeline import place_array
        seen = self._capture_device_put(monkeypatch)
        batch = pr.PackedSource(packed_file).batch(np.arange(4))
        place_array(batch["image"], sharding=None)
        assert seen[0] is batch["image"]  # the very same buffer

    def test_borrowed_view_is_copied_before_placement(self, monkeypatch):
        from edl_tpu.data import shm_ring
        from edl_tpu.data.pipeline import place_array
        seen = self._capture_device_put(monkeypatch)
        batch = {"x": np.arange(32, dtype=np.uint8)}
        ring = shm_ring.ShmRing(shm_ring.batch_nbytes(batch), 1)
        try:
            meta = shm_ring.write_batch(ring.buf(0), batch)
            view = shm_ring.read_batch(ring.buf(0), meta)["x"]
            assert not view.flags["OWNDATA"]
            place_array(view, sharding=None)
            assert seen[0] is not view
            assert seen[0].flags["OWNDATA"]
            np.testing.assert_array_equal(seen[0], batch["x"])
            del view
        finally:
            ring.close()


class TestTrainLoopIntegration:
    def test_loop_drives_packed_device_augment_end_to_end(
            self, packed_file):
        import jax
        from edl_tpu.ops.augment import make_device_augment
        from edl_tpu.parallel import mesh as mesh_lib
        from edl_tpu.train.loop import LoopConfig, TrainLoop
        mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 8}))
        src = pr.PackedSource(packed_file)
        aug = make_device_augment(pad=2, normalize="unit", base_seed=3)
        seen = []

        def step(state, batch):
            assert "augment_seed" not in batch
            seen.append(jax.device_get(batch["image"]))
            return state, {"loss": 0.0}

        ld = DataLoader(src, 8, seed=2, emit_batch_seed=True,
                        num_workers=1)
        with deadline(120):
            loop = TrainLoop(step, state=0, mesh=mesh,
                             config=LoopConfig(num_epochs=1,
                                               log_every_steps=1000),
                             augment_fn=aug)
            loop.run(ld)
        assert len(seen) == ld.steps_per_epoch()
        assert all(b.dtype == np.float32 for b in seen)
        assert ld._mp_pool is None  # run()'s finally closed the loader
