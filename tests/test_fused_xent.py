"""Streamed-vocab cross-entropy (ops/fused_xent.py) vs the dense oracle."""

import flax
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.ops.fused_xent import _chunks, streamed_lm_xent


def _data(n=64, d=32, v=512, seed=0):
    key = jax.random.PRNGKey(seed)
    h = jax.random.normal(key, (n, d))
    k = jax.random.normal(jax.random.fold_in(key, 1), (d, v)) * 0.1
    t = jax.random.randint(jax.random.fold_in(key, 2), (n,), 0, v)
    return h, k, t


def _oracle(h, k, t):
    logp = jax.nn.log_softmax(h @ k)
    return -jnp.mean(jnp.take_along_axis(logp, t[:, None], axis=-1))


class TestStreamedXent:
    @pytest.mark.parametrize("chunk", [128, 256, 512, 8192])
    def test_loss_matches_oracle(self, chunk):
        h, k, t = _data()
        np.testing.assert_allclose(float(streamed_lm_xent(h, k, t, chunk)),
                                   float(_oracle(h, k, t)), atol=2e-6)

    def test_grads_match_oracle(self):
        h, k, t = _data()
        go = jax.grad(_oracle, argnums=(0, 1))(h, k, t)
        gf = jax.grad(lambda h, k: streamed_lm_xent(h, k, t, 128),
                      argnums=(0, 1))(h, k)
        np.testing.assert_allclose(gf[0], go[0], atol=1e-6)
        np.testing.assert_allclose(gf[1], go[1], atol=1e-6)

    def test_extreme_logits_stable(self):
        """Running-max rescale must survive large-magnitude logits."""
        h, k, t = _data()
        k = k * 100.0
        got = float(streamed_lm_xent(h, k, t, 128))
        want = float(_oracle(h, k, t))
        assert np.isfinite(got)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_bf16_inputs(self):
        h, k, t = _data()
        loss = streamed_lm_xent(h.astype(jnp.bfloat16),
                                k.astype(jnp.bfloat16), t, 128)
        np.testing.assert_allclose(float(loss), float(_oracle(h, k, t)),
                                   atol=0.05)

    def test_chunk_fit(self):
        assert _chunks(512, 8192) == 512
        assert _chunks(32768, 8192) == 8192
        assert _chunks(1000, 8192) == 1000  # fits in one chunk
        assert _chunks(50257, 8192) == 8192  # odd LARGE vocab still chunks

    @pytest.mark.parametrize("v,chunk", [(50257 % 997 + 500, 128),  # odd
                                         (1000, 300), (513, 128)])
    def test_ragged_vocab_matches_oracle(self, v, chunk):
        """Vocabs with no chunk divisor: clamped slices + masking keep
        exactness (regression: fallback used to materialize full V)."""
        h, k, t = _data(v=v)
        np.testing.assert_allclose(float(streamed_lm_xent(h, k, t, chunk)),
                                   float(_oracle(h, k, t)), atol=2e-6)
        go = jax.grad(_oracle, argnums=(0, 1))(h, k, t)
        gf = jax.grad(lambda h, k: streamed_lm_xent(h, k, t, chunk),
                      argnums=(0, 1))(h, k)
        np.testing.assert_allclose(gf[0], go[0], atol=1e-6)
        np.testing.assert_allclose(gf[1], go[1], atol=1e-6)

    def test_jits(self):
        h, k, t = _data()
        f = jax.jit(lambda h, k, t: streamed_lm_xent(h, k, t, 128))
        assert np.isfinite(float(f(h, k, t)))


class TestFusedLmLoss:
    def _state_and_batch(self):
        from edl_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
        from edl_tpu.train.state import TrainState

        cfg = TransformerConfig(vocab_size=512, d_model=64, n_heads=4,
                                n_layers=2, d_ff=128, max_len=64,
                                dtype=jnp.float32)
        model = Transformer(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (4, 64), 0, 512)
        variables = flax.linen.meta.unbox(
            model.init(jax.random.PRNGKey(0), toks, train=False))
        state = TrainState.create(apply_fn=model.apply,
                                  params=variables["params"],
                                  tx=optax.sgd(0.1))
        return state, {"tokens": toks}

    def test_matches_dense_loss_and_grads(self):
        from edl_tpu.models.transformer import lm_loss_fn, lm_loss_fused

        state, batch = self._state_and_batch()
        l1, _ = lm_loss_fn(state, state.params, batch)
        l2, _ = lm_loss_fused(state, state.params, batch, chunk=128)
        np.testing.assert_allclose(float(l1), float(l2), atol=5e-6)
        g1 = jax.grad(lambda p: lm_loss_fn(state, p, batch)[0])(state.params)
        g2 = jax.grad(lambda p: lm_loss_fused(state, p, batch,
                                              chunk=128)[0])(state.params)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(a, b, atol=2e-6)

    @pytest.mark.slow
    def test_lm_train_fused_flag(self, tmp_path):
        from edl_tpu.examples.lm_train import main

        rc = main(["--data-dir", str(tmp_path / "d"), "--make-synthetic",
                   "1", "--rows-per-file", "128", "--vocab", "128",
                   "--seq-len", "32", "--d-model", "32", "--n-heads", "2",
                   "--n-layers", "1", "--d-ff", "64", "--epochs", "1",
                   "--batch-size", "16", "--fused-loss"])
        assert rc == 0
