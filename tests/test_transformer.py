"""Transformer LM: forward, sharded init, full dp*fsdp*tp*sp train step."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.models.transformer import (Transformer, TransformerConfig,
                                        lm_loss_fn)
from edl_tpu.parallel import mesh as mesh_lib, sharding as shd
from edl_tpu.train.state import TrainState
from edl_tpu.train.step import make_train_step
from jax.sharding import NamedSharding, PartitionSpec as P

VOCAB = 64


def tiny_cfg(**kw):
    defaults = dict(vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2,
                    d_ff=64, max_len=64, dtype=jnp.float32)
    defaults.update(kw)
    return TransformerConfig(**defaults)


def tokens(b=4, s=16, key=0):
    return jax.random.randint(jax.random.PRNGKey(key), (b, s), 0, VOCAB)


def test_forward_shape_single_device():
    cfg = tiny_cfg()
    model = Transformer(cfg)
    toks = tokens()
    variables = model.init(jax.random.PRNGKey(0), toks, train=False)
    logits = model.apply(variables, toks, train=False)
    assert logits.shape == (4, 16, VOCAB)
    assert logits.dtype == jnp.float32


def test_logical_to_spec_rules():
    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshSpec({"dp": 2, "fsdp": 2, "tp": 2}))
    assert shd.logical_to_spec(("vocab", "embed"), mesh=mesh) == \
        P("tp", "fsdp")
    assert shd.logical_to_spec(("batch", "seq", "embed"), mesh=mesh) == \
        P(("dp", "fsdp"))
    # Axes absent from the mesh drop out.
    small = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 8}))
    assert shd.logical_to_spec(("vocab", "embed"), mesh=small) == P()


def test_sharded_init_places_params():
    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshSpec({"dp": 2, "fsdp": 2, "tp": 2}))
    cfg = tiny_cfg(mesh=mesh)
    model = Transformer(cfg)
    toks = tokens()
    variables = shd.init_sharded(
        lambda: model.init(jax.random.PRNGKey(0), toks, train=False), mesh)
    emb = variables["params"]["tok_embed"]["embedding"]
    # vocab dim deliberately unsharded (gather can't partition over it —
    # would force involuntary table remat); embed dim splits over tp.
    assert emb.sharding.spec == P(None, "tp")
    mlp = variables["params"]["block0"]["mlp_in"]["kernel"]
    assert mlp.sharding.spec == P("fsdp", "tp")


def test_full_train_step_dp_fsdp_tp_sp():
    # The dryrun_multichip shape: all four axes live at once.
    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshSpec({"dp": 2, "fsdp": 1, "tp": 2, "sp": 2}))
    cfg = tiny_cfg(mesh=mesh)
    model = Transformer(cfg)
    toks = tokens(b=4, s=16)
    variables = shd.init_sharded(
        lambda: model.init(jax.random.PRNGKey(0), toks, train=False), mesh)
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"],
                              tx=optax.adamw(1e-3))
    step = make_train_step(lm_loss_fn, donate=False)
    batch = {"tokens": jax.device_put(
        toks, NamedSharding(mesh, P("dp", "sp")))}
    losses = []
    for _ in range(4):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    # Params stayed sharded through the update.
    emb = state.params["tok_embed"]["embedding"]
    assert emb.sharding.spec == P(None, "tp")


def test_remat_matches_no_remat():
    cfg = tiny_cfg()
    model = Transformer(cfg)
    toks = tokens()
    variables = model.init(jax.random.PRNGKey(0), toks, train=False)
    cfg_r = tiny_cfg(remat=True)
    out = model.apply(variables, toks, train=False)
    out_r = Transformer(cfg_r).apply(variables, toks, train=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_r),
                               rtol=1e-5, atol=1e-5)
