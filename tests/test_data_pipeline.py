"""Data pipeline: determinism, sharding, elastic resharding, prefetch."""

import numpy as np
import pytest

import jax
from edl_tpu.data.pipeline import (ArraySource, DataLoader, epoch_indices,
                                   prefetch, prefetch_to_device,
                                   random_crop, random_flip_lr)
from edl_tpu.parallel import mesh as mesh_lib


def make_source(n=64):
    return ArraySource({
        "x": np.arange(n, dtype=np.float32)[:, None],
        "label": np.arange(n, dtype=np.int32),
    })


def collect_ids(loader, epoch):
    return [b["label"].tolist() for b in loader.epoch(epoch)]


def test_epoch_order_deterministic_and_distinct():
    a = epoch_indices(100, epoch=3, seed=7)
    b = epoch_indices(100, epoch=3, seed=7)
    c = epoch_indices(100, epoch=4, seed=7)
    assert (a == b).all()
    assert not (a == c).all()
    assert sorted(a.tolist()) == list(range(100))


def test_sharding_partitions_epoch():
    src = make_source(64)
    loaders = [DataLoader(src, 8, rank=r, world=2, seed=1) for r in (0, 1)]
    seen = []
    for ld in loaders:
        for ids in collect_ids(ld, 0):
            assert len(ids) == 8
            seen.extend(ids)
    assert sorted(seen) == list(range(64))  # disjoint cover


def test_replay_after_elastic_restart():
    src = make_source(60)
    # World 2, epoch 5: both pods consume 3 batches then "die".
    before = [collect_ids(DataLoader(src, 5, rank=r, world=2, seed=9), 5)
              for r in (0, 1)]
    # Restarted world 2 must replay the identical epoch order.
    after = [collect_ids(DataLoader(src, 5, rank=r, world=2, seed=9), 5)
             for r in (0, 1)]
    assert before == after


def test_drop_remainder_static_shapes():
    src = make_source(70)
    ld = DataLoader(src, 8, world=2, rank=0, seed=0)
    batches = list(ld.epoch(0))
    assert len(batches) == ld.steps_per_epoch() == 4  # 35 // 8
    assert all(len(b["label"]) == 8 for b in batches)


def test_transforms_deterministic():
    rng_img = np.random.default_rng(0)
    src = ArraySource({
        "image": rng_img.normal(size=(32, 8, 8, 3)).astype(np.float32),
        "label": np.arange(32, dtype=np.int32),
    })
    def run():
        ld = DataLoader(src, 8, seed=3,
                        transforms=[random_flip_lr,
                                    lambda b, r: random_crop(b, r, pad=2)])
        return [b["image"].copy() for b in ld.epoch(2)]
    a, b = run(), run()
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
        assert x.shape == (8, 8, 8, 3)


@pytest.mark.parametrize("dtype", [np.uint8, np.float32])
def test_random_crop_vectorized_matches_per_image_loop(dtype):
    """The batched sliding-window gather must be bit-identical to the
    per-image loop it replaced (same rng draws, same windows)."""
    pad = 3
    imgs = (np.random.default_rng(0).integers(0, 256, size=(6, 12, 10, 3))
            .astype(dtype))
    out = random_crop({"image": imgs}, np.random.default_rng(9), pad=pad)

    rng = np.random.default_rng(9)  # the pre-vectorization reference
    padded = np.pad(imgs, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                    mode="reflect")
    ys = rng.integers(0, 2 * pad + 1, size=6)
    xs = rng.integers(0, 2 * pad + 1, size=6)
    ref = np.empty_like(imgs)
    for i in range(6):
        ref[i] = padded[i, ys[i]:ys[i] + 12, xs[i]:xs[i] + 10]

    np.testing.assert_array_equal(out["image"], ref)
    assert out["image"].dtype == imgs.dtype
    assert out["image"].flags["C_CONTIGUOUS"]


def test_prefetch_preserves_order_and_raises():
    items = list(range(10))
    assert list(prefetch(iter(items), size=3)) == items

    def bad():
        yield 1
        raise ValueError("boom")

    with pytest.raises(ValueError, match="boom"):
        list(prefetch(bad(), size=2))


def test_prefetch_abandoned_iterator_stops_worker():
    import threading
    import time

    pulled = []

    def slow_source():
        for i in range(1000):
            pulled.append(i)
            yield i

    it = prefetch(slow_source(), size=2)
    assert next(it) == 0
    it.close()  # abandon mid-stream (what a stop-resume does)
    deadline = time.time() + 5.0
    while time.time() < deadline and any(
            t.name == "data-prefetch" and t.is_alive()
            for t in threading.enumerate()):
        time.sleep(0.05)
    assert not any(t.name == "data-prefetch" and t.is_alive()
                   for t in threading.enumerate()), \
        "prefetch worker still alive after consumer closed"
    n = len(pulled)
    time.sleep(0.2)
    assert len(pulled) == n, "worker kept consuming after close"


def test_prefetch_to_device_shards_batches():
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 8}))
    sharding = mesh_lib.data_sharding(mesh)
    src = make_source(32)
    ld = DataLoader(src, 16, seed=0)
    out = list(prefetch_to_device(ld.epoch(0), sharding))
    assert len(out) == 2
    assert out[0]["x"].sharding == sharding
    assert isinstance(out[0]["x"], jax.Array)
