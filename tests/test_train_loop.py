"""End-to-end train loop: convergence, checkpoint/resume, LR schedules.

The fit_a_line slice (BASELINE config 1) run in-process on the 8-device CPU
mesh — the model for elastic stop-resume testing without a TPU pod.
"""

import numpy as np
import pytest

from edl_tpu.examples import fit_a_line
from edl_tpu.parallel.mesh import make_mesh
from edl_tpu.train import lr as lr_lib
from edl_tpu.train.loop import LoopConfig, TrainLoop


def _make_loop(cfg, ckpt_dir=None, num_epochs=3):
    state, step_fn = fit_a_line.build(cfg)
    return TrainLoop(
        step_fn, state, mesh=make_mesh(),
        config=LoopConfig(num_epochs=num_epochs, ckpt_dir=ckpt_dir,
                          log_every_steps=1000),
    )


def test_linear_regression_converges():
    cfg = fit_a_line.Config(num_epochs=3, steps_per_epoch=40)
    loop = _make_loop(cfg)
    loop.run(lambda e: fit_a_line.synthetic_batches(e, cfg))
    # run a fresh batch through the final params to measure loss
    import jax.numpy as jnp
    batch = next(fit_a_line.synthetic_batches(99, cfg))
    pred = loop.state.apply_fn({"params": loop.state.params}, batch["x"])
    loss = float(jnp.mean((pred - batch["y"]) ** 2))
    assert loss < 0.01, loss


def test_resume_continues_from_epoch(tmp_path):
    cfg = fit_a_line.Config(num_epochs=5, steps_per_epoch=10)
    # phase 1: run 2 epochs then "crash"
    loop1 = _make_loop(cfg, str(tmp_path), num_epochs=2)
    loop1.run(lambda e: fit_a_line.synthetic_batches(e, cfg))
    assert loop1.status.epoch == 1
    assert loop1.status.step == 20
    # phase 2: new process resumes at epoch 2, not 0
    seen_epochs = []

    def data_fn(epoch):
        seen_epochs.append(epoch)
        return fit_a_line.synthetic_batches(epoch, cfg)

    loop2 = _make_loop(cfg, str(tmp_path), num_epochs=5)
    loop2.run(data_fn)
    assert seen_epochs == [2, 3, 4]
    assert loop2.status.step == 50


def test_resume_noop_when_complete(tmp_path):
    cfg = fit_a_line.Config(num_epochs=2, steps_per_epoch=5)
    loop1 = _make_loop(cfg, str(tmp_path), num_epochs=2)
    loop1.run(lambda e: fit_a_line.synthetic_batches(e, cfg))
    loop2 = _make_loop(cfg, str(tmp_path), num_epochs=2)
    calls = []
    loop2.run(lambda e: calls.append(e) or [])
    assert calls == []


def test_elastic_world_resize_resume(tmp_path):
    """Save on an 8-way mesh, resume on a 4-way mesh (elastic shrink)."""
    cfg = fit_a_line.Config(num_epochs=4, steps_per_epoch=8)
    state, step_fn = fit_a_line.build(cfg)
    loop1 = TrainLoop(step_fn, state, mesh=make_mesh(n_devices=8),
                      config=LoopConfig(num_epochs=2, ckpt_dir=str(tmp_path)))
    loop1.run(lambda e: fit_a_line.synthetic_batches(e, cfg))

    state2, step_fn2 = fit_a_line.build(cfg)
    loop2 = TrainLoop(step_fn2, state2, mesh=make_mesh(n_devices=4),
                      config=LoopConfig(num_epochs=4, ckpt_dir=str(tmp_path)))
    loop2.run(lambda e: fit_a_line.synthetic_batches(e, cfg))
    assert loop2.status.epoch == 3
    assert loop2.status.world_size == 4
    # params actually carried over and usable on the smaller mesh
    import jax.numpy as jnp
    batch = next(fit_a_line.synthetic_batches(99, cfg))
    pred = loop2.state.apply_fn({"params": loop2.state.params}, batch["x"])
    assert float(jnp.mean((pred - batch["y"]) ** 2)) < 0.01


def test_lr_schedules():
    sched = lr_lib.piecewise_with_warmup([100, 200], [0.1, 0.01, 0.001],
                                         warmup_steps=10)
    assert float(sched(0)) == pytest.approx(0.0)
    assert float(sched(10)) == pytest.approx(0.1)
    assert float(sched(150)) == pytest.approx(0.01)
    assert float(sched(250)) == pytest.approx(0.001)

    cos = lr_lib.cosine_with_warmup(0.4, total_steps=100, warmup_steps=20)
    assert float(cos(20)) == pytest.approx(0.4, rel=1e-3)
    assert float(cos(100)) == pytest.approx(0.0, abs=1e-6)

    assert lr_lib.scale_for_world(0.1, 8, 4) == pytest.approx(0.05)

    exp = lr_lib.exponential_with_warmup(0.1, warmup_steps=5, decay_steps=10,
                                         decay_rate=0.5)
    assert float(exp(5)) == pytest.approx(0.1)
    assert float(exp(16)) == pytest.approx(0.05)


def test_midepoch_checkpoint_resume(tmp_path):
    """ckpt_every_steps: crash mid-epoch, resume skips trained batches."""
    cfg = fit_a_line.Config(num_epochs=1, steps_per_epoch=10)
    state, step_fn = fit_a_line.build(cfg)
    loop1 = TrainLoop(step_fn, state, mesh=make_mesh(),
                      config=LoopConfig(num_epochs=1, ckpt_dir=str(tmp_path),
                                        ckpt_every_steps=4))

    class Crash(Exception):
        pass

    def crashing_data(epoch):
        for i, b in enumerate(fit_a_line.synthetic_batches(epoch, cfg)):
            if i == 6:  # crash after step 6 (mid-epoch ckpt at step 4)
                raise Crash()
            yield b

    import pytest as _pytest
    with _pytest.raises(Crash):
        loop1.run(crashing_data)

    # resume: must skip the 4 checkpointed batches, train batches 4..9,
    # and finish with exactly 10 global steps (no double counting)
    trained_batches = []
    state2, step_fn2 = fit_a_line.build(cfg)

    def tracking_step(state, batch):
        trained_batches.append(1)
        return step_fn2(state, batch)

    loop2 = TrainLoop(tracking_step, state2, mesh=make_mesh(),
                      config=LoopConfig(num_epochs=1, ckpt_dir=str(tmp_path),
                                        ckpt_every_steps=4))
    loop2.run(lambda e: fit_a_line.synthetic_batches(e, cfg))
    assert len(trained_batches) == 6
    assert loop2.status.step == 10
    assert loop2.status.step_in_epoch == 0
    assert loop2.status.epoch == 0
    assert loop2.status.samples_seen == 10 * cfg.batch_size


def test_piecewise_boundaries_are_global_steps():
    sched = lr_lib.piecewise_with_warmup([100], [0.1, 0.01],
                                         warmup_steps=10)
    assert float(sched(99)) == pytest.approx(0.1)
    assert float(sched(101)) == pytest.approx(0.01)  # not shifted to 110


def test_watcher_survives_callback_exception():
    import threading as _threading
    import time as _time
    from edl_tpu.coord.registry import ServiceRegistry
    from edl_tpu.coord.store import InMemStore

    store = InMemStore()
    reg = ServiceRegistry(store, root="t")
    seen = []
    ev = _threading.Event()

    def bad_add(meta):
        seen.append(meta.server)
        if len(seen) == 1:
            raise KeyError("boom")  # must not kill the watch thread
        ev.set()

    w = reg.watch_service("svc", on_add=bad_add, interval=0.05)
    reg.register_permanent("svc", "a:1")
    _time.sleep(0.2)
    reg.register_permanent("svc", "b:2")
    assert ev.wait(2.0), "watcher thread died after callback exception"
    w.stop()


def test_prefetch_matches_inline_placement():
    """prefetch_batches stages placed batches on a thread; the training
    result must be identical to inline placement (same data, same step
    order, same final params)."""
    import jax

    cfg = fit_a_line.Config(num_epochs=2, steps_per_epoch=12)

    def run(prefetch):
        state, step_fn = fit_a_line.build(cfg)
        loop = TrainLoop(step_fn, state, mesh=make_mesh(),
                         config=LoopConfig(num_epochs=2,
                                           log_every_steps=1000,
                                           prefetch_batches=prefetch))
        loop.run(lambda e: fit_a_line.synthetic_batches(e, cfg))
        return loop

    inline, staged = run(0), run(2)
    assert staged.status.step == inline.status.step
    assert staged.status.samples_seen == inline.status.samples_seen
    for a, b in zip(jax.tree.leaves(inline.state.params),
                    jax.tree.leaves(staged.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6)


def test_prefetch_with_midepoch_resume(tmp_path):
    """Skip-before-place: a mid-epoch resume with prefetch on must not
    re-train (or even stage) already-trained batches."""
    cfg = fit_a_line.Config(num_epochs=1, steps_per_epoch=10)
    state, step_fn = fit_a_line.build(cfg)
    loop1 = TrainLoop(step_fn, state, mesh=make_mesh(),
                      config=LoopConfig(num_epochs=1, ckpt_dir=str(tmp_path),
                                        ckpt_every_steps=4,
                                        prefetch_batches=2))

    class Crash(Exception):
        pass

    def crashing_data(epoch):
        for i, b in enumerate(fit_a_line.synthetic_batches(epoch, cfg)):
            if i == 6:
                raise Crash()
            yield b

    with pytest.raises(Crash):
        loop1.run(crashing_data)

    trained = []
    state2, step_fn2 = fit_a_line.build(cfg)

    def tracking_step(state, batch):
        trained.append(1)
        return step_fn2(state, batch)

    loop2 = TrainLoop(tracking_step, state2, mesh=make_mesh(),
                      config=LoopConfig(num_epochs=1, ckpt_dir=str(tmp_path),
                                        ckpt_every_steps=4,
                                        prefetch_batches=2))
    loop2.run(lambda e: fit_a_line.synthetic_batches(e, cfg))
    assert len(trained) == 6        # batches 4..9 only
    assert loop2.status.step == 10
