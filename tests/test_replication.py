"""Replicated/sharded store: failover + fencing + REDIRECT semantics.

The contracts under test are the ones the elastic machinery consumes
(doc/design_coord.md replication section):

- kill the leader mid-watch: the client re-attaches by revision and
  sees every majority-acked event exactly once (zero lost, zero dup);
- a deposed (partitioned) leader cannot acknowledge writes — the
  commit gate times out while its quorum lease is dead — and rejoins
  via snapshot, discarding its divergent suffix;
- shard REDIRECTs route to the owning group and a redirect LOOP is
  bounded and surfaced as a clear error, not a hang.
"""

import threading
import time

import pytest

from edl_tpu.coord import wire
from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.registry import ServiceRegistry
from edl_tpu.coord.replication import (ReplicaGroup, ReplicaServer,
                                       ShardedStoreClient, ShardRouter,
                                       parse_topology, shard_key)
from edl_tpu.utils.exceptions import EdlStoreError
from edl_tpu.utils.net import free_port


@pytest.fixture
def group():
    with ReplicaGroup(3, election_ttl=0.5, commit_timeout=1.5) as g:
        g.wait_leader(timeout=20.0)
        yield g


def _wait(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def test_leader_writes_replicate_to_follower_reads(group):
    client = group.client(timeout=3.0)
    rev = client.put("/r/a", "1")
    lease = client.lease_grant(30.0)
    client.put("/r/b", "2", lease=lease)
    leader = group.leader()
    for srv in group.servers:
        if srv is leader:
            continue
        # follower reads served locally, with the leader's revisions
        # AND the lease id on the record (promotion rebuilds from it)
        follower = StoreClient(srv.endpoint, timeout=3.0)
        assert _wait(lambda: follower.get("/r/b") is not None)
        rec = follower.get("/r/a")
        assert (rec.value, rec.revision) == ("1", rev)
        assert follower.get("/r/b").lease == lease
        follower.close()
    client.close()


def test_kill_leader_mid_watch_zero_lost_zero_dup(group):
    client = group.client(timeout=3.0)
    watcher = group.client(timeout=3.0)
    watch = watcher.watch("/job/", start_revision=0)
    acked = {}
    for i in range(10):
        acked[f"p{i}"] = client.put(f"/job/rank/{i}", f"p{i}")

    killed = group.kill_leader()
    new_leader = group.wait_leader(timeout=20.0)
    assert new_leader.endpoint != killed
    for i in range(10, 20):
        acked[f"p{i}"] = client.put(f"/job/rank/{i}", f"p{i}")

    seen = {}
    duplicates = 0
    compacted = False
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        batch = watch.get(timeout=0.5)
        if batch is None:
            if seen and max(seen) >= max(acked.values()):
                break
            continue
        compacted = compacted or batch.compacted
        for ev in batch.events:
            if ev.revision in seen:
                duplicates += 1
            seen[ev.revision] = ev.value
    assert not compacted, "short stream must not hit compaction"
    assert duplicates == 0
    lost = [v for v, rev in acked.items() if rev not in seen]
    assert not lost, f"acked events lost across failover: {lost}"
    assert all(seen[rev] == v for v, rev in acked.items())
    watch.cancel()
    watcher.close()
    client.close()


def test_resume_by_revision_on_new_leader(group):
    client = group.client(timeout=3.0)
    revs = [client.put(f"/res/{i}", str(i)) for i in range(6)]
    group.kill_leader()
    group.wait_leader(timeout=20.0)
    after = [client.put(f"/res/{i}", str(100 + i)) for i in range(3)]
    # a FRESH watch that resumes from the middle of the pre-kill stream
    # replays exactly the suffix — the new leader's history covers it
    watch = client.watch("/res/", start_revision=revs[2])
    got = []
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and len(got) < 3 + len(after):
        batch = watch.get(timeout=0.5)
        if batch is None:
            continue
        assert not batch.compacted
        got.extend(ev.revision for ev in batch.events)
    assert got == revs[3:] + after
    watch.cancel()
    client.close()


def test_deposed_leader_fenced_and_snapshot_rejoins(group):
    old = group.leader()
    others = [s for s in group.servers if s is not old]
    # a client pinned to the old leader only — no transparent failover,
    # we WANT to observe the refusal
    pinned = StoreClient(old.endpoint, timeout=2.0, connect_retries=2,
                         retry_interval=0.05)
    pinned.put("/fence/pre", "committed")

    old.node._partitioned = True
    # While its quorum lease may still look live the commit gate cannot
    # reach majority; once the lease ages out the role check refuses.
    # Either way the write is NOT acknowledged.
    with pytest.raises(EdlStoreError):
        pinned.put("/fence/divergent", "doomed")

    assert _wait(lambda: any(s.node.is_leader() for s in others),
                 timeout=15.0), "survivors must elect a new leader"
    new_leader = next(s for s in others if s.node.is_leader())
    ha = StoreClient(",".join(s.endpoint for s in others), timeout=3.0)
    ha.put("/fence/post", "new-reign")

    old.node._partitioned = False
    # the deposed leader steps down on first contact with the higher
    # term and rejoins via snapshot: its divergent write is DISCARDED
    assert _wait(lambda: old.node.role() == "follower", timeout=15.0)
    assert _wait(lambda: old.node.store.get("/fence/post") is not None,
                 timeout=15.0)
    assert old.node.store.get("/fence/divergent") is None
    assert old.node.store.get("/fence/pre") is not None
    assert old.node.term() >= new_leader.node.term()
    pinned.close()
    ha.close()


def test_sharded_redirect_and_routing():
    ports = [free_port(), free_port()]
    eps = [f"127.0.0.1:{p}" for p in ports]
    topo = {"s0": [eps[0]], "s1": [eps[1]]}
    router = ShardRouter(topo)
    # two services that land on DIFFERENT groups
    svc_a = next(f"svc{i}" for i in range(100)
                 if router.owner(f"/edl/svc{i}/nodes/x") == "s0")
    svc_b = next(f"svc{i}" for i in range(100)
                 if router.owner(f"/edl/svc{i}/nodes/x") == "s1")
    servers = [
        ReplicaServer(eps[i], ports[i], group_endpoints=[eps[i]],
                      group=g, topology=topo, election_ttl=0.5)
        for i, g in enumerate(["s0", "s1"])
    ]
    for s in servers:
        s.start()
    try:
        assert _wait(lambda: all(s.node.is_leader() for s in servers))
        key_b = f"/edl/{svc_b}/nodes/h1"
        # a plain client pointed at the WRONG group follows the REDIRECT
        wrong = StoreClient(eps[0], timeout=3.0)
        wrong.put(key_b, "routed")
        right = StoreClient(eps[1], timeout=3.0)
        assert right.get(key_b).value == "routed"
        assert StoreClient(eps[1]).get_prefix(f"/edl/{svc_b}/")[0]

        # the sharded client routes directly and virtualizes leases
        sharded = ShardedStoreClient(topo, timeout=3.0)
        registry = ServiceRegistry(sharded, root="edl")
        reg = registry.register(svc_a, "h:1", info="up", ttl=5.0)
        assert _wait(lambda: registry.get_service(svc_a))
        seen = threading.Event()
        watcher = registry.watch_service(svc_b,
                                         on_add=lambda m: seen.set())
        registry.register_permanent(svc_b, "h:2")
        assert seen.wait(5.0), "watch routed to the owning group"
        # cross-shard watch refuses (try_watch turns this into polling)
        with pytest.raises(EdlStoreError):
            sharded.watch("/edl/")
        watcher.stop()
        reg.stop()
        sharded.close()
        wrong.close()
        right.close()
    finally:
        for s in servers:
            s.stop()


def test_redirect_loop_bounded_and_clear():
    import socketserver

    class _LoopHandler(socketserver.BaseRequestHandler):
        def handle(self):
            while True:
                try:
                    wire.recv_msg(self.request)
                except (wire.WireError, OSError):
                    return
                try:
                    wire.send_msg(self.request, {
                        "ok": False, "redirect": True, "group": "g",
                        "endpoints": [self.server.self_ep],
                        "error": "always elsewhere"})
                except OSError:
                    return

    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _LoopHandler)
    srv.daemon_threads = True
    srv.self_ep = f"127.0.0.1:{srv.server_address[1]}"
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        client = StoreClient(srv.self_ep, timeout=2.0, connect_retries=2,
                             retry_interval=0.05, max_hops=3)
        with pytest.raises(EdlStoreError, match="redirect loop"):
            client.put("/loop/x", "1")
        client.close()
    finally:
        srv.shutdown()
        srv.server_close()
        t.join(timeout=5.0)


def test_multi_endpoint_client_skips_dead_endpoint(group):
    dead = f"127.0.0.1:{free_port()}"
    client = StoreClient(f"{dead},{group.endpoints_spec}", timeout=1.0,
                         connect_retries=3, retry_interval=0.05)
    assert client.put("/multi/x", "1") > 0
    assert client.get("/multi/x").value == "1"
    client.close()


def test_shard_key_and_topology_parsing():
    assert shard_key("/edl/teachers/nodes/h:1") == "/edl/teachers"
    assert parse_topology("a:1,b:1") == {"shard0": ["a:1", "b:1"]}
    assert list(parse_topology("x=a:1;y=b:1")) == ["x", "y"]
    chunked = parse_topology("a:1,b:1,c:1", shards=3)
    assert [len(v) for v in chunked.values()] == [1, 1, 1]


def test_asymmetric_partition_writes_bounce_then_client_fails_over(group):
    """The chaos-plane drill as a pinned contract (ISSUE 12): the old
    leader stays REACHABLE by clients while cut off from quorum. Writes
    through it must bounce with a refusal (never ack-then-lose), a
    multi-endpoint client must fail over and keep writing, and a watch
    resumed by revision must deliver every acked write."""
    old = group.wait_leader()
    # client whose dial order starts at the (about to be deposed) leader
    ordered = [old.endpoint] + [e for e in group.endpoints
                                if e != old.endpoint]
    client = StoreClient(",".join(ordered), timeout=3.0,
                         connect_retries=30, retry_interval=0.05)
    acked: dict[str, int] = {}
    for i in range(3):
        acked[f"pre-{i}"] = client.put(f"/asym/{i}", f"pre-{i}")

    old.node.set_partition(True)  # asymmetric: clients in, quorum out
    # The write the partition catches first must REFUSE (commit gate
    # timeout or not_leader once the lease ages out) — EdlStoreError,
    # not a silent ack. put IS retryable-with-failover, so a refusal
    # may also resolve into a successful re-route; both are correct,
    # ack-then-lose is not.
    t0 = time.monotonic()
    survived = []
    for i in range(3, 8):
        try:
            acked[f"post-{i}"] = client.put(f"/asym/{i}", f"post-{i}")
            survived.append(i)
        except EdlStoreError:
            pass  # refusal: definitively not applied
    assert survived, "client never failed over to the new leader"
    assert time.monotonic() - t0 < 60.0
    new = group.leader()
    assert new is not None and new.endpoint != old.endpoint

    # every ACKED write is delivered exactly once on a fresh watch
    # resumed from before the partition (served by any live replica)
    ha = StoreClient(",".join(e for e in group.endpoints
                              if e != old.endpoint), timeout=3.0)
    watch = ha.watch("/asym/", start_revision=0)
    got: dict[int, str] = {}
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline \
            and not set(acked.values()) <= set(got):
        batch = watch.get(timeout=0.5)
        if batch is None:
            continue
        for ev in batch.events:
            assert ev.revision not in got, "duplicate delivery"
            got[ev.revision] = ev.value
    for value, rev in acked.items():
        assert got.get(rev) == value, f"acked {value}@{rev} lost"
    watch.cancel()
    ha.close()

    old.node.set_partition(None)  # heal: deposed leader snapshot-rejoins
    assert _wait(lambda: old.node.role() == "follower", timeout=15.0)
    client.close()


# -- commit-gated watch fan-out (the r18 branch-anomaly regression) ---------


def test_fanout_gate_reuse_of_revisions_is_invisible():
    """The deterministic half of the r18 anomaly drill: a gated store
    (standing in for a doomed leader) applies a suffix that never
    commits; its watchers must see NOTHING of it — not at apply time,
    not as a resume-anchor advance, and not after the new reign reuses
    those revision numbers with different values (snapshot install)."""
    from edl_tpu.coord.store import InMemStore

    store = InMemStore()
    store.set_fanout_gate(True)
    watch = store.watch("/j/")
    rev1 = store.put("/j/a", "committed")
    store.release_fanout(rev1)
    batch = watch.get(timeout=2.0)
    assert batch is not None and batch.events[0].value == "committed"

    # the doomed suffix: applied locally, never majority-committed
    store.put("/j/k", "doomed-1")
    store.put("/j/k", "doomed-2")
    assert watch.get(timeout=0.2) is None, \
        "uncommitted suffix leaked to a watcher"
    # the resume anchor must NOT advance past the commit gate — a
    # client resuming from it on the new reign would skip the reused
    # revisions entirely
    assert watch.progress_revision() == rev1

    # the new reign: same revision numbers, different (committed) data
    reign = InMemStore()
    reign.apply_put("/j/a", "committed", rev1)
    reign.apply_put("/j/k", "good", rev1 + 1)
    store.install_snapshot(reign.snapshot_state())
    batch = watch.get(timeout=2.0)
    assert batch is not None and batch.compacted, \
        "snapshot rejoin must force an explicit resync"
    assert store.get("/j/k").value == "good"
    # nothing pending survived the snapshot: later releases are no-ops
    store.release_fanout(10_000)
    assert watch.get(timeout=0.2) is None
    watch.cancel()


def test_fanout_gate_late_watcher_gets_tail_exactly_once():
    """A watcher subscribing with start_revision while a suffix is
    buffered replays only the committed prefix; the tail arrives
    exactly once when the commit gate advances over it."""
    from edl_tpu.coord.store import InMemStore

    store = InMemStore()
    store.set_fanout_gate(True)
    r1 = store.put("/j/a", "1")
    store.release_fanout(r1)
    r2 = store.put("/j/b", "2")   # buffered behind the gate
    watch = store.watch("/j/", start_revision=0)
    batch = watch.get(timeout=2.0)
    assert [e.revision for e in batch.events] == [r1]
    assert watch.get(timeout=0.1) is None
    store.release_fanout(r2)
    batch = watch.get(timeout=2.0)
    assert [e.revision for e in batch.events] == [r2]
    assert watch.get(timeout=0.1) is None  # exactly once
    watch.cancel()


def test_fanout_gate_resume_anchor_never_redelivers_pending():
    """The failover-duplicate regression: a watcher that already HAS
    revision R (it resumes with start_revision=R on a replica whose
    commit gate is still behind R) must not be handed R again when the
    gate advances over the replica's pending tail."""
    from edl_tpu.coord.store import InMemStore

    follower = InMemStore()
    follower.set_fanout_gate(True)
    follower.apply_put("/j/a", "1", 1)
    follower.release_fanout(1)
    # rev 2 applied but its commit not yet learned (pending)
    follower.apply_put("/j/b", "2", 2)
    # the client already consumed rev 2 from the dead leader: resume
    watch = follower.watch("/j/", start_revision=2)
    follower.release_fanout(2)
    assert watch.get(timeout=0.2) is None, \
        "resume anchor re-delivered by the commit-gate release"
    # but an event genuinely past the anchor still flows
    follower.apply_put("/j/c", "3", 3)
    follower.release_fanout(3)
    batch = watch.get(timeout=2.0)
    assert [e.revision for e in batch.events] == [3]
    watch.cancel()


def test_watch_on_doomed_leader_never_sees_its_suffix(group):
    """End-to-end over real sockets: a watcher pinned to a leader that
    gets partitioned from quorum must never be shown the write the
    partition catches (it may commit later OR be discarded — either
    way nothing is delivered until the outcome is decided), and after
    the new reign + snapshot rejoin the watcher resyncs to the
    committed branch only."""
    old = group.wait_leader()
    pinned = StoreClient(old.endpoint, timeout=2.0, connect_retries=2,
                         retry_interval=0.05)
    wclient = StoreClient(old.endpoint, timeout=2.0, connect_retries=2,
                          retry_interval=0.05)
    watch = wclient.watch("/branch/", start_revision=0)
    pinned.put("/branch/pre", "committed")

    def drain(seconds):
        got = []
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            batch = watch.get(timeout=0.2)
            if batch is not None:
                got.append(batch)
        return got

    pre = [ev for b in drain(2.0) for ev in b.events]
    assert any(ev.value == "committed" for ev in pre)

    old.node.set_partition(True)
    with pytest.raises(EdlStoreError):
        pinned.put("/branch/k", "doomed")
    # whatever the old leader applied locally is behind the commit
    # gate: its watcher must see NO events for it
    assert not [ev for b in drain(1.5) for ev in b.events], \
        "watcher on the doomed leader saw its uncommitted suffix"

    others = [s for s in group.servers if s is not old]
    assert _wait(lambda: any(s.node.is_leader() for s in others),
                 timeout=15.0)
    ha = StoreClient(",".join(s.endpoint for s in others), timeout=3.0)
    ha.put("/branch/k", "good")  # the committed branch (revisions may
    # collide with the doomed suffix's — that is the point)

    old.node.set_partition(None)
    assert _wait(lambda: old.node.role() == "follower", timeout=15.0)
    assert _wait(lambda: old.node.store.get("/branch/k") is not None
                 and old.node.store.get("/branch/k").value == "good",
                 timeout=15.0)
    # the watcher either got an explicit compacted resync (snapshot
    # rejoin) or nothing — but NEVER a doomed value, and never the
    # same revision with two different values
    seen: dict[int, str] = {}
    for b in drain(3.0):
        for ev in b.events:
            assert ev.value != "doomed"
            assert seen.get(ev.revision, ev.value) == ev.value, \
                "same revision delivered with two different values"
            seen[ev.revision] = ev.value
    watch.cancel()
    wclient.close()
    pinned.close()
    ha.close()
