"""deploy/k8s manifests stay structurally valid (tools/validate_k8s.py).

The reference shipped raw yaml with no gate; here the validator runs in
the suite (and CI) so a typo'd selector, dangling service reference, or
unparseable resource quantity fails before any deploy.
"""

import os
import sys

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from validate_k8s import validate_dir  # noqa: E402

K8S_DIR = os.path.join(REPO, "deploy", "k8s")


def test_bundle_is_valid():
    assert validate_dir(K8S_DIR) == []


def test_validator_catches_selector_mismatch(tmp_path):
    (tmp_path / "bad.yaml").write_text("""
apiVersion: apps/v1
kind: Deployment
metadata: {name: d}
spec:
  selector: {matchLabels: {app: x}}
  template:
    metadata: {labels: {app: y}}
    spec:
      containers: [{name: c, image: i}]
""")
    errs = validate_dir(str(tmp_path))
    assert any("selector" in e for e in errs), errs


def test_validator_catches_missing_image(tmp_path):
    (tmp_path / "bad.yaml").write_text("""
apiVersion: v1
kind: Pod
metadata: {name: p}
spec:
  containers: [{name: c}]
""")
    errs = validate_dir(str(tmp_path))
    assert any("without image" in e for e in errs), errs


def test_validator_catches_bad_quantity(tmp_path):
    (tmp_path / "bad.yaml").write_text("""
apiVersion: v1
kind: Pod
metadata: {name: p}
spec:
  containers:
    - name: c
      image: i
      resources: {requests: {cpu: lots}}
""")
    errs = validate_dir(str(tmp_path))
    assert any("unparseable resource" in e for e in errs), errs


def test_jobset_containers_checked(tmp_path):
    (tmp_path / "js.yaml").write_text("""
apiVersion: jobset.x-k8s.io/v1alpha2
kind: JobSet
metadata: {name: j}
spec:
  replicatedJobs:
    - name: w
      template:
        spec:
          template:
            spec:
              containers: [{name: c}]
""")
    errs = validate_dir(str(tmp_path))
    assert any("without image" in e for e in errs), errs


def test_jobset_rank_env_contract():
    """The JobSet variant must feed the launcher env contract
    (EDL_TPU_RANK from the completion index, coordinator on job 0)."""
    with open(os.path.join(K8S_DIR, "train-jobset.yaml")) as f:
        doc = yaml.safe_load(f)
    rj = doc["spec"]["replicatedJobs"][0]
    tmpl = rj["template"]["spec"]
    assert tmpl["parallelism"] == tmpl["completions"]
    env = {e["name"]: e for e in
           tmpl["template"]["spec"]["containers"][0]["env"]}
    assert "job-completion-index" in str(
        env["EDL_TPU_RANK"]["valueFrom"]["fieldRef"]["fieldPath"])
    assert int(env["EDL_TPU_WORLD_SIZE"]["value"]) == tmpl["completions"]
    assert "EDL_TPU_COORDINATOR" in env


def test_ctr_job_wires_task_dispenser_elasticity():
    """The CTR job's elasticity is the TaskMaster lease loop: every
    trainer must point at the in-bundle store and carry a unique
    trainer id (pod name) so leases re-dispense on pod death."""
    with open(os.path.join(K8S_DIR, "ctr-train.yaml")) as f:
        doc = yaml.safe_load(f)
    assert doc["kind"] == "Job"
    tmpl = doc["spec"]["template"]["spec"]
    args = tmpl["containers"][0]["args"]
    assert any("edl-store" in a and ":2379" in a for a in args), args
    assert any(a.startswith("--trainer-id=$(POD_NAME)") for a in args)
    env = {e["name"] for e in tmpl["containers"][0]["env"]}
    assert "POD_NAME" in env
    # scaling parallelism is the elastic knob; completions bounds it
    assert doc["spec"]["parallelism"] >= 1


@pytest.mark.parametrize("fname", ["train-job.yaml", "train-jobset.yaml",
                                   "edl-store.yaml", "ctr-train.yaml",
                                   "distill-serving.yaml"])
def test_each_file_parses(fname):
    with open(os.path.join(K8S_DIR, fname)) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    assert docs
