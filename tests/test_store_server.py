"""TCP store server/client integration: same semantics over the wire.

Test model: reference test_etcd_client.sh boots a real etcd then runs
etcd_client_test.py against it; here the server is in-process but the client
goes through real sockets and the framed protocol.
"""

import threading

import pytest

from edl_tpu.coord.client import LeaseKeeper, StoreClient
from edl_tpu.coord.server import StoreServer
from edl_tpu.utils.exceptions import EdlLeaseExpired, EdlStoreError


@pytest.fixture
def server():
    with StoreServer(port=0, host="127.0.0.1", sweep_interval=0.05) as srv:
        yield srv


@pytest.fixture
def client(server):
    c = StoreClient(f"127.0.0.1:{server.port}", timeout=5.0)
    yield c
    c.close()


def test_roundtrip(client):
    rev = client.put("/a", "1")
    assert client.get("/a").value == "1"
    assert client.get("/a").revision == rev
    recs, _ = client.get_prefix("/")
    assert [r.key for r in recs] == ["/a"]
    assert client.delete("/a")
    assert client.get("/a") is None


def test_typed_errors_survive_the_wire(client):
    # A put against a dead lease must raise EdlLeaseExpired (the subtype,
    # not just EdlStoreError) even through the TCP client — launcher
    # recovery paths dispatch on it.
    lease = client.lease_grant(5.0)
    assert client.lease_revoke(lease)
    with pytest.raises(EdlLeaseExpired):
        client.put("/dead", "1", lease=lease)


def test_cas_over_wire(client):
    assert client.put_if_absent("/rank/0", "me")
    assert not client.put_if_absent("/rank/0", "you")
    assert client.compare_and_swap("/rank/0", "me", "me2")
    assert not client.compare_and_swap("/rank/0", "nope", "x")


def test_lease_expiry_over_wire(server, client):
    lease = client.lease_grant(ttl=0.2)
    client.put("/eph", "v", lease=lease)
    assert client.get("/eph") is not None
    # sweeper expires it without further traffic
    deadline = threading.Event()
    deadline.wait(0.6)
    assert client.get("/eph") is None
    assert not client.lease_keepalive(lease)


def test_lease_keeper_keeps_alive(server, client):
    lease = client.lease_grant(ttl=0.3)
    client.put("/kept", "v", lease=lease)
    keeper = LeaseKeeper(client, lease, interval=0.05).start()
    threading.Event().wait(0.8)
    assert client.get("/kept") is not None
    keeper.stop(revoke=True)
    assert client.get("/kept") is None


def test_events_over_wire(client):
    r0 = client.put("/x", "1")
    client.put("/y", "2")
    evs, rev, compacted = client.events_since(r0)
    assert not compacted
    assert [(e.type, e.key) for e in evs] == [("PUT", "/y")]


def test_error_propagates(client):
    lease = client.lease_grant(ttl=10.0)
    client.lease_revoke(lease)
    with pytest.raises(EdlStoreError):
        client.put("/k", "v", lease=lease)


def test_concurrent_rank_claims(server):
    """N clients race put_if_absent for ranks; each rank claimed exactly once."""
    n = 8
    winners = []
    lock = threading.Lock()

    def claim(pod_id):
        c = StoreClient(f"127.0.0.1:{server.port}")
        got = None
        for rank in range(n):
            if c.put_if_absent(f"/job/rank/{rank}", pod_id):
                got = rank
                break
        with lock:
            winners.append((pod_id, got))
        c.close()

    threads = [threading.Thread(target=claim, args=(f"pod-{i}",)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ranks = sorted(r for _, r in winners)
    assert ranks == list(range(n))
