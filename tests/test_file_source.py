"""FileSource (file-backed pipeline) + flagship imagenet_train example."""

import json
import os

import numpy as np
import pytest

from edl_tpu.data.pipeline import ArraySource, DataLoader, FileSource
from edl_tpu.utils.exceptions import EdlDataError


def _write_shards(tmp_path, counts, dim=3, seed=0):
    rng = np.random.default_rng(seed)
    files, all_x, all_y = [], [], []
    for i, n in enumerate(counts):
        x = rng.normal(size=(n, dim)).astype(np.float32)
        y = rng.integers(0, 5, size=n).astype(np.int32)
        p = str(tmp_path / f"s{i}.npz")
        np.savez(p, x=x, y=y)
        files.append(p)
        all_x.append(x)
        all_y.append(y)
    return files, np.concatenate(all_x), np.concatenate(all_y)


class TestFileSource:
    def test_matches_array_source(self, tmp_path):
        files, x, y = _write_shards(tmp_path, [7, 5, 9])
        fs = FileSource(files, cache_files=2)
        assert len(fs) == 21
        arr = ArraySource({"x": x, "y": y})
        idx = np.array([0, 6, 7, 11, 12, 20, 3])  # spans all three files
        got, want = fs.batch(idx), arr.batch(idx)
        np.testing.assert_array_equal(got["x"], want["x"])
        np.testing.assert_array_equal(got["y"], want["y"])

    def test_loader_epoch_identical_to_in_memory(self, tmp_path):
        files, x, y = _write_shards(tmp_path, [16, 16])
        a = DataLoader(ArraySource({"x": x, "y": y}), 8, seed=3)
        f = DataLoader(FileSource(files, cache_files=1), 8, seed=3)
        for ba, bf in zip(a.epoch(2), f.epoch(2)):
            np.testing.assert_array_equal(ba["x"], bf["x"])
            np.testing.assert_array_equal(ba["y"], bf["y"])

    def test_lru_eviction_correctness(self, tmp_path):
        files, x, _ = _write_shards(tmp_path, [4, 4, 4, 4])
        fs = FileSource(files, cache_files=1)
        for idx in ([0, 5], [10, 15], [1, 14], [6, 9]):
            got = fs.batch(np.array(idx))
            np.testing.assert_array_equal(got["x"], x[np.array(idx)])
        assert len(fs._cache) <= 1 + 1  # bounded

    def test_empty_index_returns_empty_arrays(self, tmp_path):
        """A zero-length index (a remote client's empty batch request)
        yields empty arrays with the right trailing shapes/dtypes, not
        an IndexError (ADVICE r3)."""
        files, _, _ = _write_shards(tmp_path, [4, 4])
        src = FileSource(files)
        out = src.batch(np.array([], dtype=np.int64))
        full = src.batch(np.arange(2))
        assert set(out) == set(full)
        for k in out:
            assert out[k].shape == (0,) + full[k].shape[1:]
            assert out[k].dtype == full[k].dtype

    def test_empty_file_list_rejected(self):
        with pytest.raises(EdlDataError):
            FileSource([])

    def test_zero_cache_rejected(self, tmp_path):
        files, _, _ = _write_shards(tmp_path, [4])
        with pytest.raises(EdlDataError):
            FileSource(files, cache_files=0)

    def test_lru_keeps_hot_shard(self, tmp_path):
        files, _, _ = _write_shards(tmp_path, [4, 4, 4])
        fs = FileSource(files, cache_files=2)
        fs.batch(np.array([0]))   # load shard 0
        fs.batch(np.array([5]))   # load shard 1
        fs.batch(np.array([1]))   # HIT shard 0 -> refresh recency
        fs.batch(np.array([9]))   # load shard 2 -> must evict 1, not 0
        assert 0 in fs._cache and 1 not in fs._cache

    def test_concurrent_batches_race_free(self, tmp_path):
        """Thread-per-connection DataServer sharing one FileSource: the
        LRU mutation must be lock-protected (regression: unlocked
        _cache_order.remove raced to ValueError/KeyError)."""
        import threading

        files, x, _ = _write_shards(tmp_path, [64] * 6)
        fs = FileSource(files, cache_files=2)
        errors = []

        def hammer(seed):
            rng = np.random.default_rng(seed)
            try:
                for _ in range(200):
                    idx = rng.integers(0, len(fs), size=16)
                    got = fs.batch(idx)
                    np.testing.assert_array_equal(got["x"], x[idx])
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=hammer, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors

    def test_header_scan_counts(self, tmp_path):
        from edl_tpu.data.pipeline import _npz_rows
        files, _, _ = _write_shards(tmp_path, [7, 13])
        assert [_npz_rows(f) for f in files] == [7, 13]


class TestImagenetExample:
    def test_end_to_end_learns_and_logs(self, tmp_path):
        from edl_tpu.examples.imagenet_train import main

        data = str(tmp_path / "data")
        rc = main(["--data-dir", data, "--make-synthetic", "3",
                   "--rows-per-file", "256", "--model", "ResNetTiny",
                   "--num-classes", "10", "--image-size", "24",
                   "--epochs", "3", "--batch-size", "64",
                   "--warmup-epochs", "1", "--lr-strategy", "cosine",
                   "--lr", "0.05", "--no-augment", "--label-smoothing", "0",
                   "--ckpt-dir", str(tmp_path / "ckpt"),
                   "--benchmark-log", str(tmp_path / "blog")])
        assert rc == 0
        blog = json.load(open(tmp_path / "blog" / "log_0.json"))
        assert len(blog["epochs"]) == 3
        assert blog["final"]["acc1"] > 0.8, blog["final"]
        assert blog["max_examples_per_sec_global"] > 0
        # checkpoints were written per epoch
        assert any(n.startswith("ckpt-")
                   for n in os.listdir(tmp_path / "ckpt"))

    def test_resume_from_checkpoint(self, tmp_path):
        """Second invocation resumes instead of restarting (elastic
        restart path of the flagship trainer)."""
        from edl_tpu.examples.imagenet_train import main

        data = str(tmp_path / "data")
        common = ["--data-dir", data, "--rows-per-file", "128",
                  "--model", "ResNetTiny", "--num-classes", "5",
                  "--image-size", "16", "--batch-size", "32",
                  "--warmup-epochs", "1", "--lr-strategy", "cosine",
                  "--lr", "0.03", "--no-augment",
                  "--ckpt-dir", str(tmp_path / "ckpt")]
        assert main(["--make-synthetic", "2", "--epochs", "1"] + common) == 0
        versions = [n for n in os.listdir(tmp_path / "ckpt")
                    if n.startswith("ckpt-")]
        assert versions
        # resume to epoch 2: must not error and must add a version
        assert main(["--epochs", "2"] + common) == 0
        versions2 = [n for n in os.listdir(tmp_path / "ckpt")
                     if n.startswith("ckpt-")]
        assert len(versions2) > len(versions)
