"""The r6 overlapped serving path: request pipelining, staged batcher,
adaptive coalescing, gather-send wire, and the satellite contracts
(dead-teacher pruning, per-part top-k validation, jax-free wire import).

Invariant focus: D1-D3 must survive pipelining — responses must pair
with THEIR requests after a worker dies with several in flight, and the
reader must still yield in source order with depth > 1.
"""

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from edl_tpu.distill import tensor_wire
from edl_tpu.distill.reader import (DistillReader, EdlDistillError,
                                    _EpochPipeline)
from edl_tpu.distill.teacher_server import (Batcher, TeacherClient,
                                            TeacherServer)
from tests.test_distill_reader import (check_epoch, make_batches,
                                       ref_logits, _FnTeacherClient)


# -- pipelined fake teachers (no network, value-checkable) -----------------

class _AsyncHandle:
    def __init__(self, client, feeds):
        self._client = client
        self._feeds = feeds

    def result(self):
        c = self._client
        c.resolved += 1
        if c.fail_after is not None and c.resolved > c.fail_after:
            raise ConnectionError("teacher died mid-flight")
        if c.delay:
            time.sleep(c.delay)
        return {"teacher_logits": ref_logits(self._feeds["image"])}


class _AsyncFnTeacherClient:
    """predict_async-capable fake: the worker pipelines against it.
    ``fail_after=N``: the connection dies when resolving result N+1 —
    with depth > 1 several requests are in flight at that moment."""

    def __init__(self, endpoint, delay=0.0, fail_after=None):
        self.endpoint = endpoint
        self.delay = delay
        self.fail_after = fail_after
        self.resolved = 0
        self.sent = 0
        self.max_inflight_seen = 0

    def predict_async(self, feeds):
        self.sent += 1
        self.max_inflight_seen = max(self.max_inflight_seen,
                                     self.sent - self.resolved)
        return _AsyncHandle(self, feeds)

    def predict(self, feeds):
        return self.predict_async(feeds).result()

    def close(self):
        pass


# -- reader pipelining ------------------------------------------------------

def test_reader_source_order_with_depth():
    """D2 regression with depth > 1: teachers of very different speeds,
    several requests in flight each — batches still come back in source
    order, values exact."""
    delays = {"fast": 0.0, "slow": 0.02}
    clients = {}

    def factory(ep):
        clients[ep] = _AsyncFnTeacherClient(ep, delay=delays[ep])
        return clients[ep]

    batches = make_batches(n_batches=8, rows=16)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"],
                       teachers=["fast", "slow"], teacher_batch_size=4,
                       pipeline_depth=3, client_factory=factory)
    check_epoch(batches, list(dr()))
    # pipelining actually happened: some client held > 1 in flight
    assert max(c.max_inflight_seen for c in clients.values()) > 1


def test_pipelined_worker_death_requeues_all_inflight():
    """D1+D3 under churn: a teacher dies while holding several in-flight
    requests; every one of them must be re-served by the survivor, each
    response matching its request by value, order preserved."""
    dying = {}

    def factory(ep):
        if ep == "dying":
            dying[ep] = _AsyncFnTeacherClient(ep, delay=0.005,
                                              fail_after=2)
            return dying[ep]
        return _AsyncFnTeacherClient(ep, delay=0.002)

    batches = make_batches(n_batches=10, rows=16)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"],
                       teachers=["good", "dying"], teacher_batch_size=4,
                       pipeline_depth=3, manage_interval=0.05,
                       client_factory=factory)
    check_epoch(batches, list(dr()))
    # it really died holding work: more sent than resolved at death
    assert dying["dying"].sent > dying["dying"].resolved


def test_sync_only_client_still_works_at_depth():
    """Clients without predict_async (the pre-r6 contract) degrade to
    depth 1 — same pipeline, no behavior change."""
    batches = make_batches(n_batches=4, rows=16)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"], teachers=["t0"],
                       teacher_batch_size=4, pipeline_depth=8,
                       client_factory=lambda ep: _FnTeacherClient(ep))
    check_epoch(batches, list(dr()))


def test_window_scales_with_pipeline_depth():
    dr = DistillReader(lambda: iter([]), feeds=["image"], predicts=["p"],
                       teachers=["a", "b"], pipeline_depth=6,
                       client_factory=lambda ep: _FnTeacherClient(ep))
    p = _EpochPipeline(dr)
    assert p._sem_slots == (6 + 1) * 2 + 2   # D5: (depth+1)*teachers+2


# -- satellite: dead-teacher pruning ---------------------------------------

def test_departed_dead_teacher_pruned_no_deadman():
    """Discovery mode: a teacher that died AND was removed from the
    assignment must not permanently trip the deadman (the D6 docstring's
    scale-to-zero promise) — the epoch waits for the balancer and a
    later teacher completes it."""
    batches = make_batches(n_batches=2, rows=8)
    start = time.monotonic()

    def servers():
        t = time.monotonic() - start
        if t < 0.3:
            return ["dead"]      # assigned but connect-refusing
        if t < 1.2:
            return []            # departed AND removed from assignment
        return ["good"]

    def factory(ep):
        if ep == "dead":
            raise ConnectionRefusedError("refused")
        return _FnTeacherClient(ep)

    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"],
                       discovery="unused:0", service="svc",
                       teacher_batch_size=4, manage_interval=0.05,
                       deadman_timeout=0.8, client_factory=factory)
    dr._get_servers = servers
    # without pruning, dead_teachers["dead"] keeps empty_pool_ok False
    # and the deadman trips at ~0.8s < the 1.2s empty window
    check_epoch(batches, list(dr()))


# -- satellite: per-part top-k validation ----------------------------------

def test_sparse_topk_mismatch_names_endpoint():
    class _WrongK:
        def __init__(self, ep):
            self.endpoint = ep

        def predict(self, feeds):
            rows = len(feeds["image"])
            return {"teacher_logits.idx": np.zeros((rows, 2), np.int32),
                    "teacher_logits.val": np.zeros((rows, 2), np.float16)}

        def close(self):
            pass

    batches = make_batches(n_batches=2, rows=8)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"], teachers=["t0:1"],
                       teacher_batch_size=4, compress_topk=4,
                       sparse_predicts=True,
                       client_factory=lambda ep: _WrongK(ep))
    with pytest.raises(EdlDistillError) as ei:
        list(dr())
    msg = str(ei.value)
    assert "t0:1" in msg            # names the offending endpoint
    assert "top-2" in msg and "4" in msg


# -- client/server pipelining over real TCP --------------------------------

@pytest.fixture
def echo_teacher():
    def predict(feeds):
        return {"teacher_logits": ref_logits(feeds["image"])}
    with TeacherServer(predict, host="127.0.0.1", max_wait=0.001) as srv:
        yield f"127.0.0.1:{srv.port}"


def test_client_pipelining_seq_roundtrip(echo_teacher):
    c = TeacherClient(echo_teacher, max_inflight=16)
    try:
        feeds = [np.full((2, 3), float(i), np.float32) for i in range(8)]
        handles = [c.predict_async({"image": f}) for f in feeds]
        assert c.inflight() == 8
        # a control op rides the same FIFO stream mid-flight
        assert c.ping()
        for f, h in zip(feeds, handles):
            np.testing.assert_allclose(h.result()["teacher_logits"],
                                       ref_logits(f), rtol=1e-6)
        assert c.inflight() == 0
        stats = c.stats()
        assert stats["served_requests"] >= 8
    finally:
        c.close()


def test_pipelined_responses_resolve_out_of_submission_order(echo_teacher):
    """result() on a LATER handle first: earlier responses are absorbed
    into their handles along the way and stay readable."""
    c = TeacherClient(echo_teacher, max_inflight=8)
    try:
        feeds = [np.full((1, 4), float(i), np.float32) for i in range(4)]
        handles = [c.predict_async({"image": f}) for f in feeds]
        np.testing.assert_allclose(handles[3].result()["teacher_logits"],
                                   ref_logits(feeds[3]), rtol=1e-6)
        np.testing.assert_allclose(handles[0].result()["teacher_logits"],
                                   ref_logits(feeds[0]), rtol=1e-6)
    finally:
        c.close()


def test_reader_over_real_server_with_depth(echo_teacher):
    batches = make_batches(n_batches=5, rows=24)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"],
                       teachers=[echo_teacher], teacher_batch_size=8,
                       pipeline_depth=4)
    check_epoch(batches, list(dr()))


# -- adaptive coalescing ----------------------------------------------------

def test_adaptive_coalesce_grows_batches_while_device_busy():
    """While a group computes, newly arrived requests keep coalescing
    past max_wait — the mean device batch must climb above the 4-row
    request size (the r6 acceptance: mean climbs off one request)."""
    def predict(feeds):
        time.sleep(0.004)   # a busy device
        return {"y": feeds["x"]}

    b = Batcher(predict, max_batch=32, max_wait=0.0005).start()
    try:
        errs = []

        def runner():
            for _ in range(8):
                r = b.submit({"x": np.ones((4, 2), np.float32)})
                r.done.wait(10.0)
                if r.error is not None or r.result["y"].shape != (4, 2):
                    errs.append(r.error or "bad shape")
                    return

        threads = [threading.Thread(target=runner) for _ in range(4)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert not errs
        stats = b.stats()
        assert stats["served_rows"] == 4 * 8 * 4
        assert stats["batch_rows_mean"] > 4.0, stats
        assert stats["pending_hwm"] >= 2
    finally:
        b.stop()


def test_staged_batcher_slices_results_exactly():
    """Per-request result slicing across the staged pipeline: every
    submitter gets ITS rows back (values, not just shapes)."""
    def predict(feeds):
        return {"y": feeds["x"] * 2.0}

    b = Batcher(predict, max_batch=64, max_wait=0.05).start()
    try:
        reqs = []

        def submit(i):
            reqs.append((i, b.submit(
                {"x": np.full((3, 2), float(i), np.float32)})))

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(6)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        for i, req in reqs:
            req.done.wait(5.0)
            assert req.error is None
            np.testing.assert_allclose(req.result["y"],
                                       np.full((3, 2), 2.0 * i))
    finally:
        b.stop()


def test_batcher_failure_fails_only_that_group():
    fail_on = {"flag": True}

    def predict(feeds):
        if fail_on["flag"]:
            raise RuntimeError("boom")
        return {"y": feeds["x"]}

    b = Batcher(predict, max_batch=8, max_wait=0.001).start()
    try:
        r1 = b.submit({"x": np.ones((2, 2), np.float32)})
        r1.done.wait(5.0)
        assert r1.error is not None and "boom" in r1.error
        fail_on["flag"] = False
        r2 = b.submit({"x": np.ones((2, 2), np.float32)})
        r2.done.wait(5.0)
        assert r2.error is None
    finally:
        b.stop()


# -- tensor wire gather send ------------------------------------------------

def test_wire_gather_send_roundtrip():
    a, b = socket.socketpair()
    tensors = {
        "big": np.arange(300000, dtype=np.float32).reshape(500, 600),
        "empty": np.zeros((0, 5), np.float32),
        "scalar": np.array(7, np.int64),
        "noncontig": np.arange(100, dtype=np.float32).reshape(10, 10).T,
        "u8": np.arange(16, dtype=np.uint8),
    }
    got = {}

    def rx():
        got["meta"], got["tensors"] = tensor_wire.recv_tensors(b)

    th = threading.Thread(target=rx)
    th.start()
    tensor_wire.send_tensors(a, {"op": "x", "seq": 3}, tensors)
    th.join(10.0)
    assert not th.is_alive()
    assert got["meta"]["seq"] == 3
    for name, want in tensors.items():
        np.testing.assert_array_equal(got["tensors"][name], want)
    a.close()
    b.close()


# -- satellite: wire-only import stays jax-free ----------------------------

def test_distill_import_is_jax_free():
    """`import edl_tpu.distill` must work for wire-only consumers that
    only need TeacherClient + numpy (sharded_teacher loads lazily)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        "import sys\n"
        "pre = 'jax' in sys.modules\n"
        "import edl_tpu.distill\n"
        "from edl_tpu.distill import TeacherClient, DistillReader\n"
        "if not pre:\n"
        "    assert 'jax' not in sys.modules, 'distill pulled in jax'\n"
        "import edl_tpu.distill as d\n"
        "assert callable(d.sharded_predict_fn)\n"   # lazy path resolves
        "print('OK')\n")
    env = {**os.environ,
           "PYTHONPATH": root + os.pathsep + os.environ.get("PYTHONPATH",
                                                            "")}
    out = subprocess.run([sys.executable, "-c", code], cwd=root, env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout
