"""Distributed lock + leader election (coord/lock.py).

The missing-from-reference test suite for the etcd lock/election pattern
(pkg/master/etcd_client.go:100-131): mutual exclusion, crash takeover via
lease expiry, fencing (held() goes False on loss), and election over the
real TCP store.
"""

import time

import pytest

from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.lock import DistributedLock, EdlLockError, LeaderElection
from edl_tpu.coord.server import StoreServer
from edl_tpu.coord.store import InMemStore


@pytest.fixture
def store():
    return InMemStore()  # real clock: lock keepalive threads need time.sleep


@pytest.fixture
def server():
    with StoreServer(port=0, host="127.0.0.1", sweep_interval=0.05) as srv:
        yield srv


class TestDistributedLock:
    def test_mutual_exclusion(self, store):
        a = DistributedLock(store, "/l", "A", ttl=5)
        b = DistributedLock(store, "/l", "B", ttl=5)
        assert a.try_acquire()
        assert not b.try_acquire()
        assert a.held() and not b.held()
        a.release()
        assert b.try_acquire()
        b.release()

    def test_reentrant_same_owner(self, store):
        a = DistributedLock(store, "/l", "A", ttl=5)
        assert a.try_acquire()
        assert a.try_acquire()  # idempotent while held
        a.release()

    def test_context_manager(self, store):
        with DistributedLock(store, "/l", "A", ttl=5) as a:
            assert a.held()
            b = DistributedLock(store, "/l", "B", ttl=5)
            assert not b.acquire(timeout=0.3)
        # released on exit
        assert DistributedLock(store, "/l", "C", ttl=5).try_acquire()

    def test_acquire_blocks_until_released(self, store):
        import threading
        a = DistributedLock(store, "/l", "A", ttl=5)
        b = DistributedLock(store, "/l", "B", ttl=5)
        assert a.try_acquire()
        got = []
        t = threading.Thread(target=lambda: got.append(
            b.acquire(timeout=10, poll=0.05)))
        t.start()
        time.sleep(0.2)
        a.release()
        t.join(timeout=10)
        assert got == [True]
        b.release()

    def test_expiry_takeover_and_fencing(self, server):
        """Partitioned holder: its lease dies server-side, a rival takes
        the lock; the zombie's held() goes False within its ttl even
        though no loss event reached it yet (renewal-age fencing)."""
        sa = StoreClient(f"127.0.0.1:{server.port}")
        sb = StoreClient(f"127.0.0.1:{server.port}")
        a = DistributedLock(sa, "/l", "A", ttl=0.4)
        b = DistributedLock(sb, "/l", "B", ttl=5)
        assert a.try_acquire()
        sb.lease_revoke(a._hold.lease)  # server-side death of A's lease
        assert b.acquire(timeout=10, poll=0.1)
        assert b.held()
        # the zombie must know it lost before any privileged write
        deadline = time.time() + 5
        while a.held() and time.time() < deadline:
            time.sleep(0.05)
        assert not a.held()
        b.release()
        a.release()  # late zombie release is harmless

    def test_stalled_keepalive_flips_held_within_ttl(self, server):
        """A keeper that stops renewing (GC pause analogue) must flip
        held() False by renewal age alone — no loss event ever fires."""
        sa = StoreClient(f"127.0.0.1:{server.port}")
        a = DistributedLock(sa, "/l", "A", ttl=0.5)
        assert a.try_acquire()
        # stall: keeper thread killed silently, lost never set
        a._hold.stop.set()
        a._hold.keeper.join(timeout=2)
        a._hold.stop.clear()  # held() must flip by renewal age alone
        time.sleep(0.6)
        assert not a.held()

    def test_release_never_deletes_successor(self, server):
        sa = StoreClient(f"127.0.0.1:{server.port}")
        sb = StoreClient(f"127.0.0.1:{server.port}")
        a = DistributedLock(sa, "/l", "A", ttl=0.4)
        b = DistributedLock(sb, "/l", "B", ttl=5)
        assert a.try_acquire()
        sb.lease_revoke(a._hold.lease)
        assert b.acquire(timeout=10, poll=0.1)
        a.release()  # late zombie release
        rec = sb.get("/l")
        assert rec is not None and rec.value == "B"
        b.release()

    def test_context_manager_raises_on_timeout(self, store):
        a = DistributedLock(store, "/l", "A", ttl=5)
        assert a.try_acquire()
        b = DistributedLock(store, "/l", "B", ttl=5)
        b.acquire = lambda timeout=None, poll=0.2: False  # force failure
        with pytest.raises(EdlLockError):
            b.__enter__()
        a.release()


class TestLeaderElection:
    def test_campaign_and_observe(self, store):
        ea = LeaderElection(store, "/leader", "A", ttl=5)
        eb = LeaderElection(store, "/leader", "B", ttl=5)
        assert ea.campaign(timeout=5)
        assert ea.is_leader()
        assert not eb.campaign(timeout=0.3)
        assert eb.leader() == "A"
        ea.resign()
        assert eb.campaign(timeout=5)
        assert eb.leader() == "B"
        eb.resign()

    def test_on_lost_fires_on_lease_loss(self, server):
        sa = StoreClient(f"127.0.0.1:{server.port}")
        lost = []
        ea = LeaderElection(store=sa, key="/leader", owner="A", ttl=0.4,
                            on_lost=lambda: lost.append(True))
        assert ea.campaign(timeout=5)
        sa.lease_revoke(ea.lock._hold.lease)  # partition: lease dies server-side
        deadline = time.time() + 5
        while not lost and time.time() < deadline:
            time.sleep(0.05)
        assert lost == [True]
        assert not ea.is_leader()
