"""Dynamic loss scaling (train/amp.py) — the reference's fp16 knob."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from edl_tpu.train.amp import (DynamicLossScale, all_finite,
                               scaled_value_and_grad,
                               update_scale_and_select)
from edl_tpu.train.state import TrainState
from edl_tpu.train.step import make_train_step


def _state(w=1.0):
    return TrainState.create(
        apply_fn=None,
        params={"w": jnp.float32(w)},
        tx=optax.sgd(0.1))


class TestPrimitives:
    def test_all_finite(self):
        assert bool(all_finite({"a": jnp.ones(3)}))
        assert not bool(all_finite({"a": jnp.array([1.0, jnp.inf])}))
        assert not bool(all_finite({"a": jnp.array([jnp.nan])}))

    def test_grads_unscaled_back(self):
        ls = DynamicLossScale.create(init_scale=1024.0)

        def loss(p):
            return (p["w"] ** 2, {})

        (loss_val, _), grads = scaled_value_and_grad(
            loss, {"w": jnp.float32(3.0)}, ls)
        assert float(loss_val) == 9.0  # reported loss is UNscaled
        np.testing.assert_allclose(float(grads["w"]), 6.0, rtol=1e-6)

    def test_overflow_halves_and_keeps_old(self):
        ls = DynamicLossScale.create(init_scale=8.0)
        bad = {"w": jnp.float32(jnp.nan)}
        new, old = {"w": jnp.float32(2.0)}, {"w": jnp.float32(1.0)}
        ls2, sel, finite = update_scale_and_select(ls, bad, new, old)
        assert not bool(finite)
        assert float(ls2.scale) == 4.0
        assert float(sel["w"]) == 1.0  # step skipped
        assert int(ls2.growth_count) == 0

    def test_growth_after_interval(self):
        ls = DynamicLossScale(scale=jnp.float32(8.0),
                              growth_count=jnp.int32(1),
                              growth_interval=2)
        good = {"w": jnp.float32(1.0)}
        ls2, sel, finite = update_scale_and_select(
            ls, good, {"w": jnp.float32(2.0)}, {"w": jnp.float32(1.0)})
        assert bool(finite)
        assert float(ls2.scale) == 16.0  # grew at the interval
        assert int(ls2.growth_count) == 0
        assert float(sel["w"]) == 2.0

    def test_scale_floor_and_cap(self):
        low = DynamicLossScale(scale=jnp.float32(1.0),
                               growth_count=jnp.int32(0),
                               growth_interval=2000)
        ls2, _, _ = update_scale_and_select(
            low, {"w": jnp.float32(jnp.inf)},
            {"w": jnp.float32(0.0)}, {"w": jnp.float32(0.0)})
        assert float(ls2.scale) == 1.0  # floor
        high = DynamicLossScale(scale=jnp.float32(2.0 ** 24),
                                growth_count=jnp.int32(10),
                                growth_interval=1)
        ls3, _, _ = update_scale_and_select(
            high, {"w": jnp.float32(1.0)},
            {"w": jnp.float32(0.0)}, {"w": jnp.float32(0.0)})
        assert float(ls3.scale) == 2.0 ** 24  # cap


class TestAmpTrainStep:
    def test_trains_like_unscaled(self):
        def loss_fn(state, params, batch):
            pred = params["w"] * batch["x"]
            return jnp.mean((pred - batch["y"]) ** 2), {}

        batch = {"x": jnp.arange(1.0, 5.0), "y": 3.0 * jnp.arange(1.0, 5.0)}
        plain = make_train_step(loss_fn, donate=False)
        amp = make_train_step(loss_fn, donate=False, loss_scale=True)
        s_plain, s_amp = _state(0.0), _state(0.0)
        ls = DynamicLossScale.create()
        for _ in range(10):
            s_plain, m_plain = plain(s_plain, batch)
            s_amp, m_amp, ls = amp(s_amp, batch, ls)
            assert bool(m_amp["finite"])
        np.testing.assert_allclose(float(s_amp.params["w"]),
                                   float(s_plain.params["w"]), rtol=1e-5)

    def test_overflow_step_skipped_in_train_step(self):
        def loss_fn(state, params, batch):
            # overflow when scale is huge and loss moderate: force a nan
            return params["w"] * jnp.float32(jnp.inf), {}

        amp = make_train_step(loss_fn, donate=False, loss_scale=True)
        state = _state(1.0)
        ls = DynamicLossScale.create(init_scale=2.0 ** 15)
        state2, m, ls2 = amp(state, {"x": jnp.zeros(1)}, ls)
        assert not bool(m["finite"])
        assert float(state2.params["w"]) == 1.0  # unchanged
        assert float(ls2.scale) == 2.0 ** 14
