"""Discovery server/client/registrar integration over real TCP + InMemStore.

Mirrors the reference's test_distill_reader.sh flow (etcd + registrar +
discovery server + client) without external binaries: the coordination
store is in-process, the discovery wire is real sockets.
"""

import socket
import threading
import time

import pytest

from edl_tpu.coord.registry import ServiceRegistry
from edl_tpu.coord.store import InMemStore
from edl_tpu.distill.discovery_client import DiscoveryClient, EdlDiscoveryError
from edl_tpu.distill.discovery_server import (BALANCE_SERVICE,
                                              DiscoveryServer)
from edl_tpu.distill.registrar import TeacherRegistrar


@pytest.fixture
def store():
    return InMemStore()


@pytest.fixture
def registry(store):
    return ServiceRegistry(store, root="edl_distill")


def make_server(store, **kw):
    kw.setdefault("port", 0)
    kw.setdefault("host", "127.0.0.1")   # loopback bind => loopback advertise
    kw.setdefault("tick_interval", 0.1)
    return DiscoveryServer(store, **kw).start()


def test_register_heartbeat_assignment(store, registry):
    regs = [registry.register("svc", f"127.0.0.1:{9000+i}", ttl=5.0)
            for i in range(2)]
    with make_server(store) as _srv:
        client = DiscoveryClient(_srv.endpoint, "svc",
                                 heartbeat_interval=0.1).start()
        try:
            servers = client.wait_for_servers(timeout=10.0)
            assert set(servers) == {"127.0.0.1:9000", "127.0.0.1:9001"}

            # Teacher joins: the heartbeat delta must install it.
            regs.append(registry.register("svc", "127.0.0.1:9002", ttl=5.0))
            deadline = time.time() + 10
            while time.time() < deadline:
                if len(client.get_servers()) == 3:
                    break
                time.sleep(0.05)
            assert len(client.get_servers()) == 3

            # Teacher leaves: assignment shrinks.
            regs[0].stop()
            registry.deregister("svc", "127.0.0.1:9000")
            deadline = time.time() + 10
            while time.time() < deadline:
                if "127.0.0.1:9000" not in client.get_servers():
                    break
                time.sleep(0.05)
            assert "127.0.0.1:9000" not in client.get_servers()
        finally:
            client.stop()
            for r in regs[1:]:
                r.stop()


def test_two_clients_share_one_teacher(store, registry):
    reg = registry.register("svc", "127.0.0.1:9100", ttl=5.0)
    with make_server(store) as srv:
        c1 = DiscoveryClient(srv.endpoint, "svc",
                             heartbeat_interval=0.1).start()
        c2 = DiscoveryClient(srv.endpoint, "svc",
                             heartbeat_interval=0.1).start()
        try:
            assert c1.wait_for_servers(10.0) == ["127.0.0.1:9100"]
            assert c2.wait_for_servers(10.0) == ["127.0.0.1:9100"]
        finally:
            c1.stop()
            c2.stop()
            reg.stop()


def test_silent_client_expires(store, registry):
    reg = registry.register("svc", "127.0.0.1:9200", ttl=5.0)
    with make_server(store, client_ttl=0.5) as srv:
        client = DiscoveryClient(srv.endpoint, "svc",
                                 heartbeat_interval=60.0).start()  # silent
        try:
            client.wait_for_servers(10.0)
            deadline = time.time() + 10
            while time.time() < deadline:
                stats = srv.table.stats()
                if stats.get("svc", {}).get("clients") == 0:
                    break
                time.sleep(0.05)
            assert srv.table.stats()["svc"]["clients"] == 0
        finally:
            client.stop()
            reg.stop()


def test_redirect_to_shard_owner(store, registry):
    reg = registry.register("svc", "127.0.0.1:9300", ttl=5.0)
    a = make_server(store)
    b = make_server(store)
    try:
        # Let both replicas see each other in the __balance__ ring.
        deadline = time.time() + 10
        while time.time() < deadline:
            if (len(a.table._ring.nodes) == 2
                    and len(b.table._ring.nodes) == 2):
                break
            time.sleep(0.05)
        owner = a.table.owner_of("svc")
        assert owner == b.table.owner_of("svc"), "replicas disagree on owner"
        other = a if owner == b.endpoint else b

        client = DiscoveryClient(other.endpoint, "svc",
                                 heartbeat_interval=0.1).start()
        try:
            client.wait_for_servers(10.0)
            assert client._connected_to == owner, \
                "client not redirected to the shard owner"
        finally:
            client.stop()
    finally:
        a.stop()
        b.stop()
        reg.stop()


def test_registrar_probes_then_registers(store, registry):
    # Teacher endpoint that starts listening only after a delay: the
    # registrar must wait for aliveness, then the discovery path sees it.
    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    port = lst.getsockname()[1]
    endpoint = f"127.0.0.1:{port}"

    def listen_later():
        time.sleep(0.5)
        lst.listen(1)

    threading.Thread(target=listen_later, daemon=True).start()
    registrar = TeacherRegistrar(store, "svc", endpoint, ttl=5.0,
                                 probe_timeout=10.0, probe_interval=0.1)
    t0 = time.monotonic()
    registrar.start()
    assert time.monotonic() - t0 >= 0.3, "registered before server was up"
    try:
        metas = registry.get_service("svc")
        assert [m.server for m in metas] == [endpoint]
    finally:
        registrar.stop()
        lst.close()
    assert registry.get_service("svc") == []


def test_registrar_times_out_when_never_alive(store):
    registrar = TeacherRegistrar(store, "svc", "127.0.0.1:1",  # closed port
                                 probe_timeout=0.5, probe_interval=0.1)
    with pytest.raises(Exception):
        registrar.start()


def test_discovery_replicas_register_in_ring(store, registry):
    with make_server(store) as srv:
        metas = registry.get_service(BALANCE_SERVICE)
        assert [m.server for m in metas] == [srv.endpoint]


def test_busy_teachers_deprioritized_via_registrar_info(store, registry):
    """Registrar-published `util` flows through the discovery tick into
    the balancer's tie-break (balance.py I6): with more teachers than
    the clients can use, the busiest teacher stays idle."""
    import json

    regs = []
    utils = {"127.0.0.1:9100": 0.95, "127.0.0.1:9101": 0.05,
             "127.0.0.1:9102": 0.10, "127.0.0.1:9103": 0.15,
             "127.0.0.1:9104": 0.20}
    for ep, u in utils.items():
        regs.append(registry.register(
            "svc", ep, info=json.dumps({"util": u}), ttl=5.0))
    with make_server(store) as srv:
        clients = [DiscoveryClient(srv.endpoint, "svc",
                                   heartbeat_interval=0.1).start()
                   for _ in range(2)]
        try:
            for c in clients:
                c.wait_for_servers(timeout=10.0)
            # The FIRST client is briefly assigned all 5 teachers while
            # alone (client_cap=5//1); poll to the 2-client steady state
            # where client_cap = 5//2 = 2 -> 4 links total.
            deadline = time.time() + 10
            used = set()
            while time.time() < deadline:
                sets = [set(c.get_servers()) for c in clients]
                used = sets[0] | sets[1]
                if all(len(s) == 2 for s in sets):
                    break
                time.sleep(0.1)
            assert all(len(set(c.get_servers())) == 2 for c in clients)
            # the busy teacher is the one left out
            assert "127.0.0.1:9100" not in used, used
            assert len(used) == 4
        finally:
            for c in clients:
                c.stop()
    for r in regs:
        r.stop()
