"""The watch/subscribe primitive (ISSUE 8): event ordering, revision
resume across reconnects, lease-expiry DELETE delivery, the compaction ->
`compacted` -> get_prefix resync contract, cancel/teardown hygiene — run
as one parity suite against InMemStore, the Python StoreServer, and
(skip-if-unbuilt) the native C++ edl-store — plus the converted
consumers: ServiceWatcher callbacks at event latency, lock/election
handoff waking on the holder's DELETE, the scaler ticking on fresh
utilization, the redis pub/sub flavor, and the EDL_TPU_COORD_WATCH=0
escape hatch pinning the pure-polling fallback.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time

import pytest

from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.server import StoreServer
from edl_tpu.coord.store import InMemStore, try_watch
from edl_tpu.utils import net

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native", "store")


# -- parity fixtures ---------------------------------------------------------

@pytest.fixture(scope="session")
def native_binary():
    build = subprocess.run(["make", "-C", NATIVE_DIR], capture_output=True,
                           text=True)
    if build.returncode != 0:
        pytest.skip(f"native build unavailable:\n{build.stderr[-500:]}")
    return os.path.join(NATIVE_DIR, "edl-store")


def _start_native(binary, tmp_path):
    port = net.free_port()
    proc = subprocess.Popen(
        [binary, "--host", "127.0.0.1", "--port", str(port),
         "--sweep-interval", "0.05"],
        stdout=open(tmp_path / "native-watch.log", "ab"),
        stderr=subprocess.STDOUT)
    client = StoreClient(f"127.0.0.1:{port}", timeout=5.0)
    deadline = time.time() + 10
    while time.time() < deadline:
        if client.ping():
            return proc, client
        time.sleep(0.1)
    proc.kill()
    pytest.fail("edl-store never came up")


@pytest.fixture(params=["inmem", "server", "native"])
def watch_store(request, tmp_path):
    """The same Store API over all three engines; the suite asserting
    identical watch semantics against each IS the parity contract."""
    if request.param == "inmem":
        yield InMemStore()
    elif request.param == "server":
        with StoreServer(port=0, host="127.0.0.1",
                         sweep_interval=0.05) as srv:
            client = StoreClient(f"127.0.0.1:{srv.port}")
            client._test_server = srv  # for leak introspection
            yield client
            client.close()
    else:
        binary = request.getfixturevalue("native_binary")
        proc, client = _start_native(binary, tmp_path)
        yield client
        client.close()
        proc.terminate()
        proc.wait(timeout=5)


def _drain(watch, n_events, timeout=5.0):
    """Collect exactly n events (flattening batches); fail on timeout."""
    events, deadline = [], time.monotonic() + timeout
    while len(events) < n_events and time.monotonic() < deadline:
        batch = watch.get(timeout=max(0.0, deadline - time.monotonic()))
        if batch is None:
            break
        assert not batch.compacted, f"unexpected compaction: {batch}"
        events.extend(batch.events)
    assert len(events) == n_events, f"got {len(events)}/{n_events}: {events}"
    return events


# -- the primitive, across all three engines ---------------------------------

def test_events_ordered_and_prefix_filtered(watch_store):
    s = watch_store
    watch = s.watch("/a/")
    try:
        s.put("/a/x", "1")
        s.put("/b/noise", "n")      # outside the prefix: never delivered
        s.put("/a/y", "2")
        s.delete("/a/x")
        events = _drain(watch, 3)
        assert [(e.type, e.key, e.value) for e in events] == [
            ("PUT", "/a/x", "1"), ("PUT", "/a/y", "2"),
            ("DELETE", "/a/x", "1")]
        revs = [e.revision for e in events]
        assert revs == sorted(revs) and len(set(revs)) == 3
        assert watch.get(timeout=0.1) is None
    finally:
        watch.cancel()


def test_resume_from_revision_exactly_once(watch_store):
    s = watch_store
    r0 = s.put("/r/seen", "old")
    s.put("/r/a", "1")
    s.put("/r/b", "2")
    watch = s.watch("/r/", start_revision=r0)
    try:
        events = _drain(watch, 2)
        assert [e.key for e in events] == ["/r/a", "/r/b"]
        # live events continue after the replayed backlog, no dupes
        s.put("/r/c", "3")
        assert _drain(watch, 1)[0].key == "/r/c"
        assert watch.get(timeout=0.1) is None
    finally:
        watch.cancel()


def test_lease_expiry_delivers_delete(watch_store):
    s = watch_store
    watch = s.watch("/lease/")
    try:
        lease = s.lease_grant(0.25)
        s.put("/lease/k", "v", lease=lease)
        assert _drain(watch, 1)[0].type == "PUT"
        # expiry: server flavors sweep on a thread; the in-mem flavor
        # expires on any public call (the documented lazy contract)
        deadline = time.monotonic() + 5.0
        batch = None
        while batch is None and time.monotonic() < deadline:
            s.get("/lease/other")  # nudges lazy expiry on in-mem
            batch = watch.get(timeout=0.2)
        assert batch is not None, "lease-expiry DELETE never delivered"
        assert batch.events[0].type == "DELETE"
        assert batch.events[0].key == "/lease/k"
    finally:
        watch.cancel()


def test_compaction_forces_explicit_resync(watch_store):
    s = watch_store
    r0 = s.put("/c/0", "v")
    # overflow the bounded event history (4096) past r0
    for i in range(4200):
        s.put(f"/c/{i % 37}", str(i))
    watch = s.watch("/c/", start_revision=r0)
    try:
        batch = watch.get(timeout=5.0)
        assert batch is not None and batch.compacted, batch
        assert batch.events == () or list(batch.events) == []
        # the documented recovery: full get_prefix, then the stream is
        # live again from the compacted batch's revision
        records, rev = s.get_prefix("/c/")
        assert records and rev >= batch.revision
        s.put("/c/after", "resynced")
        got = _drain(watch, 1)
        assert got[0].key == "/c/after"
    finally:
        watch.cancel()


def test_cancel_leaks_nothing(watch_store):
    s = watch_store
    watch = s.watch("/x/")
    s.put("/x/1", "v")
    assert _drain(watch, 1)
    watch.cancel()
    assert watch.get(timeout=0.1) is None
    assert watch.cancelled
    # engine-side teardown: in-mem unregisters synchronously; the
    # servers notice the dead stream within ~2 heartbeats
    if isinstance(s, InMemStore):
        assert s.watcher_count() == 0
    elif hasattr(s, "_test_server"):
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline \
                and s._test_server.store.watcher_count():
            time.sleep(0.1)
        assert s._test_server.store.watcher_count() == 0
    # the store stays fully usable either way
    assert s.put("/x/2", "v") > 0


def test_lock_handoff_wakes_on_delete(watch_store):
    """Satellite: StoreLock waiters + election campaigns wake on the
    holder's DELETE. poll=5s would make a poll-driven handoff take
    seconds — the asserted latency proves the event path."""
    from edl_tpu.coord.lock import DistributedLock
    a = DistributedLock(watch_store, "/locks/m", "A", ttl=5)
    b = DistributedLock(watch_store, "/locks/m", "B", ttl=5)
    assert a.try_acquire()
    handoff = {}

    def waiter():
        t0 = time.monotonic()
        handoff["ok"] = b.acquire(timeout=10, poll=5.0)
        handoff["latency"] = time.monotonic() - t0

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.5)  # let B park on the watch
    a.release()
    t.join(timeout=10)
    assert handoff.get("ok") is True
    # event wakeup: far under the 5s poll (generous bound for CI)
    assert handoff["latency"] < 3.0, handoff
    b.release()


# -- reconnect / restart (TCP path) ------------------------------------------

def test_reconnect_resumes_without_loss_or_dup():
    """Kill the server mid-watch, mutate while it is down, restart on
    the same port + store: the client watch must deliver the missed
    events exactly once (resume-from-revision over the wire)."""
    store = InMemStore()
    srv = StoreServer(port=0, host="127.0.0.1", store=store,
                      sweep_interval=0.05).start()
    port = srv.port
    client = StoreClient(f"127.0.0.1:{port}")
    watch = client.watch("/j/", heartbeat=0.2)
    try:
        client.put("/j/before", "1")
        assert _drain(watch, 1)[0].key == "/j/before"
        srv.stop()
        store.put("/j/while-down-1", "2")   # engine survives the server
        store.put("/j/while-down-2", "3")
        srv2 = StoreServer(port=port, host="127.0.0.1", store=store,
                           sweep_interval=0.05).start()
        try:
            events = _drain(watch, 2, timeout=15.0)
            assert [e.key for e in events] == ["/j/while-down-1",
                                               "/j/while-down-2"]
            store.put("/j/after", "4")
            assert _drain(watch, 1, timeout=10.0)[0].key == "/j/after"
            assert watch.get(timeout=0.2) is None  # no duplicates
        finally:
            srv2.stop()
    finally:
        watch.cancel()
        client.close()


def test_server_restart_compaction_resyncs():
    """The native store documents that event history does not survive a
    restart; the Python analogue is a FRESH engine behind the same
    port. The resumed watch must then see `compacted`, never silently
    missing events."""
    srv = StoreServer(port=0, host="127.0.0.1", sweep_interval=0.05).start()
    port = srv.port
    client = StoreClient(f"127.0.0.1:{port}")
    watch = client.watch("/k/", heartbeat=0.2)
    try:
        client.put("/k/a", "1")
        assert _drain(watch, 1)
        srv.stop()
        # a fresh engine whose event window starts past the client's
        # resume revision (the native daemon does exactly this on
        # restart: history does not survive, first_event_rev = rev + 1)
        fresh = InMemStore(max_events=2)
        for i in range(6):  # revisions the old stream never saw
            fresh.put(f"/k/unseen{i}", "x")
        srv2 = StoreServer(port=port, host="127.0.0.1", store=fresh,
                           sweep_interval=0.05).start()
        try:
            deadline = time.monotonic() + 15.0
            batch = None
            while time.monotonic() < deadline:
                batch = watch.get(timeout=1.0)
                if batch is not None:
                    break
            # resume revision > fresh store's history start -> the
            # server cannot prove continuity -> explicit compaction
            assert batch is not None and batch.compacted, batch
        finally:
            srv2.stop()
    finally:
        watch.cancel()
        client.close()


# -- converted consumers -----------------------------------------------------

def test_service_watcher_fires_on_events_not_polls():
    """ServiceWatcher with a 30s poll interval: with watches the
    callbacks must land at event latency — a poll could not explain
    sub-second delivery."""
    from edl_tpu.coord.registry import ServiceRegistry
    store = InMemStore()
    registry = ServiceRegistry(store, root="t")
    added, removed = [], []
    add_ev, rm_ev = threading.Event(), threading.Event()
    watcher = registry.watch_service(
        "svc",
        on_add=lambda m: (added.append(m.server), add_ev.set()),
        on_remove=lambda m: (removed.append(m.server), rm_ev.set()),
        interval=30.0)
    try:
        registry.register_permanent("svc", "a:1", info="x")
        assert add_ev.wait(2.0), "on_add waited for a poll tick"
        assert added == ["a:1"]
        registry.deregister("svc", "a:1")
        assert rm_ev.wait(2.0), "on_remove waited for a poll tick"
        assert removed == ["a:1"]
        assert watcher.servers() == []
    finally:
        watcher.stop()


def test_cluster_watcher_sees_change_at_event_latency():
    from edl_tpu.collective import register as reg
    from edl_tpu.collective.cluster import Cluster, Pod
    from edl_tpu.collective.watcher import ClusterWatcher
    store = InMemStore()
    pods = []
    for i in range(2):
        pod = Pod(pod_id=f"p{i}", addr="127.0.0.1", port=7000 + i)
        r = reg.PodRegister(store, "wjob", pod, ttl=10.0)
        r.claim()
        pods.append((pod, r))
    baseline = Cluster(job_id="wjob", version=1,
                       pods=[p for p, _ in pods])
    watcher = ClusterWatcher(store, baseline, interval=30.0).start()
    try:
        time.sleep(0.3)
        assert not watcher.changed.is_set()
        t0 = time.monotonic()
        pods[1][1].release()  # departure -> DELETE on the rank prefix
        assert watcher.changed.wait(3.0), \
            "membership change waited for a poll tick"
        assert time.monotonic() - t0 < 3.0
    finally:
        watcher.stop()
        for _, r in pods:
            r.release()


def test_scaler_ticks_on_fresh_utilization_not_interval():
    """The scaler's reaction is no longer quantized to the interval: a
    fresh utilization PUT triggers a decision pass while the 30s
    fallback interval is still far away."""
    from edl_tpu.coord.collector import util_key
    from edl_tpu.scaler.controller import ScalerConfig, ScalerController
    from edl_tpu.scaler.policy import Proposal

    class HoldPolicy:
        def decide(self, views, now):
            return [Proposal(v.job_id, v.world_size, v.world_size, "hold")
                    for v in views]

        def restore(self, entries):
            pass

        def notify_resized(self, job_id, world, now):
            pass

    store = InMemStore()
    config = ScalerConfig()
    config.interval = 30.0
    config.min_tick_s = 0.0
    ctl = ScalerController(store, ["wjob"], HoldPolicy(), config=config,
                           dry_run=True, elect=False)
    ctl.start()
    try:
        deadline = time.monotonic() + 10.0
        while not ctl.journal.tail() and time.monotonic() < deadline:
            time.sleep(0.05)
        n0 = len(ctl.journal.tail())
        assert n0 >= 1, "first tick never ran"
        time.sleep(1.0)  # idle: no fresh util -> no extra ticks
        assert len(ctl.journal.tail()) == n0
        t0 = time.monotonic()
        store.put(util_key("wjob", "pod0"), json.dumps(
            {"examples_per_sec": 10.0, "published_unix": time.time(),
             "world_size": 1}))
        while len(ctl.journal.tail()) == n0 \
                and time.monotonic() - t0 < 10.0:
            time.sleep(0.05)
        reaction = time.monotonic() - t0
        assert len(ctl.journal.tail()) > n0, \
            "fresh utilization never triggered a tick"
        assert reaction < 10.0 < config.interval, reaction
    finally:
        ctl.stop()


def test_redis_pubsub_watch_flavor():
    from edl_tpu.coord.redis_store import RedisStore
    from edl_tpu.coord.resp import MiniRedis
    mini = MiniRedis().start()
    store = RedisStore(mini.endpoint)
    try:
        watch = store.watch("/svc/")
        time.sleep(0.2)  # let SUBSCRIBE land
        store.put("/svc/a", "v1")
        batch = watch.get(timeout=3.0)
        assert batch.events[0].type == "PUT"
        assert batch.events[0].key == "/svc/a"
        store.put("/other/x", "n")
        assert watch.get(timeout=0.3) is None  # prefix-filtered
        store.delete("/svc/a")
        batch = watch.get(timeout=3.0)
        assert batch.events[0].type == "DELETE"
        assert batch.events[0].value == "v1"
        # explicit revoke emits DELETEs (TTL expiry cannot — the
        # documented weaker contract; expiry_events=False keeps the
        # consumers' poll cadence as the net)
        lease = store.lease_grant(5.0)
        store.put("/svc/leased", "x", lease=lease)
        assert watch.get(timeout=3.0).events[0].type == "PUT"
        store.lease_revoke(lease)
        assert watch.get(timeout=3.0).events[0].type == "DELETE"
        assert not watch.expiry_events
        # no replay over pub/sub: a resume request is an immediate,
        # explicit resync signal
        resumed = store.watch("/svc/", start_revision=1)
        assert resumed.get(timeout=2.0).compacted
        resumed.cancel()
        watch.cancel()
    finally:
        store.close()
        mini.stop()


def test_escape_hatch_restores_pure_polling(monkeypatch):
    """EDL_TPU_COORD_WATCH=0 (satellite): try_watch refuses, no watcher
    registers anywhere, and the converted consumers still work on their
    original poll loops — the integration pin for the escape hatch."""
    from edl_tpu.coord.lock import DistributedLock
    from edl_tpu.coord.registry import ServiceRegistry
    monkeypatch.setenv("EDL_TPU_COORD_WATCH", "0")
    store = InMemStore()
    assert try_watch(store, "/any/") is None
    # ServiceWatcher: poll-driven callbacks still fire
    registry = ServiceRegistry(store, root="t")
    seen = threading.Event()
    watcher = registry.watch_service("svc", on_add=lambda m: seen.set(),
                                     interval=0.05)
    registry.register_permanent("svc", "a:1")
    assert seen.wait(2.0)
    assert store.watcher_count() == 0, "a watch leaked past the hatch"
    watcher.stop()
    # lock handoff still completes on the poll fallback
    a = DistributedLock(store, "/l", "A", ttl=5)
    b = DistributedLock(store, "/l", "B", ttl=5)
    assert a.try_acquire()
    got = []
    t = threading.Thread(
        target=lambda: got.append(b.acquire(timeout=5, poll=0.05)))
    t.start()
    time.sleep(0.2)
    a.release()
    t.join(timeout=10)
    assert got == [True]
    assert store.watcher_count() == 0
    b.release()


# -- native sanitizer selftests (CI sequential steps) ------------------------
# TSAN and ASan cannot share one binary, so the SAME watcher-churn
# scenario runs against each sanitizer build: tsan catches lock/race
# mistakes in the watch fan-out, asan+ubsan catches the memory half
# (use-after-free of a cancelled watcher's queue state, OOB in the
# frame codec, signed overflow in revision math).

@pytest.fixture(scope="session")
def tsan_binary():
    build = subprocess.run(["make", "-C", NATIVE_DIR, "tsan"],
                           capture_output=True, text=True)
    if build.returncode != 0:
        pytest.skip(f"tsan build unavailable:\n{build.stderr[-500:]}")
    return os.path.join(NATIVE_DIR, "edl-store-tsan")


@pytest.fixture(scope="session")
def asan_binary():
    build = subprocess.run(["make", "-C", NATIVE_DIR, "asan"],
                           capture_output=True, text=True)
    if build.returncode != 0:
        pytest.skip(f"asan build unavailable:\n{build.stderr[-500:]}")
    return os.path.join(NATIVE_DIR, "edl-store-asan")


@pytest.mark.slow
def test_native_watch_selftest_tsan(tsan_binary, tmp_path):
    """Concurrent watchers churning against concurrent mutators + the
    sweeper, under ThreadSanitizer: the watcher registry and fan-out
    ride the store's mutation path, so any locking mistake in the new
    code is a data race this run aborts on (halt_on_error)."""
    _watch_churn(tsan_binary, tmp_path,
                 env={"TSAN_OPTIONS":
                      "halt_on_error=1 exitcode=66 abort_on_error=0"},
                 report_marker="WARNING: ThreadSanitizer")


@pytest.mark.slow
def test_native_watch_selftest_asan(asan_binary, tmp_path):
    """The same churn under AddressSanitizer+UBSan: watcher churn
    allocates/frees per-watcher queue state on the mutation path, so a
    use-after-free or OOB there aborts the daemon mid-run."""
    _watch_churn(asan_binary, tmp_path,
                 env={"ASAN_OPTIONS":
                      "halt_on_error=1 exitcode=66 abort_on_error=0",
                      "UBSAN_OPTIONS": "halt_on_error=1 print_stacktrace=1"},
                 report_marker="ERROR: AddressSanitizer",
                 extra_markers=("runtime error:",))


def _watch_churn(binary, tmp_path, *, env, report_marker,
                 extra_markers=()):
    port = net.free_port()
    log_path = tmp_path / "san-watch.log"
    env = dict(os.environ, **env)
    proc = subprocess.Popen(
        [binary, "--host", "127.0.0.1", "--port", str(port),
         "--sweep-interval", "0.01"],
        stdout=open(log_path, "ab"), stderr=subprocess.STDOUT, env=env)
    boot = StoreClient(f"127.0.0.1:{port}", timeout=10.0)
    deadline = time.time() + 20
    while time.time() < deadline and not boot.ping():
        time.sleep(0.1)
    assert boot.ping(), "sanitizer daemon never came up"
    boot.close()

    errors, stop = [], threading.Event()

    def mutator(wid: int):
        try:
            c = StoreClient(f"127.0.0.1:{port}", timeout=10.0)
            for i in range(50):
                c.put(f"/w/{wid}/{i % 5}", str(i))
                if i % 4 == 0:
                    lease = c.lease_grant(0.05)  # sweeper-raced DELETEs
                    try:
                        c.put(f"/w/lease/{wid}", "x", lease=lease)
                    except Exception:  # noqa: BLE001 — the race is the point
                        pass
                if i % 7 == 0:
                    c.delete_prefix(f"/w/{wid}/")
            c.close()
        except Exception as exc:  # noqa: BLE001 — collected for assert
            errors.append(("mut", wid, exc))

    def watcher(wid: int):
        try:
            c = StoreClient(f"127.0.0.1:{port}", timeout=10.0)
            for _ in range(6):  # churn: subscribe, consume, cancel
                w = c.watch("/w/", heartbeat=0.05)
                until = time.monotonic() + 0.3
                while time.monotonic() < until:
                    w.get(timeout=0.1)
                w.cancel()
            c.close()
        except Exception as exc:  # noqa: BLE001 — collected for assert
            errors.append(("watch", wid, exc))

    threads = [threading.Thread(target=mutator, args=(w,)) for w in range(4)]
    threads += [threading.Thread(target=watcher, args=(w,)) for w in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    stop.set()
    try:
        assert not errors, f"client errors (daemon died mid-run?): {errors}"
        assert proc.poll() is None, \
            f"daemon exited {proc.returncode} — sanitizer report:\n" \
            f"{log_path.read_bytes().decode(errors='replace')[-3000:]}"
    finally:
        proc.terminate()
        proc.wait(timeout=10)
    report = log_path.read_bytes().decode(errors="replace")
    for marker in (report_marker, *extra_markers):
        assert marker not in report, report[-3000:]
