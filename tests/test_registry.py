"""Service registry: register/expiry/watch over both backends."""

import threading
import time

import pytest

from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.registry import ServiceRegistry
from edl_tpu.coord.server import StoreServer
from edl_tpu.coord.store import InMemStore


def test_register_and_read_inmem():
    reg = ServiceRegistry(InMemStore(), root="test")
    reg.register_permanent("teachers", "1.2.3.4:9000", info="{gpu:20%}")
    metas = reg.get_service("teachers")
    assert len(metas) == 1
    assert metas[0].server == "1.2.3.4:9000"
    assert metas[0].info == "{gpu:20%}"


def test_ephemeral_registration_lifecycle():
    with StoreServer(port=0, host="127.0.0.1", sweep_interval=0.05) as srv:
        client = StoreClient(f"127.0.0.1:{srv.port}")
        reg = ServiceRegistry(client, root="job0")
        r = reg.register("teachers", "127.0.0.1:9000", ttl=0.3)
        time.sleep(0.8)  # several TTLs: keeper must hold it alive
        assert [m.server for m in reg.get_service("teachers")] == ["127.0.0.1:9000"]
        r.stop()
        time.sleep(0.1)
        assert reg.get_service("teachers") == []
        client.close()


def test_double_register_rejected():
    from edl_tpu.utils.exceptions import EdlRegisterError

    reg = ServiceRegistry(InMemStore(), root="t")
    r = reg.register("svc", "h:1", ttl=10)
    with pytest.raises(EdlRegisterError):
        reg.register("svc", "h:1", ttl=10)
    r.stop()


def test_watch_add_remove():
    store = InMemStore()
    reg = ServiceRegistry(store, root="t")
    added, removed = [], []
    ev = threading.Event()
    watcher = reg.watch_service(
        "svc",
        on_add=lambda m: added.append(m.server),
        on_remove=lambda m: (removed.append(m.server), ev.set()),
        interval=0.05,
    )
    reg.register_permanent("svc", "a:1")
    reg.register_permanent("svc", "b:2")
    time.sleep(0.3)
    assert sorted(added) == ["a:1", "b:2"]
    reg.deregister("svc", "a:1")
    assert ev.wait(2.0)
    assert removed == ["a:1"]
    assert [m.server for m in watcher.servers()] == ["b:2"]
    watcher.stop()


def test_update_info():
    store = InMemStore()
    reg = ServiceRegistry(store, root="t")
    r = reg.register("svc", "h:1", info="load=0", ttl=10)
    r.update_info("load=9")
    assert reg.get_service("svc")[0].info == "load=9"
    r.stop()


def test_reregister_does_not_steal_replacement(monkeypatch):
    """After lease loss, a zombie Registration must not reclaim a key that a
    replacement process re-registered for the same server identity."""
    store = InMemStore()
    reg = ServiceRegistry(store, root="t")
    old = reg.register("svc", "h:1", ttl=10)
    # Simulate the zombie's lease expiring server-side.
    store.lease_revoke(old._keeper.lease)
    # Replacement claims the same identity.
    new = reg.register("svc", "h:1", ttl=10)
    # Zombie notices and tries to re-register: must fail, not steal.
    old._on_lost = lambda: None  # silence keeper callback
    import pytest as _pytest
    from edl_tpu.utils.exceptions import EdlRegisterError as _ERE
    with old._lock:
        with _pytest.raises(_ERE):
            old._register(initial=False)
    # Replacement's registration still intact and on a live lease.
    assert len(reg.get_service("svc")) == 1
    assert store.lease_keepalive(new._keeper.lease)
    new.stop()


def test_watch_on_update():
    store = InMemStore()
    reg = ServiceRegistry(store, root="t")
    updates = []
    ev = threading.Event()
    r = reg.register("svc", "h:1", info="load=0", ttl=10)
    w = reg.watch_service("svc", on_update=lambda m: (updates.append(m.info), ev.set()),
                          interval=0.05)
    r.update_info("load=9")
    assert ev.wait(2.0)
    assert updates[-1] == "load=9"
    assert w.servers()[0].info == "load=9"
    w.stop()
    r.stop()
