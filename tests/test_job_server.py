"""JobServer HTTP API + fault injection + JobClient reconcile logic."""

import sys
import time

from edl_tpu.collective.job_server import (JobClient, JobServer, JobState,
                                           get_job, request_resize)


def make_server(**kw):
    kw.setdefault("port", 0)
    state = JobState("j1", 1, 4, desired=2)
    return JobServer(state, **kw).start()


def test_job_state_clamps_initial_desired():
    assert JobState("j1", 1, 4, desired=99).desired == 4
    assert JobState("j1", 2, 4, desired=0).desired == 2
    assert JobState("j1", 1, 4).desired == 4


def test_get_and_resize():
    server = make_server()
    try:
        addr = f"127.0.0.1:{server.port}"
        job = get_job(addr)
        assert job == {"job_id": "j1", "desired_nodes": 2, "min_nodes": 1,
                       "max_nodes": 4}
        out = request_resize(addr, 3)
        assert out["desired_nodes"] == 3
        # Clamped to [min, max].
        assert request_resize(addr, 99)["desired_nodes"] == 4
        assert request_resize(addr, 0)["desired_nodes"] == 1
    finally:
        server.stop()


def _raw_post(addr: str, body: bytes):
    """POST /resize with an arbitrary body; (status, parsed JSON)."""
    import json
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        f"http://{addr}/resize", method="POST", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def test_resize_rejects_bad_payloads_with_400():
    """Malformed JSON / non-object / missing / non-integer `desired`
    are client errors with an error body — never a handler 500."""
    server = make_server()
    try:
        addr = f"127.0.0.1:{server.port}"
        for body in (b"{not json", b"[1, 2]", b"{}",
                     b'{"desired": "lots"}', b'{"desired": 2.5}',
                     b'{"desired": true}', b'{"desired": null}',
                     b'{"desired": [3]}'):
            status, doc = _raw_post(addr, body)
            assert status == 400, body
            assert "error" in doc, body
        # the state survived every bad request untouched
        assert get_job(addr)["desired_nodes"] == 2
        # integer-valued floats and numeric strings still work
        assert _raw_post(addr, b'{"desired": 3.0}')[1][
            "desired_nodes"] == 3
    finally:
        server.stop()


def test_resize_clamp_is_visible():
    """An out-of-range request is clamped LOUDLY: warning logged and
    the response marks it for the scaler's decision journal."""
    import logging

    class _Capture(logging.Handler):
        def __init__(self):
            super().__init__(level=logging.WARNING)
            self.records = []

        def emit(self, record):
            self.records.append(record)

    # the repo logger sets propagate=False, so attach directly
    capture = _Capture()
    logger = logging.getLogger("edl_tpu.collective.job_server")
    logger.addHandler(capture)
    try:
        state = JobState("j1", 1, 4, desired=2)
        out = state.resize(99)
        assert out["desired_nodes"] == 4
        assert out["clamped"] is True and out["requested"] == 99
        assert any("clamped" in r.getMessage()
                   for r in capture.records)
        assert state.resize(3).get("clamped") is False
    finally:
        logger.removeHandler(capture)


def test_resize_log_records_every_served_change():
    """The audit trail the scaler demo cross-checks against the
    decision journal: one entry per served resize, fault injections
    tagged with their source."""
    state = JobState("j1", 1, 4, desired=2, seed=7)
    state.resize(3)
    state.resize(99)   # clamped to 4
    state.random_resize()
    assert [e["from"] for e in state.resize_log] == [2, 3, 4]
    assert state.resize_log[0] == {"from": 2, "to": 3, "requested": 3,
                                   "clamped": False, "source": "resize"}
    assert state.resize_log[1]["to"] == 4
    assert state.resize_log[1]["clamped"] is True
    assert state.resize_log[1]["requested"] == 99
    assert state.resize_log[2]["source"] == "fault"
    assert state.resize_log[-1]["to"] == state.desired


def test_fault_injection_changes_desired():
    state = JobState("j1", 1, 4, desired=2, seed=7)
    server = JobServer(state, port=0, time_interval_to_change=0.1).start()
    try:
        seen = set()
        deadline = time.time() + 3.0
        while time.time() < deadline and len(seen) < 2:
            seen.add(get_job(f"127.0.0.1:{server.port}")["desired_nodes"])
            time.sleep(0.05)
        assert len(seen) >= 2, "fault injector never changed desired_nodes"
    finally:
        server.stop()


def test_job_client_reconciles_process_count():
    server = make_server()
    try:
        addr = f"127.0.0.1:{server.port}"
        # A launcher stand-in that just sleeps.
        client = JobClient(addr, [sys.executable, "-c",
                                  "import time; time.sleep(60)"], poll=0.1)
        client.reconcile(2)
        assert len(client.procs) == 2
        client.reconcile(3)
        assert len(client.procs) == 3
        client.reconcile(1)
        time.sleep(0.3)
        client._reap()
        assert len(client.procs) == 1
    finally:
        for p in client.procs:
            p.kill()
        server.stop()
