"""JobServer HTTP API + fault injection + JobClient reconcile logic."""

import sys
import time

from edl_tpu.collective.job_server import (JobClient, JobServer, JobState,
                                           get_job, request_resize)


def make_server(**kw):
    kw.setdefault("port", 0)
    state = JobState("j1", 1, 4, desired=2)
    return JobServer(state, **kw).start()


def test_job_state_clamps_initial_desired():
    assert JobState("j1", 1, 4, desired=99).desired == 4
    assert JobState("j1", 2, 4, desired=0).desired == 2
    assert JobState("j1", 1, 4).desired == 4


def test_get_and_resize():
    server = make_server()
    try:
        addr = f"127.0.0.1:{server.port}"
        job = get_job(addr)
        assert job == {"job_id": "j1", "desired_nodes": 2, "min_nodes": 1,
                       "max_nodes": 4}
        out = request_resize(addr, 3)
        assert out["desired_nodes"] == 3
        # Clamped to [min, max].
        assert request_resize(addr, 99)["desired_nodes"] == 4
        assert request_resize(addr, 0)["desired_nodes"] == 1
    finally:
        server.stop()


def test_fault_injection_changes_desired():
    state = JobState("j1", 1, 4, desired=2, seed=7)
    server = JobServer(state, port=0, time_interval_to_change=0.1).start()
    try:
        seen = set()
        deadline = time.time() + 3.0
        while time.time() < deadline and len(seen) < 2:
            seen.add(get_job(f"127.0.0.1:{server.port}")["desired_nodes"])
            time.sleep(0.05)
        assert len(seen) >= 2, "fault injector never changed desired_nodes"
    finally:
        server.stop()


def test_job_client_reconciles_process_count():
    server = make_server()
    try:
        addr = f"127.0.0.1:{server.port}"
        # A launcher stand-in that just sleeps.
        client = JobClient(addr, [sys.executable, "-c",
                                  "import time; time.sleep(60)"], poll=0.1)
        client.reconcile(2)
        assert len(client.procs) == 2
        client.reconcile(3)
        assert len(client.procs) == 3
        client.reconcile(1)
        time.sleep(0.3)
        client._reap()
        assert len(client.procs) == 1
    finally:
        for p in client.procs:
            p.kill()
        server.stop()
