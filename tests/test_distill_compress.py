"""Teacher-wire compression: top-k+fp16 logits, sparse distill loss.

The transport lever VERDICT r4 called the binding constraint on the
distill e2e path: fp32 dense logits are 4 KB/row at 1000 classes; the
negotiated top-k wire (distill/teacher_server.py compress_outputs /
expand_outputs) cuts that ~125x at K=8 while keeping the distill loss
exact w.r.t. the top-k renormalized teacher.
"""

import numpy as np
import pytest

from edl_tpu.distill.reader import DistillReader, EdlDistillError
from edl_tpu.distill.teacher_server import (EXPAND_FILL, TeacherClient,
                                            TeacherServer, compress_outputs,
                                            expand_outputs)

CLASSES = 1000


def _logits(rows=6, classes=CLASSES, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(rows, classes)).astype(np.float32)


class TestCompressExpand:
    def test_roundtrip_preserves_topk(self):
        arr = _logits()
        frag, out = compress_outputs({"logits": arr},
                                     {"topk": 8, "values": "float16"})
        assert set(out) == {"logits.idx", "logits.val"}
        assert out["logits.idx"].dtype == np.uint16  # 1000 classes fit
        assert out["logits.val"].dtype == np.float16
        dense = expand_outputs(dict(frag), dict(out))["logits"]
        assert dense.shape == arr.shape and dense.dtype == np.float32
        # each row: the top-8 survive (to fp16 precision), rest = fill
        for r in range(arr.shape[0]):
            top = np.argsort(-arr[r])[:8]
            np.testing.assert_allclose(dense[r, top], arr[r, top],
                                       rtol=1e-3)
            rest = np.setdiff1d(np.arange(CLASSES), top)
            assert (dense[r, rest] == EXPAND_FILL).all()

    def test_values_sorted_descending(self):
        _, out = compress_outputs({"l": _logits(3)}, {"topk": 5})
        vals = out["l.val"].astype(np.float32)
        assert (np.diff(vals, axis=1) <= 0).all()

    def test_wide_head_uses_int32_indices(self):
        arr = _logits(2, classes=70000, seed=1)
        _, out = compress_outputs({"l": arr}, {"topk": 4})
        assert out["l.idx"].dtype == np.int32

    def test_ineligible_tensors_pass_through(self):
        outs = {"emb": np.zeros((4, 2, 3), np.float32),   # 3-D
                "ids": np.zeros((4, 100), np.int32),      # not floating
                "tiny": np.zeros((4, 3), np.float32)}     # classes <= k
        frag, out = compress_outputs(outs, {"topk": 8})
        assert frag == {} and set(out) == set(outs)

    def test_wire_bytes_shrink(self):
        arr = _logits(16)
        _, out = compress_outputs({"l": arr}, {"topk": 8})
        dense_b = arr.nbytes
        sparse_b = sum(a.nbytes for a in out.values())
        assert sparse_b * 50 < dense_b  # >50x smaller at K=8/1000

    def test_softmax_parity_with_exact_topk(self):
        """softmax(expanded/T) == renormalized softmax over the true
        top-k (the quality contract of the approximation)."""
        arr = _logits(4)
        frag, out = compress_outputs({"l": arr},
                                     {"topk": 8, "values": "float32"})
        dense = expand_outputs(dict(frag), dict(out))["l"]
        t = 2.0
        got = np.exp(dense / t) / np.exp(dense / t).sum(-1, keepdims=True)
        for r in range(4):
            top = np.argsort(-arr[r])[:8]
            e = np.exp(arr[r, top] / t)
            np.testing.assert_allclose(got[r, top], e / e.sum(),
                                       rtol=1e-5)
            assert got[r].sum() == pytest.approx(1.0, rel=1e-5)


def _predict(feeds):
    # Deterministic linear head over flattened image
    x = feeds["image"].reshape(feeds["image"].shape[0], -1)
    w = np.random.default_rng(3).normal(
        size=(x.shape[1], CLASSES)).astype(np.float32)
    return {"teacher_logits": (x.astype(np.float32) @ w)}


class TestOverTheWire:
    def test_client_negotiates_and_expands(self):
        with TeacherServer(_predict, host="127.0.0.1") as srv:
            feeds = {"image": np.random.default_rng(0).normal(
                size=(4, 8)).astype(np.float32)}
            plain = TeacherClient(f"127.0.0.1:{srv.port}")
            dense = plain.predict(feeds)["teacher_logits"]
            comp = TeacherClient(f"127.0.0.1:{srv.port}", compress_topk=8)
            got = comp.predict(feeds)["teacher_logits"]
            assert got.shape == dense.shape
            for r in range(4):
                top = np.argsort(-dense[r])[:8]
                np.testing.assert_allclose(got[r, top], dense[r, top],
                                           rtol=1e-3)
            plain.close()
            comp.close()

    def test_sparse_client_returns_idx_val(self):
        with TeacherServer(_predict, host="127.0.0.1") as srv:
            c = TeacherClient(f"127.0.0.1:{srv.port}", compress_topk=4,
                              expand=False)
            out = c.predict({"image": np.ones((2, 8), np.float32)})
            assert set(out) == {"teacher_logits.idx", "teacher_logits.val"}
            assert out["teacher_logits.idx"].shape == (2, 4)
            c.close()

    def test_server_side_device_topk_announced_and_expanded(self):
        """A predict_fn that already emits sparse idx/val (device-side
        lax.top_k) + compressed_meta: dense clients expand transparently,
        sparse clients get idx/val."""
        dense_ref = {}

        def sparse_predict(feeds):
            logits = _predict(feeds)["teacher_logits"]
            dense_ref["logits"] = logits
            k = 8
            idx = np.argsort(-logits, axis=1)[:, :k]
            val = np.take_along_axis(logits, idx, axis=1)
            return {"teacher_logits.idx": idx.astype(np.int32),
                    "teacher_logits.val": val.astype(np.float16)}

        meta = {"teacher_logits": {"topk": 8, "classes": CLASSES,
                                   "values": "<f2"}}
        with TeacherServer(sparse_predict, host="127.0.0.1",
                           compressed_meta=meta) as srv:
            feeds = {"image": np.random.default_rng(2).normal(
                size=(3, 8)).astype(np.float32)}
            dense_client = TeacherClient(f"127.0.0.1:{srv.port}")
            got = dense_client.predict(feeds)["teacher_logits"]
            assert got.shape == (3, CLASSES)
            ref = dense_ref["logits"]
            for r in range(3):
                top = np.argsort(-ref[r])[:8]
                np.testing.assert_allclose(got[r, top], ref[r, top],
                                           rtol=1e-3)
                assert (np.delete(got[r], top) == EXPAND_FILL).all()
            dense_client.close()
            sparse_client = TeacherClient(f"127.0.0.1:{srv.port}",
                                          expand=False)
            out = sparse_client.predict(feeds)
            assert set(out) == {"teacher_logits.idx",
                                "teacher_logits.val"}
            sparse_client.close()

    def test_client_negotiation_never_recompresses_sparse_outputs(self):
        """A client whose compress_topk differs from the server's
        device-side K must NOT have name.val shredded into
        name.val.idx/name.val.val (regression)."""
        def sparse_predict(feeds):
            rows = feeds["image"].shape[0]
            return {"teacher_logits.idx":
                        np.tile(np.arange(8, dtype=np.int32), (rows, 1)),
                    "teacher_logits.val":
                        np.ones((rows, 8), np.float16)}

        meta = {"teacher_logits": {"topk": 8, "classes": CLASSES,
                                   "values": "<f2"}}
        with TeacherServer(sparse_predict, host="127.0.0.1",
                           compressed_meta=meta) as srv:
            c = TeacherClient(f"127.0.0.1:{srv.port}", compress_topk=4,
                              expand=False)  # smaller K than server's
            out = c.predict({"image": np.zeros((2, 8), np.float32)})
            assert set(out) == {"teacher_logits.idx",
                                "teacher_logits.val"}
            c.close()

    def test_cli_serve_topk_predict_builder(self):
        """--serve-topk path of the teacher CLI builder: device top-k,
        sparse outputs, values fp16."""
        from edl_tpu.distill.teacher_server import _build_model_predict
        predict, meta = _build_model_predict("mlp", 10, "", "image",
                                             "logits", (8, 8, 1),
                                             "float32", serve_topk=3)
        assert meta == {"logits": {"topk": 3, "classes": 10,
                                   "values": "<f2"}}
        out = predict({"image": np.zeros((2, 8, 8, 1), np.float32)})
        assert set(out) == {"logits.idx", "logits.val"}
        assert out["logits.idx"].shape == (2, 3)
        assert out["logits.val"].dtype == np.float16
        # descending and in-range
        assert (np.diff(out["logits.val"].astype(np.float32),
                        axis=1) <= 0).all()
        assert (out["logits.idx"] >= 0).all()
        assert (out["logits.idx"] < 10).all()

    def test_cli_serve_topk_clamped_to_classes(self):
        """--serve-topk larger than the head must clamp, not crash the
        first predict (lax.top_k rejects k > axis size)."""
        from edl_tpu.distill.teacher_server import _build_model_predict
        predict, meta = _build_model_predict(
            "mlp", 6, "", "image", "logits", (8, 8, 1), "float32",
            serve_topk=16)
        assert meta["logits"]["topk"] == 6  # clamped AND announced
        out = predict({"image": np.zeros((2, 8, 8, 1), np.float32)})
        assert out["logits.idx"].shape == (2, 6)

    def test_uint8_feeds_ship_unchanged(self):
        seen = {}

        def spy_predict(feeds):
            seen["dtype"] = feeds["image"].dtype
            return _predict({"image": feeds["image"].astype(np.float32)})

        with TeacherServer(spy_predict, host="127.0.0.1") as srv:
            c = TeacherClient(f"127.0.0.1:{srv.port}")
            c.predict({"image": np.zeros((2, 8), np.uint8)})
            c.close()
        assert seen["dtype"] == np.uint8  # 4x less feed bandwidth kept


class TestReaderIntegration:
    def _batches(self, n=3, rows=8):
        rng = np.random.default_rng(5)
        return [{"image": rng.normal(size=(rows, 8)).astype(np.float32),
                 "label": rng.integers(0, CLASSES, size=(rows,))}
                for _ in range(n)]

    def test_reader_with_compression_transparent(self):
        batches = self._batches()
        with TeacherServer(_predict, host="127.0.0.1") as srv:
            dr = DistillReader(lambda: iter(batches), feeds=["image"],
                               predicts=["teacher_logits"],
                               teachers=[f"127.0.0.1:{srv.port}"],
                               teacher_batch_size=4, compress_topk=8)
            got = list(dr())
        assert len(got) == len(batches)
        for want, out in zip(batches, got):
            assert out["teacher_logits"].shape == (8, CLASSES)
            ref = _predict({"image": want["image"]})["teacher_logits"]
            for r in range(8):
                top = np.argsort(-ref[r])[:8]
                np.testing.assert_allclose(out["teacher_logits"][r, top],
                                           ref[r, top], rtol=1e-3)

    def test_reader_sparse_mode_end_to_end(self):
        batches = self._batches()
        with TeacherServer(_predict, host="127.0.0.1") as srv:
            dr = DistillReader(lambda: iter(batches), feeds=["image"],
                               predicts=["teacher_logits"],
                               teachers=[f"127.0.0.1:{srv.port}"],
                               teacher_batch_size=4, compress_topk=8,
                               sparse_predicts=True)
            got = list(dr())
        for want, out in zip(batches, got):
            assert out["teacher_logits.idx"].shape == (8, 8)
            assert out["teacher_logits.val"].dtype == np.float16

    def test_sparse_requires_topk(self):
        with pytest.raises(EdlDistillError, match="compress_topk"):
            DistillReader(lambda: iter([]), feeds=["x"], predicts=["p"],
                          teachers=["t"], sparse_predicts=True)

    def test_sparse_rejects_slot_formats(self):
        dr = DistillReader(ins=["x"], predicts=["p"], teachers=["t"],
                           compress_topk=4, sparse_predicts=True)
        with pytest.raises(EdlDistillError, match="dict-format"):
            dr.set_batch_generator(lambda: iter([]))


class TestSparseLoss:
    def test_sparse_kl_matches_dense_on_expanded(self):
        """sparse_distill_kl == distill_kl over the scatter-expanded
        teacher — exactly (same renormalized top-k distribution)."""
        import jax.numpy as jnp
        from edl_tpu.train.classification import (distill_kl,
                                                  sparse_distill_kl)
        student = _logits(4, seed=9)
        teacher = _logits(4, seed=10)
        frag, out = compress_outputs({"t": teacher},
                                     {"topk": 8, "values": "float32"})
        dense = expand_outputs(dict(frag), dict(out))["t"]
        a = float(sparse_distill_kl(jnp.asarray(student),
                                    jnp.asarray(out["t.idx"]
                                                .astype(np.int32)),
                                    jnp.asarray(out["t.val"]),
                                    temperature=2.0))
        b = float(distill_kl(jnp.asarray(student), jnp.asarray(dense),
                             temperature=2.0))
        assert a == pytest.approx(b, rel=1e-5)

    def test_sparse_distill_step_trains(self):
        import jax
        import optax
        from edl_tpu.models.mlp import MLP
        from edl_tpu.train.classification import (create_state,
                                                  make_sparse_distill_step)
        model = MLP(num_classes=16, hidden=(8,))
        state = create_state(model, jax.random.PRNGKey(0), (1, 4, 4, 1),
                             optax.sgd(0.1))
        step = make_sparse_distill_step(16, temperature=2.0,
                                        hard_weight=0.3)
        rng = np.random.default_rng(0)
        teacher = rng.normal(size=(8, 16)).astype(np.float32)
        _, out = compress_outputs({"teacher_logits": teacher}, {"topk": 4})
        batch = {"image": rng.normal(size=(8, 4, 4, 1)).astype(np.float32),
                 "label": rng.integers(0, 16, size=(8,)).astype(np.int32),
                 "teacher_logits.idx": out["teacher_logits.idx"]
                 .astype(np.int32),
                 "teacher_logits.val": out["teacher_logits.val"]}
        losses = []
        for _ in range(5):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]  # it learns the sparse targets
