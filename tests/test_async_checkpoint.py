"""Async snapshot-then-write checkpointing: semantics and atomicity.

The contracts the elastic story leans on (CheckFreq/Check-N-Run recipe,
train/checkpoint.py `save_async`):

- snapshot isolation: the checkpoint holds the state AS OF the save
  call, however the live state mutates before the write runs;
- drop-to-latest: a queued unwritten snapshot is superseded by a newer
  one; an in-flight write is never aborted;
- wait()/close() barriers drain the writer; a background write failure
  surfaces on the NEXT save/wait call, and the manager recovers;
- sync and async saves produce bitwise-identical checkpoint bytes
  (replicated msgpack AND sharded chunk files);
- crash-mid-save atomicity: a writer killed between chunk writes and the
  seal leaves a torn .tmp dir that restore never sees and startup GC
  removes.
"""

import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.parallel import mesh as mesh_lib, sharding as shd
from edl_tpu.train import sharded_checkpoint as sc
from edl_tpu.train.checkpoint import (CheckpointManager,
                                      CheckpointWriteError)
from edl_tpu.train.state import TrainState, TrainStatus


def _state(value: float) -> TrainState:
    params = {"w": jnp.full((4,), value), "b": jnp.zeros((2, 2))}
    return TrainState.create(apply_fn=lambda *a: None, params=params,
                             tx=optax.sgd(0.1))


def _w(state) -> float:
    return float(np.asarray(state.params["w"])[0])


# -- async semantics ---------------------------------------------------------


def test_async_roundtrip_and_wait_barrier(tmp_path):
    mgr = CheckpointManager(str(tmp_path), process_index=0)
    mgr.save_async(_state(1.5), TrainStatus(epoch=3, step=30))
    mgr.wait()
    # after the barrier the version is sealed and visible
    assert mgr.versions() == [0]
    restored, status = mgr.restore(_state(0.0))
    assert _w(restored) == 1.5
    assert status.epoch == 3 and status.step == 30
    mgr.close()


def test_snapshot_isolation_from_live_state_and_status(tmp_path):
    """The write happens later — it must capture save-call-time values,
    not whatever the training loop mutated them into since."""
    mgr = CheckpointManager(str(tmp_path), process_index=0)
    gate = threading.Event()
    real_write = mgr._write_replicated

    def gated_write(host_state, status):
        gate.wait(10.0)
        return real_write(host_state, status)

    mgr._write_replicated = gated_write
    state = _state(7.0)
    status = TrainStatus(epoch=1, step=10)
    mgr.save_async(state, status)
    # mutate the live objects while the write is still pending
    status.step = 999
    status.epoch = 42
    state = None  # the loop would donate/overwrite the buffers
    gate.set()
    mgr.wait()
    restored, got = mgr.restore(_state(0.0))
    assert _w(restored) == 7.0
    assert got.step == 10 and got.epoch == 1
    mgr.close()


def test_drop_to_latest_supersede_never_inflight(tmp_path):
    mgr = CheckpointManager(str(tmp_path), process_index=0)
    started = threading.Event()
    gate = threading.Event()
    real_write = mgr._write_replicated

    def gated_write(host_state, status):
        started.set()
        gate.wait(10.0)
        return real_write(host_state, status)

    mgr._write_replicated = gated_write
    mgr.save_async(_state(1.0), TrainStatus(step=1))
    assert started.wait(10.0)  # save #1 is IN FLIGHT (never aborted)
    mgr.save_async(_state(2.0), TrainStatus(step=2))  # queued ...
    mgr.save_async(_state(3.0), TrainStatus(step=3))  # ... superseded by #3
    gate.set()
    mgr.wait()
    # exactly two versions: the in-flight #1 and the latest #3; #2 died
    assert mgr.versions() == [0, 1]
    assert mgr.stats()["superseded"] == 1
    r1, s1 = mgr.restore(_state(0.0), version=0)
    r3, s3 = mgr.restore(_state(0.0), version=1)
    assert _w(r1) == 1.0 and s1.step == 1
    assert _w(r3) == 3.0 and s3.step == 3
    mgr.close()


def test_writer_error_surfaces_on_next_save_then_recovers(tmp_path):
    mgr = CheckpointManager(str(tmp_path), process_index=0)
    real_write = mgr._write_replicated
    boom = RuntimeError("disk on fire")

    def failing_write(host_state, status):
        raise boom

    mgr._write_replicated = failing_write
    mgr.save_async(_state(1.0), TrainStatus(step=1))  # enqueues fine
    # drain without raising (close(raise_errors=False) is the crash path)
    mgr.close(raise_errors=False)
    with pytest.raises(CheckpointWriteError) as exc_info:
        mgr.save_async(_state(2.0), TrainStatus(step=2))
    assert exc_info.value.__cause__ is boom
    # the error was consumed; the manager keeps working afterwards
    mgr._write_replicated = real_write
    mgr.save_async(_state(3.0), TrainStatus(step=3))
    mgr.wait()
    restored, status = mgr.restore(_state(0.0))
    assert _w(restored) == 3.0 and status.step == 3
    mgr.close()


def test_wait_raises_writer_error(tmp_path):
    mgr = CheckpointManager(str(tmp_path), process_index=0)
    mgr._write_replicated = lambda *a: (_ for _ in ()).throw(
        RuntimeError("boom"))
    mgr.save_async(_state(1.0), TrainStatus(step=1))
    with pytest.raises(CheckpointWriteError):
        mgr.wait()
    mgr.close()


def test_nonzero_rank_save_async_is_noop(tmp_path):
    mgr = CheckpointManager(str(tmp_path), process_index=1)
    mgr.save_async(_state(1.0), TrainStatus(step=1))
    mgr.wait()
    mgr.close()
    assert mgr.versions() == []


# -- bitwise identity --------------------------------------------------------


def test_sync_async_bitwise_identical_replicated(tmp_path):
    state, status = _state(4.25), TrainStatus(epoch=2, step=20, world_size=8)
    sync_mgr = CheckpointManager(str(tmp_path / "sync"), process_index=0)
    sync_mgr.save(state, status)
    async_mgr = CheckpointManager(str(tmp_path / "async"), process_index=0)
    async_mgr.save_async(state, status)
    async_mgr.close()
    for name in ("state.msgpack", "meta.json"):
        a = (tmp_path / "sync" / "ckpt-0" / name).read_bytes()
        b = (tmp_path / "async" / "ckpt-0" / name).read_bytes()
        assert a == b, f"{name} differs between sync and async saves"


def _sharded_state(mesh):
    from edl_tpu.models.transformer import Transformer, TransformerConfig
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_len=64,
                            dtype=jnp.float32, mesh=mesh)
    model = Transformer(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 64)
    variables = shd.init_sharded(
        lambda: model.init(jax.random.PRNGKey(0), toks, train=False), mesh)
    return TrainState.create(apply_fn=model.apply,
                             params=variables["params"],
                             tx=optax.adamw(1e-3))


def test_sync_async_bitwise_identical_sharded(tmp_path):
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"fsdp": 2, "tp": 2}),
                              n_devices=4)
    state = _sharded_state(mesh)
    status = TrainStatus(epoch=1, step=5)
    sync_mgr = CheckpointManager(str(tmp_path / "sync"), sharded=True)
    sync_mgr.save(state, status)
    async_mgr = CheckpointManager(str(tmp_path / "async"), sharded=True)
    async_mgr.save_async(state, status)
    async_mgr.close()
    sdir, adir = tmp_path / "sync" / "ckpt-0", tmp_path / "async" / "ckpt-0"
    names = sorted(os.listdir(sdir))
    assert names == sorted(os.listdir(adir))
    for name in names:
        assert (sdir / name).read_bytes() == (adir / name).read_bytes(), \
            f"{name} differs between sync and async sharded saves"


def test_async_sharded_roundtrip_onto_other_mesh(tmp_path):
    big = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 2, "fsdp": 2,
                                                "tp": 2}))
    state = _sharded_state(big)
    mgr = CheckpointManager(str(tmp_path), sharded=True)
    mgr.save_async(state, TrainStatus(epoch=0, step=1))
    mgr.wait()
    small = mesh_lib.make_mesh(mesh_lib.MeshSpec({"fsdp": 2, "tp": 2}),
                               n_devices=4)
    fresh = _sharded_state(small)
    restored, status = mgr.restore(fresh)
    assert status.step == 1
    for a, b in zip(jax.tree.leaves(jax.device_get(state)),
                    jax.tree.leaves(jax.device_get(restored))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


# -- crash-mid-save atomicity + startup GC -----------------------------------


def test_crash_between_chunks_and_seal_falls_back_and_gcs(tmp_path):
    """Kill the writer after the chunk writes but before the seal: the
    torn .tmp dir must never be visible to restore (previous sealed
    version wins) and must be GC'd at the next start."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"fsdp": 2, "tp": 2}),
                              n_devices=4)
    state = _sharded_state(mesh)
    mgr = CheckpointManager(str(tmp_path), sharded=True)
    assert mgr.save(state, TrainStatus(epoch=0, step=10)) == 0

    # the "crash": chunks + index of version 1 land in the pending dir,
    # but the writer dies before meta.json + the atomic rename
    torn = tmp_path / ".tmp-ckpt-1"
    sc.save_sharded(str(torn), state)
    assert torn.is_dir() and not (torn / "meta.json").exists()

    # a re-formed world restores the previous SEALED version
    mgr2 = CheckpointManager(str(tmp_path), sharded=True)
    assert mgr2.latest_version() == 0
    restored, status = mgr2.restore(_sharded_state(mesh))
    assert status.step == 10

    # ... and startup GC (the TrainLoop.try_restore path) removes the
    # torn dir instead of leaking it forever
    mgr2.gc_stale_tmp()
    assert not torn.exists()
    assert mgr2.versions() == [0]


def test_train_loop_startup_gcs_torn_tmp(tmp_path):
    """The trainer start path itself sweeps torn partial saves."""
    from edl_tpu.examples import fit_a_line
    from edl_tpu.parallel.mesh import make_mesh
    from edl_tpu.train.loop import LoopConfig, TrainLoop

    for name in (".tmp-ckpt-7", ".tmp-refetch-x"):
        (tmp_path / name).mkdir()
        (tmp_path / name / "leaf0-o0.npy").write_bytes(b"torn")
    cfg = fit_a_line.Config(num_epochs=1, steps_per_epoch=3)
    state, step_fn = fit_a_line.build(cfg)
    loop = TrainLoop(step_fn, state, mesh=make_mesh(),
                     config=LoopConfig(num_epochs=1, ckpt_dir=str(tmp_path),
                                       log_every_steps=1000))
    loop.run(lambda e: fit_a_line.synthetic_batches(e, cfg))
    assert not any(n.startswith(".tmp-") for n in os.listdir(tmp_path))
    assert loop.status.epoch == 0  # and training completed


# -- restore: parallel region reads + one open per chunk ---------------------


def test_restore_parallel_matches_serial(tmp_path):
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 2, "fsdp": 2,
                                                 "tp": 2}))
    state = _sharded_state(mesh)
    sc.save_sharded(str(tmp_path / "s"), state)
    fresh = _sharded_state(mesh)
    serial = sc.restore_sharded(str(tmp_path / "s"), fresh, threads=1)
    parallel = sc.restore_sharded(str(tmp_path / "s"), fresh, threads=4)
    for a, b in zip(jax.tree.leaves(jax.device_get(serial)),
                    jax.tree.leaves(jax.device_get(parallel))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_opens_each_chunk_once(tmp_path, monkeypatch):
    """A resharding restore intersects each chunk with many target
    regions; the handle cache must np.load each file once, not once per
    region."""
    small = mesh_lib.make_mesh(mesh_lib.MeshSpec({"fsdp": 2, "tp": 2}),
                               n_devices=4)
    state = _sharded_state(small)
    sc.save_sharded(str(tmp_path / "s"), state)

    opens: dict[str, int] = {}
    real_load = np.load

    def counting_load(path, *a, **kw):
        opens[os.path.basename(str(path))] = \
            opens.get(os.path.basename(str(path)), 0) + 1
        return real_load(path, *a, **kw)

    monkeypatch.setattr(sc.np, "load", counting_load)
    # 4 -> 8 devices: every saved chunk feeds multiple target shards
    big = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 2, "fsdp": 2,
                                                "tp": 2}))
    sc.restore_sharded(str(tmp_path / "s"), _sharded_state(big))
    assert opens, "no chunk reads recorded"
    multi = [n for n, c in opens.items() if c > 1]
    assert not multi, f"chunks re-opened per region: {multi}"


def test_restore_threads_env_knob(monkeypatch):
    monkeypatch.setenv("EDL_TPU_CKPT_RESTORE_THREADS", "3")
    assert sc.restore_threads() == 3
    monkeypatch.setenv("EDL_TPU_CKPT_RESTORE_THREADS", "bogus")
    assert sc.restore_threads() >= 1
    monkeypatch.delenv("EDL_TPU_CKPT_RESTORE_THREADS")
    assert sc.restore_threads() >= 1


# -- TrainLoop integration ---------------------------------------------------


def test_loop_async_saves_match_sync_saves(tmp_path):
    """ckpt_async must not change WHAT gets checkpointed — final
    checkpoint bytes of an async run equal the sync run's."""
    from edl_tpu.examples import fit_a_line
    from edl_tpu.parallel.mesh import make_mesh
    from edl_tpu.train.loop import LoopConfig, TrainLoop

    def run(subdir, ckpt_async):
        cfg = fit_a_line.Config(num_epochs=2, steps_per_epoch=6)
        state, step_fn = fit_a_line.build(cfg)
        loop = TrainLoop(step_fn, state, mesh=make_mesh(),
                         config=LoopConfig(num_epochs=2,
                                           ckpt_dir=str(tmp_path / subdir),
                                           ckpt_every_steps=4,
                                           ckpt_async=ckpt_async,
                                           log_every_steps=1000))
        loop.run(lambda e: fit_a_line.synthetic_batches(e, cfg))
        return loop

    sync_loop, async_loop = run("sync", False), run("async", True)
    assert async_loop.ckpt_saves == sync_loop.ckpt_saves
    stats = async_loop.ckpt_stats()
    assert stats["ckpt_saves_async"] > 0 and stats["ckpt_errors"] == 0
    # On a loaded host the writer may legally coalesce back-to-back
    # saves (a snapshot superseded before its write starts), so the
    # version LISTS can differ; every save must still be accounted for
    # as either a write or a supersede...
    assert stats["ckpt_writes"] + stats["ckpt_superseded"] == \
        stats["ckpt_saves_async"]

    # ...and the NEWEST checkpoint — what a restore would see — must be
    # byte-identical to the sync run's.
    def newest(subdir):
        versions = sorted(os.listdir(tmp_path / subdir),
                          key=lambda v: int(v.rsplit("-", 1)[-1]))
        return tmp_path / subdir / versions[-1]

    assert (newest("async") / "state.msgpack").read_bytes() == \
        (newest("sync") / "state.msgpack").read_bytes()


def test_loop_surfaces_writer_failure(tmp_path):
    """A background write failure must fail the RUN (at the epoch-end
    wait barrier), not vanish into a daemon thread."""
    from edl_tpu.examples import fit_a_line
    from edl_tpu.parallel.mesh import make_mesh
    from edl_tpu.train.loop import LoopConfig, TrainLoop

    cfg = fit_a_line.Config(num_epochs=1, steps_per_epoch=4)
    state, step_fn = fit_a_line.build(cfg)
    loop = TrainLoop(step_fn, state, mesh=make_mesh(),
                     config=LoopConfig(num_epochs=1,
                                       ckpt_dir=str(tmp_path / "ck"),
                                       log_every_steps=1000))
    loop.ckpt._write_replicated = lambda *a: (_ for _ in ()).throw(
        OSError("no space left on device"))
    with pytest.raises(CheckpointWriteError):
        loop.run(lambda e: fit_a_line.synthetic_batches(e, cfg))


def test_status_json_matches_sync_semantics(tmp_path):
    """meta.json of an async mid-epoch save records the cursor AS OF the
    save step (the resume contract), not the end-of-run cursor."""
    from edl_tpu.examples import fit_a_line
    from edl_tpu.parallel.mesh import make_mesh
    from edl_tpu.train.loop import LoopConfig, TrainLoop

    cfg = fit_a_line.Config(num_epochs=1, steps_per_epoch=10)
    state, step_fn = fit_a_line.build(cfg)
    loop = TrainLoop(step_fn, state, mesh=make_mesh(),
                     config=LoopConfig(num_epochs=1,
                                       ckpt_dir=str(tmp_path),
                                       ckpt_every_steps=4,
                                       log_every_steps=1000))
    loop.run(lambda e: fit_a_line.synthetic_batches(e, cfg))
    with open(tmp_path / "ckpt-0" / "meta.json") as f:
        meta = json.load(f)
    assert meta["status"]["step"] == 4
    assert meta["status"]["step_in_epoch"] == 4
