"""Model zoo + classification step tests (tiny shapes, virtual CPU devices).

Mirrors the reference's approach of exercising the full training machinery
without cluster hardware (SURVEY.md §4 fake-backend trick).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

pytestmark = pytest.mark.slow  # zoo forwards/steps compile ResNet-class graphs (~2.5 min on one CPU core)

from edl_tpu.models.resnet import ResNet, ResNetTiny, ResNet50_vd
from edl_tpu.models.vgg import VGG
from edl_tpu.train import classification as cls
from edl_tpu.parallel import mesh as mesh_lib

NUM_CLASSES = 10


def tiny_resnet(vd=False):
    return ResNetTiny(num_classes=NUM_CLASSES, vd=vd, dtype=jnp.float32)


def make_batch(n=8, hw=32, key=0):
    k = jax.random.PRNGKey(key)
    return {
        "image": jax.random.normal(k, (n, hw, hw, 3), jnp.float32),
        "label": jax.random.randint(jax.random.PRNGKey(key + 1), (n,), 0,
                                    NUM_CLASSES),
    }


@pytest.mark.parametrize("vd", [False, True])
def test_resnet_forward_shapes(vd):
    model = tiny_resnet(vd)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 32, 32, 3)), train=False)
    logits = model.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
    assert logits.shape == (2, NUM_CLASSES)
    assert logits.dtype == jnp.float32
    assert "batch_stats" in variables


def test_resnet50_vd_param_count():
    # ResNet50_vd ~ 25.6M params; sanity that the full architecture builds.
    model = ResNet50_vd(num_classes=1000)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 224, 224, 3)), train=False))
    n = sum(int(np.prod(x.shape))
            for x in jax.tree.leaves(variables["params"]))
    assert 25e6 < n < 26.5e6, n


def test_vgg_forward():
    model = VGG(stage_convs=(1, 1, 1, 1, 1), num_classes=NUM_CLASSES,
                fc_dim=32, dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 32, 32, 3)), train=False)
    logits = model.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
    assert logits.shape == (2, NUM_CLASSES)


def test_classification_step_trains():
    model = tiny_resnet()
    state = cls.create_state(model, jax.random.PRNGKey(0), (1, 32, 32, 3),
                             optax.sgd(0.1, momentum=0.9))
    step = cls.make_classification_step(NUM_CLASSES, smoothing=0.1,
                                        mixup_alpha=0.2, donate=False)
    batch = make_batch()
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 5


def test_bn_stats_update():
    model = tiny_resnet()
    state = cls.create_state(model, jax.random.PRNGKey(0), (1, 32, 32, 3),
                             optax.sgd(0.1))
    step = cls.make_classification_step(NUM_CLASSES, donate=False)
    before = jax.tree.leaves(state.batch_stats)[0].copy()
    state, _ = step(state, make_batch())
    after = jax.tree.leaves(state.batch_stats)[0]
    assert not np.allclose(before, after)


def test_mixup_properties():
    key = jax.random.PRNGKey(0)
    x = jnp.ones((4, 2, 2, 3))
    y = jax.nn.one_hot(jnp.array([0, 1, 2, 3]), 4)
    mx, my = cls.mixup(key, x, y, alpha=0.5)
    assert mx.shape == x.shape and my.shape == y.shape
    # Targets stay a distribution.
    np.testing.assert_allclose(np.asarray(my.sum(-1)), 1.0, rtol=1e-5)


def test_smoothed_labels():
    t = cls.smoothed_labels(jnp.array([1]), 4, smoothing=0.1)
    np.testing.assert_allclose(np.asarray(t[0]),
                               [0.025, 0.925, 0.025, 0.025], rtol=1e-5)


def test_distill_step_matches_teacher():
    model = tiny_resnet()
    state = cls.create_state(model, jax.random.PRNGKey(0), (1, 32, 32, 3),
                             optax.sgd(0.1))
    step = cls.make_distill_step(NUM_CLASSES, temperature=2.0,
                                 hard_weight=0.3, donate=False)
    batch = make_batch()
    batch["teacher_logits"] = jax.random.normal(
        jax.random.PRNGKey(7), (8, NUM_CLASSES))
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_eval_step_topk():
    model = tiny_resnet()
    state = cls.create_state(model, jax.random.PRNGKey(0), (1, 32, 32, 3),
                             optax.sgd(0.1))
    out = cls.make_eval_step()(state, make_batch())
    assert set(out) == {"acc1", "acc5"}
    assert 0.0 <= float(out["acc1"]) <= float(out["acc5"]) <= 1.0


def test_step_on_dp_mesh():
    # The sharded path: batch split over 8 virtual devices, grads allreduced
    # by XLA (capability of fleet NCCL allreduce, SURVEY §2.3).
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 8}))
    model = tiny_resnet()
    state = cls.create_state(model, jax.random.PRNGKey(0), (1, 32, 32, 3),
                             optax.sgd(0.1))
    step = cls.make_classification_step(NUM_CLASSES, donate=False)
    batch = mesh_lib.shard_batch(mesh, make_batch(n=16))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
