"""Gradient-path equivalence contract of the DCN-aware comm plane.

The load-bearing claims of edl_tpu/train/comm.py, each pinned:

- bucketing is numerics-free: bucketed-DENSE on the flat world is
  BITWISE identical to the plain jit step (reduction is elementwise;
  the 1/W scaling is exact on power-of-two worlds);
- the hierarchical decomposition is a re-associated sum: the 2-slice
  hybrid dryrun holds loss parity at float tolerance;
- compression never loses gradient mass: the error-feedback residual
  carries exactly what the top-k wire dropped, and re-contributes it;
- bucket-plan edges: 0-d leaves, ragged tails, dtype grouping,
  oversized leaves;
- the int8 wire (ops/pack.py): XLA fallback == Pallas interpret
  kernel, bounded quantization error, exact zero round-trip.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.models.mlp import MLP
from edl_tpu.parallel import mesh as mesh_lib
from edl_tpu.parallel.compat import shard_map
from edl_tpu.train import comm
from edl_tpu.train.state import TrainState
from edl_tpu.train.step import make_train_step

WORLD = 8


def _mlp_problem(seed: int = 0, hidden=(32, 16), classes: int = 4,
                 rows: int = 16):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(rows, 8)).astype(np.float32)
    y = rng.integers(0, classes, size=rows).astype(np.int32)
    model = MLP(num_classes=classes, hidden=hidden)
    params = model.init(jax.random.PRNGKey(seed), jnp.asarray(x))["params"]
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=optax.sgd(0.1, momentum=0.9))

    def loss_fn(state, params, batch):
        logits = state.apply_fn({"params": params}, batch["x"])
        onehot = jax.nn.one_hot(batch["y"], classes)
        loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot,
                                 axis=-1))
        acc = jnp.mean((jnp.argmax(logits, -1) == batch["y"])
                       .astype(jnp.float32))
        return loss, {"acc": acc}

    return loss_fn, state, {"x": x, "y": y}


def _replicate(mesh, tree):
    return jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P())), tree)


# -- bucket planning --------------------------------------------------------


def test_plan_buckets_greedy_fill_and_padding():
    params = {"a": jnp.zeros((100,)), "b": jnp.zeros((100,)),
              "c": jnp.zeros((1000,))}
    # 150 floats = 600B budget: a+b exceed it -> a alone, then b, then
    # the oversized c gets its own bucket
    plan = comm.plan_buckets(params, bucket_mb=600 / (1 << 20), align=8)
    sizes = [b.size for b in plan.buckets]
    assert sizes == [100, 100, 1000]
    for b in plan.buckets:
        assert b.padded % 8 == 0
        assert b.padded >= b.size
    assert plan.buckets[0].padded == 104  # ragged tail padded up


def test_plan_buckets_groups_by_dtype_and_keeps_scalars():
    params = {"w": jnp.zeros((64,), jnp.float32),
              "n": jnp.zeros((), jnp.int32),       # 0-d leaf
              "v": jnp.zeros((8,), jnp.float32)}
    plan = comm.plan_buckets(params, bucket_mb=4.0, align=8)
    dtypes = sorted(str(b.dtype) for b in plan.buckets)
    assert dtypes == ["float32", "int32"]
    assert plan.n_leaves == 3
    int_bucket = next(b for b in plan.buckets
                      if b.dtype == jnp.int32)
    assert int_bucket.size == 1 and int_bucket.padded == 8


def test_plan_buckets_deterministic():
    params = {"a": jnp.zeros((37,)), "b": jnp.zeros((113,))}
    p1 = comm.plan_buckets(params, 0.001, align=8)
    p2 = comm.plan_buckets(params, 0.001, align=8)
    assert p1.buckets == p2.buckets


def test_pack_unpack_roundtrip_bitwise():
    rng = np.random.default_rng(3)
    tree = {"a": jnp.asarray(rng.normal(size=(7, 3)).astype(np.float32)),
            "s": jnp.asarray(np.float32(rng.normal())),  # 0-d
            "b": jnp.asarray(rng.normal(size=(33,)).astype(np.float32))}
    plan = comm.plan_buckets(tree, bucket_mb=0.0001, align=8)
    bufs = comm.pack_buckets(tree, plan)
    for buf, b in zip(bufs, plan.buckets):
        assert buf.shape == (b.padded,)
    out = comm.unpack_buckets(bufs, plan)
    assert comm.tree_bitwise_equal(tree, out)


# -- the equivalence contract ----------------------------------------------


def test_bucketed_dense_bitwise_with_jit():
    """The tentpole gate: flat bucketed-dense == plain jit, bitwise,
    over multiple steps (params AND loss)."""
    loss_fn, state, batch = _mlp_problem()
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1}))
    placed = mesh_lib.shard_batch(mesh, batch)
    jit_step = make_train_step(loss_fn, donate=False)
    comm_step = comm.make_comm_train_step(
        loss_fn, mesh=mesh, donate=False,
        config=comm.CommConfig(bucket_mb=0.001))
    s1, s2 = _replicate(mesh, state), _replicate(mesh, state)
    for _ in range(3):
        s1, m1 = jit_step(s1, placed)
        s2, m2 = comm_step(s2, placed)
        assert float(m1["loss"]) == float(m2["loss"])
        assert comm.tree_bitwise_equal(s1.params, s2.params)
    assert comm_step.plan.n_buckets > 1  # multiple buckets exercised


def test_hybrid_two_slice_dryrun_loss_parity():
    """The 2-slice dryrun term: hierarchical dense (reduce-scatter ->
    cross-slice psum -> all-gather) against the flat jit trajectory —
    a re-associated sum, loss parity at float tolerance."""
    loss_fn, state, batch = _mlp_problem(seed=1)
    topo = mesh_lib.SliceTopology(2, WORLD // 2)
    hybrid = mesh_lib.make_hybrid_mesh(mesh_lib.MeshSpec({"dp": -1}),
                                       topo)
    flat = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1}))
    jit_step = make_train_step(loss_fn, donate=False)
    comm_step = comm.make_comm_train_step(
        loss_fn, mesh=hybrid, topology=topo, donate=False,
        config=comm.CommConfig(bucket_mb=0.001))
    s1 = _replicate(flat, state)
    s2 = _replicate(hybrid, state)
    fb = mesh_lib.shard_batch(flat, batch)
    hb = mesh_lib.shard_batch(hybrid, batch)
    for _ in range(3):
        s1, m1 = jit_step(s1, fb)
        s2, m2 = comm_step(s2, hb)
        assert float(m2["loss"]) == pytest.approx(float(m1["loss"]),
                                                  abs=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        s1.params, s2.params)
    assert comm_step.dcn_bytes_per_step() > 0


def test_parity_gate_reports_ok():
    loss_fn, state, batch = _mlp_problem(seed=2)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1}))
    gate = comm.loss_parity_gate(
        loss_fn, state, batch, mesh=mesh,
        config=comm.CommConfig(bucket_mb=0.001, compress="topk",
                               topk_frac=0.25, min_compress_elems=16),
        steps=2, envelope=0.2)
    assert gate["bitwise_dense"] is True
    assert gate["dense_loss_delta"] == 0.0
    assert "max_loss_delta" in gate and gate["loss_envelope_ok"]
    assert gate["ok"]


# -- sparse cross-slice leg -------------------------------------------------


def _run_cross_topk(values: np.ndarray, resid: np.ndarray, k: int):
    """Drive _cross_topk under shard_map on the flat dp axis (every
    chip its own slice): values/resid are (W, m) per-device rows."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1}))
    groups = [list(range(WORLD))]

    def fn(v, e):
        out, e2 = comm._cross_topk(v.reshape(-1), e.reshape(-1), "dp",
                                   groups, k)
        return out.reshape(1, -1), e2.reshape(1, -1)

    f = shard_map(fn, mesh=mesh, in_specs=(P("dp"), P("dp")),
                  out_specs=(P(), P("dp")))
    return f(jnp.asarray(values), jnp.asarray(resid))


def test_sparse_topk_full_k_matches_dense_psum():
    rng = np.random.default_rng(7)
    v = rng.normal(size=(WORLD, 24)).astype(np.float32)
    out, resid = _run_cross_topk(v, np.zeros_like(v), k=24)
    np.testing.assert_allclose(np.asarray(out)[0], v.sum(0), rtol=1e-6,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(resid), 0.0, atol=1e-7)


def test_sparse_topk_conserves_gradient_mass():
    """sent + residual == contribution, per chip — nothing is lost,
    only deferred (the error-feedback invariant)."""
    rng = np.random.default_rng(8)
    v = rng.normal(size=(WORLD, 32)).astype(np.float32)
    out, resid = _run_cross_topk(v, np.zeros_like(v), k=4)
    # the reduced result plus every chip's residual re-adds to the
    # dense sum
    np.testing.assert_allclose(
        np.asarray(out)[0] + np.asarray(resid).sum(0), v.sum(0),
        rtol=1e-5, atol=1e-6)
    # each chip kept exactly k entries; the rest sit in its residual
    assert ((np.asarray(resid) != 0).sum(axis=1) == 32 - 4).all()


def test_residual_carryover_across_steps():
    """A value too small to make step 1's top-k accumulates in the
    residual and ships once it dominates — DGC's deferred send."""
    v = np.zeros((WORLD, 16), np.float32)
    v[0, :4] = [10.0, 9.0, 8.0, 7.0]  # chip 0's big entries
    v[0, 5] = 0.6                      # small: dropped at k=4
    out1, resid1 = _run_cross_topk(v, np.zeros_like(v), k=4)
    assert float(np.asarray(out1)[0, 5]) == 0.0
    assert float(np.asarray(resid1)[0, 5]) == pytest.approx(0.6)
    # step 2: same small value again; 0.6 + 0.6 rides the residual.
    # big entries zero this step, so the deferred mass dominates.
    v2 = np.zeros_like(v)
    v2[0, 5] = 0.6
    out2, resid2 = _run_cross_topk(v2, np.asarray(resid1), k=4)
    assert float(np.asarray(out2)[0, 5]) == pytest.approx(1.2)
    assert float(np.asarray(resid2)[0, 5]) == 0.0


def test_compressed_step_threads_residual_state():
    """Integration: the CommTrainStep's residual cell is live — after a
    topk step the stored comm state is nonzero and has the (W, m)
    dp-sharded layout."""
    loss_fn, state, batch = _mlp_problem(seed=3)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1}))
    step = comm.make_comm_train_step(
        loss_fn, mesh=mesh, donate=False,
        config=comm.CommConfig(bucket_mb=0.001, compress="topk",
                               topk_frac=0.125, min_compress_elems=16))
    placed = mesh_lib.shard_batch(mesh, batch)
    s = _replicate(mesh, state)
    s, _ = step(s, placed)
    assert step._comm, "no residual state threaded"
    total = 0.0
    for r, b in zip(step._comm, step.plan.buckets):
        if r.shape[1]:
            assert r.shape == (WORLD, b.padded)  # chips=1: full bucket
            total += float(jnp.sum(jnp.abs(r)))
    assert total > 0.0


# -- int8 wire --------------------------------------------------------------


def test_int8_pack_roundtrip_error_bounded():
    from edl_tpu.ops.pack import pack_int8, unpack_int8
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.normal(size=(300,)).astype(np.float32))
    q, scale = pack_int8(x)
    assert q.dtype == jnp.int8 and q.shape == x.shape
    err = np.abs(np.asarray(unpack_int8(q, scale)) - np.asarray(x))
    assert err.max() <= float(scale) / 2 + 1e-7


def test_int8_pack_zero_vector_exact():
    from edl_tpu.ops.pack import pack_int8, unpack_int8
    q, scale = pack_int8(jnp.zeros((64,)))
    assert float(scale) == 1.0
    assert not np.asarray(q).any()
    assert not np.asarray(unpack_int8(q, scale)).any()


def test_int8_pallas_kernel_matches_xla(monkeypatch):
    from edl_tpu.ops import pack as pack_mod
    rng = np.random.default_rng(10)
    # ragged length: exercises the lane-padding path in the kernel
    x = jnp.asarray(rng.normal(size=(200,)).astype(np.float32))
    q_xla, s_xla = pack_mod._pack_xla(x)
    monkeypatch.setattr(pack_mod, "_FORCE_INTERPRET", True)
    q_k, s_k = pack_mod.pack_int8(x)
    assert float(s_xla) == pytest.approx(float(s_k), rel=1e-6)
    np.testing.assert_array_equal(np.asarray(q_xla), np.asarray(q_k))


def test_int8_step_tracks_dense_within_envelope():
    loss_fn, state, batch = _mlp_problem(seed=4)
    topo = mesh_lib.SliceTopology(2, WORLD // 2)
    mesh = mesh_lib.make_hybrid_mesh(mesh_lib.MeshSpec({"dp": -1}), topo)
    gate = comm.loss_parity_gate(
        loss_fn, state, batch, mesh=mesh, topology=topo,
        config=comm.CommConfig(bucket_mb=0.001, compress="int8",
                               min_compress_elems=16),
        steps=3, envelope=5e-3)
    assert gate["loss_envelope_ok"], gate


# -- knobs / validation / wiring -------------------------------------------


def test_loop_config_env_knobs(monkeypatch):
    from edl_tpu.train.loop import LoopConfig
    from edl_tpu.utils.config import from_env
    monkeypatch.setenv("EDL_TPU_DCN_COMPRESS", "topk")
    monkeypatch.setenv("EDL_TPU_COMM_BUCKET_MB", "2.5")
    cfg = from_env(LoopConfig)
    assert cfg.dcn_compress == "topk"
    assert cfg.comm_bucket_mb == 2.5


def test_comm_config_validation():
    with pytest.raises(ValueError):
        comm.CommConfig(compress="gzip")
    with pytest.raises(ValueError):
        comm.CommConfig(bucket_mb=0)
    with pytest.raises(ValueError):
        comm.CommConfig(topk_frac=0.0)


def test_make_train_step_routing_and_conflicts():
    loss_fn, state, batch = _mlp_problem()
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1}))
    cfg = comm.CommConfig(bucket_mb=1.0)
    step = make_train_step(loss_fn, comm=cfg, mesh=mesh)
    assert isinstance(step, comm.CommTrainStep)
    with pytest.raises(ValueError):
        make_train_step(loss_fn, comm=cfg)  # no mesh
    with pytest.raises(ValueError):
        make_train_step(loss_fn, comm=cfg, mesh=mesh, loss_scale=True)


def test_non_dp_mesh_rejected():
    loss_fn, *_ = _mlp_problem()
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1, "tp": 2}))
    with pytest.raises(ValueError, match="dp-only"):
        comm.make_comm_train_step(loss_fn, mesh=mesh,
                                  config=comm.CommConfig())
    with pytest.raises(ValueError, match="n_slices"):
        comm.make_comm_train_step(
            loss_fn, mesh=mesh_lib.make_mesh(mesh_lib.MeshSpec(
                {"dp": -1})),
            topology=mesh_lib.SliceTopology(3, 2),
            config=comm.CommConfig())


def test_stats_and_obs_counter():
    from edl_tpu.obs import metrics as obs_metrics
    loss_fn, state, batch = _mlp_problem(seed=5)
    topo = mesh_lib.SliceTopology(2, WORLD // 2)
    mesh = mesh_lib.make_hybrid_mesh(mesh_lib.MeshSpec({"dp": -1}), topo)
    step = comm.make_comm_train_step(
        loss_fn, mesh=mesh, topology=topo, donate=False,
        config=comm.CommConfig(bucket_mb=0.001))
    counter = obs_metrics.registry().counter("step_dcn_bytes")
    before = counter.value
    placed = mesh_lib.shard_batch(mesh, batch)
    s = _replicate(mesh, state)
    s, _ = step(s, placed)
    s, _ = step(s, placed)
    stats = step.stats()
    assert stats["comm_steps"] == 2
    assert stats["dcn_bytes_per_step"] > 0
    assert stats["dcn_overlap_pct"] > 0  # multi-bucket plan
    assert counter.value - before == 2 * stats["dcn_bytes_per_step"]


def test_batch_stats_model_trains_under_comm_path():
    """BN models ride the comm path: batch_stats fold in (pmean across
    shards — the documented delta vs global-batch stats) and training
    matches jit within tolerance."""
    import flax.linen as nn
    from edl_tpu.train import classification as cls

    class TinyBN(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = True):
            x = nn.Conv(8, (3, 3))(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            x = nn.relu(x).reshape((x.shape[0], -1))
            return nn.Dense(4)(x)

    rng = np.random.default_rng(6)
    x = rng.normal(size=(16, 8, 8, 3)).astype(np.float32)
    y = rng.integers(0, 4, size=16).astype(np.int32)
    model = TinyBN()
    state = cls.create_state(model, jax.random.PRNGKey(0), (1, 8, 8, 3),
                             optax.sgd(0.05))
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1}))

    def loss_fn(state, params, batch):
        variables = {"params": params, "batch_stats": state.batch_stats}
        logits, mutated = state.apply_fn(variables, batch["image"],
                                         train=True,
                                         mutable=["batch_stats"])
        onehot = jax.nn.one_hot(batch["label"], 4)
        loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot,
                                 axis=-1))
        return loss, {"batch_stats": mutated["batch_stats"]}

    placed = mesh_lib.shard_batch(mesh, {"image": x, "label": y})
    jit_step = make_train_step(loss_fn, donate=False)
    comm_step = comm.make_comm_train_step(
        loss_fn, mesh=mesh, donate=False,
        config=comm.CommConfig(bucket_mb=0.001))
    s1, s2 = _replicate(mesh, state), _replicate(mesh, state)
    for _ in range(2):
        s1, m1 = jit_step(s1, placed)
        s2, m2 = comm_step(s2, placed)
    # BN under the manual path normalizes PER SHARD (the reference's
    # per-GPU convention); the jit path normalizes over the global
    # batch — a documented semantic delta, bounded by the envelope
    assert float(m2["loss"]) == pytest.approx(float(m1["loss"]),
                                              abs=0.05)
    # shard-mean of means == global mean; variances differ by the
    # between-shard variance term — loose tolerance on the stats tree
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=0.15),
        s1.batch_stats, s2.batch_stats)


def test_dense_path_residual_state_is_empty_width():
    loss_fn, state, batch = _mlp_problem(seed=7)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1}))
    step = comm.make_comm_train_step(
        loss_fn, mesh=mesh, donate=False,
        config=comm.CommConfig(bucket_mb=0.001))
    placed = mesh_lib.shard_batch(mesh, batch)
    s = _replicate(mesh, state)
    step(s, placed)
    assert all(r.shape[1] == 0 for r in step._comm)
    assert step.dcn_bytes_per_step() == 0  # flat world, dense: no DCN


def test_dcn_reduce_span_emitted_when_tracing(monkeypatch):
    """The obs satellite: with tracing on, every comm-step dispatch
    rides a `step.dcn_reduce` span carrying the wire accounting."""
    import contextlib

    from edl_tpu.obs import trace

    calls = []

    @contextlib.contextmanager
    def fake_span(name, parent=None, attrs=None):
        calls.append((name, attrs))
        yield None

    monkeypatch.setattr(trace, "enabled", lambda: True)
    monkeypatch.setattr(trace, "span", fake_span)
    loss_fn, state, batch = _mlp_problem(seed=8)
    topo = mesh_lib.SliceTopology(2, WORLD // 2)
    mesh = mesh_lib.make_hybrid_mesh(mesh_lib.MeshSpec({"dp": -1}), topo)
    step = comm.make_comm_train_step(
        loss_fn, mesh=mesh, topology=topo, donate=False,
        config=comm.CommConfig(bucket_mb=0.001))
    s = _replicate(mesh, state)
    step(s, mesh_lib.shard_batch(mesh, batch))
    assert calls and calls[0][0] == "step.dcn_reduce"
    assert calls[0][1]["dcn_bytes"] == step.dcn_bytes_per_step()
    assert calls[0][1]["buckets"] == step.plan.n_buckets


def test_sparse_psum_axis_index_groups_scope_reduction():
    """dgc.sparse_psum grown group scoping: with axis_index_groups the
    top-k exchange stays INSIDE each group (the hierarchical DCN-leg
    contract — mesh.dp_comm_groups feeds exactly these lists)."""
    from edl_tpu.train import dgc

    intra, _ = mesh_lib.dp_comm_groups(2, WORLD // 2)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1}))
    rng = np.random.default_rng(11)
    v = rng.normal(size=(WORLD, 64)).astype(np.float32)

    def fn(x):
        out = dgc.sparse_psum({"g": x.reshape(-1)}, "dp", keep_frac=1.0,
                              axis_index_groups=intra)
        return out["g"].reshape(1, -1)

    out = shard_map(fn, mesh=mesh, in_specs=(P("dp"),),
                    out_specs=P("dp"))(jnp.asarray(v))
    out = np.asarray(out)
    # every device holds ITS group's sum, not the global sum
    np.testing.assert_allclose(out[0], v[:4].sum(0), rtol=1e-6)
    np.testing.assert_allclose(out[7], v[4:].sum(0), rtol=1e-6)
    assert not np.allclose(out[0], v.sum(0))

    # sparse path (k=1 per worker): contributions stay group-local
    one = np.zeros((WORLD, 64), np.float32)
    one[0, 3] = 5.0   # group 0's only mass
    one[4, 9] = -7.0  # group 1's only mass

    def fn2(x):
        out = dgc.sparse_psum({"g": x.reshape(-1)}, "dp",
                              keep_frac=1 / 64,
                              axis_index_groups=intra)
        return out["g"].reshape(1, -1)

    out2 = np.asarray(shard_map(fn2, mesh=mesh, in_specs=(P("dp"),),
                                out_specs=P("dp"))(jnp.asarray(one)))
    assert out2[0, 3] == 5.0 and out2[0, 9] == 0.0
    assert out2[7, 9] == -7.0 and out2[7, 3] == 0.0
