"""Hybrid ICI x DCN multi-slice meshes on the 8-device CPU world.

The CPU world has no hardware slice_index, so every multi-slice test
passes an explicit SliceTopology — the same emulation path the dryrun
uses. The invariants under test are topology-independent: dp's major
dimension enumerates slices, every other axis stays slice-local, and a
hybrid mesh is a pure device PERMUTATION of the flat mesh, so training
math is identical to numerical tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.collective.cluster import Pod, form_cluster
from edl_tpu.collective.job_env import JobEnv, TrainerEnv, trainer_environ
from edl_tpu.parallel.distributed import make_mesh_from_env, slice_topology
from edl_tpu.parallel.mesh import (
    MeshSpec, SliceTopology, detect_slice_topology, dp_size,
    form_global_batch, make_hybrid_mesh, make_mesh, shard_batch)


def _slice_of(device, chips_per_slice):
    """Emulated slice id: contiguous chunks of the flat device list."""
    return jax.devices().index(device) // chips_per_slice


# -- resolution against (n_slices, chips_per_slice) -------------------------

def test_resolve_hybrid_wildcard_dp_absorbs_both_levels():
    dcn, ici = MeshSpec({"dp": -1, "tp": 2}).resolve_hybrid(
        SliceTopology(2, 4))
    assert dcn == {"dp": 2, "tp": 1}
    assert ici == {"dp": 2, "tp": 2}


def test_resolve_hybrid_fixed_dp_splits_on_slices():
    dcn, ici = MeshSpec({"dp": 8}).resolve_hybrid(SliceTopology(2, 4))
    assert (dcn["dp"], ici["dp"]) == (2, 4)


def test_resolve_hybrid_wildcard_nondp():
    dcn, ici = MeshSpec({"dp": 2, "fsdp": -1}).resolve_hybrid(
        SliceTopology(2, 4))
    assert dcn == {"dp": 2, "fsdp": 1}
    assert ici == {"dp": 1, "fsdp": 4}


def test_resolve_hybrid_rejects_bad_shapes():
    topo = SliceTopology(2, 4)
    with pytest.raises(ValueError):  # no dp axis to carry DCN
        MeshSpec({"fsdp": 8}).resolve_hybrid(topo)
    with pytest.raises(ValueError):  # dp not divisible by n_slices
        MeshSpec({"dp": 3, "tp": -1}).resolve_hybrid(topo)
    with pytest.raises(ValueError):  # tp does not fit in a slice
        MeshSpec({"dp": -1, "tp": 3}).resolve_hybrid(topo)
    with pytest.raises(ValueError):
        MeshSpec({"dp": -1, "tp": -1}).resolve_hybrid(topo)


def test_single_slice_degenerates_to_flat():
    flat = make_mesh(MeshSpec({"dp": -1, "tp": 2}))
    hyb = make_hybrid_mesh(MeshSpec({"dp": -1, "tp": 2}),
                           SliceTopology(1, 8))
    assert hyb.shape == flat.shape
    assert [d.id for d in hyb.devices.flat] == \
        [d.id for d in flat.devices.flat]


# -- device placement: dp crosses DCN, the rest stays slice-local -----------

def test_dp_major_enumerates_slices_and_others_stay_local():
    topo = SliceTopology(2, 4)
    mesh = make_hybrid_mesh(MeshSpec({"dp": -1, "tp": 2}), topo)
    devs = mesh.devices  # (dp=4, tp=2)
    assert mesh.shape == {"dp": 4, "tp": 2}
    # dp's major half is entirely slice 0, minor half slice 1
    assert {_slice_of(d, 4) for d in devs[:2].flat} == {0}
    assert {_slice_of(d, 4) for d in devs[2:].flat} == {1}
    # every tp line lives inside ONE slice (no per-layer DCN traffic)
    for row in devs:
        assert len({_slice_of(d, 4) for d in row}) == 1


def test_topology_must_match_device_count():
    with pytest.raises(ValueError):
        make_hybrid_mesh(MeshSpec({"dp": -1}), SliceTopology(2, 3))


def test_detect_slice_topology_flat_on_cpu():
    topo = detect_slice_topology(jax.devices())
    assert topo == SliceTopology(1, 8)
    assert not topo.is_multi_slice


# -- elasticity: the hybrid mesh re-forms across resizes --------------------

def test_hybrid_mesh_reforms_across_resizes():
    """2 -> 4 -> 8 devices, always 2 slices: per-slice axes re-resolve
    against chips_per_slice, dp absorbs the growth, batches place."""
    spec = MeshSpec({"dp": -1, "fsdp": 2})
    for n in (4, 8):  # fsdp=2 needs >=2 chips per slice
        topo = SliceTopology(2, n // 2)
        mesh = make_hybrid_mesh(spec, topo, n_devices=n)
        assert mesh.shape["fsdp"] == 2
        assert mesh.shape["dp"] == n // 2
        assert dp_size(mesh) == n
        batch = shard_batch(mesh, {"x": np.arange(2 * n * 3, dtype=np.float32)
                                   .reshape(2 * n, 3)})
        assert batch["x"].addressable_shards[0].data.shape == (2, 3)
    # the 2-device world: one chip per slice, dp-only
    mesh = make_hybrid_mesh(MeshSpec({"dp": -1}), SliceTopology(2, 1),
                            n_devices=2)
    assert mesh.shape == {"dp": 2}
    assert dp_size(mesh) == 2


def test_shard_batch_rows_follow_dp_device_order():
    """When dp spans the slice axis, row blocks land slice-major: the
    first half of the batch on slice 0, second half on slice 1 — the
    layout form_global_batch's per-process contiguous-slice contract
    relies on in a real multi-slice world."""
    topo = SliceTopology(2, 4)
    mesh = make_hybrid_mesh(MeshSpec({"dp": -1}), topo)
    x = np.arange(16 * 2, dtype=np.float32).reshape(16, 2)
    placed = shard_batch(mesh, {"x": x})["x"]
    np.testing.assert_array_equal(np.asarray(placed), x)  # round trip
    for shard in placed.addressable_shards:
        rows = shard.data[:, 0] / 2  # row ids (x[i, 0] = 2i)
        lo = rows.min()
        # rows 0-7 (batch half 0) must sit on slice-0 devices
        assert _slice_of(shard.device, 4) == (0 if lo < 8 else 1)


def test_form_global_batch_on_hybrid_mesh():
    """Single-process world: degenerates to shard_batch but must honor
    the hybrid data sharding (dp spanning slices)."""
    topo = SliceTopology(2, 4)
    mesh = make_hybrid_mesh(MeshSpec({"dp": -1, "fsdp": 2}), topo)
    local = {"x": np.arange(8 * 2, dtype=np.float32).reshape(8, 2)}
    placed = form_global_batch(mesh, local)
    np.testing.assert_array_equal(np.asarray(placed["x"]), local["x"])
    assert placed["x"].addressable_shards[0].data.shape == (1, 2)


# -- the tentpole invariant: hybrid == flat to numerical tolerance ----------

def test_hybrid_mesh_loss_matches_flat_mesh():
    """Same params, same data, one dp-allreduced gradient step on the
    flat {dp:8} mesh vs the 2-slice hybrid — the hybrid mesh is a device
    permutation; loss and updated params must agree."""
    rng = np.random.default_rng(0)
    w0 = jnp.asarray(rng.normal(size=(3, 5)).astype(np.float32))
    x = np.asarray(rng.normal(size=(16, 3)).astype(np.float32))
    y = np.asarray(rng.normal(size=(16, 5)).astype(np.float32))

    def run(mesh):
        @jax.jit
        def step(w, batch):
            def loss_fn(w):
                return jnp.mean((batch["x"] @ w - batch["y"]) ** 2)
            loss, g = jax.value_and_grad(loss_fn)(w)
            return w - 0.1 * g, loss

        batch = shard_batch(mesh, {"x": x, "y": y})
        w = jax.device_put(w0, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()))
        for _ in range(3):
            w, loss = step(w, batch)
        return np.asarray(w), float(loss)

    flat_w, flat_loss = run(make_mesh(MeshSpec({"dp": -1})))
    hyb_w, hyb_loss = run(make_hybrid_mesh(MeshSpec({"dp": -1}),
                                           SliceTopology(2, 4)))
    assert np.isclose(hyb_loss, flat_loss, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(hyb_w, flat_w, rtol=1e-5, atol=1e-6)


def test_hybrid_mesh_loss_matches_flat_mesh_with_fsdp():
    """Same invariant with a 2D dp x fsdp data world (both axes carry
    batch rows; fsdp is slice-local in the hybrid layout)."""
    rng = np.random.default_rng(1)
    w0 = jnp.asarray(rng.normal(size=(4, 4)).astype(np.float32))
    x = np.asarray(rng.normal(size=(16, 4)).astype(np.float32))

    def run(mesh):
        @jax.jit
        def loss(w, batch):
            return jnp.mean(jnp.tanh(batch["x"] @ w) ** 2)

        batch = shard_batch(mesh, {"x": x})
        w = jax.device_put(w0, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()))
        return float(loss(w, batch))

    spec = MeshSpec({"dp": -1, "fsdp": 2})
    flat = run(make_mesh(spec))
    hyb = run(make_hybrid_mesh(spec, SliceTopology(2, 4)))
    assert np.isclose(hyb, flat, rtol=1e-5, atol=1e-7)


# -- env contract -----------------------------------------------------------

def test_slice_topology_env_beats_detection():
    topo = slice_topology(TrainerEnv(n_slices=2))
    assert topo == SliceTopology(2, 4)
    assert slice_topology(TrainerEnv()) == SliceTopology(1, 8)
    with pytest.raises(ValueError):
        slice_topology(TrainerEnv(n_slices=3))  # 8 % 3 != 0


def test_make_mesh_from_env_hybrid_vs_flat():
    spec = MeshSpec({"dp": -1, "fsdp": 2})
    hyb = make_mesh_from_env(spec, TrainerEnv(n_slices=2))
    assert hyb.shape == {"dp": 4, "fsdp": 2}
    # dp-major half on slice 0 => it IS the hybrid layout
    assert {_slice_of(d, 4) for d in hyb.devices[:2].flat} == {0}
    flat = make_mesh_from_env(spec, TrainerEnv())
    assert [d.id for d in flat.devices.flat] == list(range(8))


def test_trainer_environ_carries_slice_contract():
    pods = [Pod(pod_id=f"p{i}", addr="127.0.0.1", port=7000 + i,
                claimed_rank=i) for i in range(4)]
    cluster = form_cluster("job", 1, pods)
    job = JobEnv.from_environ(job_id="job", pod_id="p2", slices=2)
    env = trainer_environ(cluster, "p2", job)
    assert env["EDL_TPU_SLICES"] == "2"
    assert env["EDL_TPU_SLICE_ID"] == "1"  # ranks 2,3 -> slice 1
    # rank-contiguous: first half of the ranks is slice 0
    assert trainer_environ(cluster, "p0", job)["EDL_TPU_SLICE_ID"] == "0"
    assert trainer_environ(cluster, "p1", job)["EDL_TPU_SLICE_ID"] == "0"
    # flat jobs keep the auto markers
    flat = trainer_environ(cluster, "p0",
                           JobEnv.from_environ(job_id="job", pod_id="p0"))
    assert flat["EDL_TPU_SLICES"] == "0"
    assert flat["EDL_TPU_SLICE_ID"] == "-1"
    # one pod spanning both slices locally (emulation / single-host):
    # slice id is per-device, not per-pod -> auto marker
    solo = form_cluster("job", 1, [Pod(pod_id="p0", addr="127.0.0.1",
                                       port=7000, claimed_rank=0)])
    env1 = trainer_environ(solo, "p0",
                           JobEnv.from_environ(job_id="job", pod_id="p0",
                                               slices=2))
    assert env1["EDL_TPU_SLICES"] == "2"
    assert env1["EDL_TPU_SLICE_ID"] == "-1"
    from edl_tpu.collective.job_env import slice_of_rank
    with pytest.raises(ValueError):
        slice_of_rank(0, 3, 2)  # 3 pods, 2 slices: neither divides


def test_trainer_env_parses_slice_vars(monkeypatch):
    monkeypatch.setenv("EDL_TPU_SLICES", "2")
    monkeypatch.setenv("EDL_TPU_SLICE_ID", "1")
    env = TrainerEnv.from_environ()
    assert env.n_slices == 2 and env.slice_id == 1
