"""BOW/CNN text students, DeepFM CTR model, and their example pipelines.

Covers the reference's NLP-distill students (example/distill/nlp/model.py)
and CTR model + file-dispensed training (example/ctr/ctr/train.py over the
task master) at test scale.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from edl_tpu.models.bow import BOWClassifier, CNNClassifier
from edl_tpu.models.deepfm import DeepFM, auc, bce_with_logits


class TestTextModels:
    @pytest.mark.parametrize("cls_", [BOWClassifier, CNNClassifier])
    def test_forward_shapes(self, cls_):
        model = cls_(vocab_size=100, embed_dim=16, num_classes=2)
        ids = jnp.array([[1, 2, 3, 0, 0], [4, 5, 0, 0, 0]], jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), ids)
        logits = model.apply(variables, ids)
        assert logits.shape == (2, 2)

    def test_padding_is_ignored(self):
        """Appending pad ids must not change the logits (masked sum)."""
        model = BOWClassifier(vocab_size=100, embed_dim=16)
        short = jnp.array([[7, 8, 9, 0, 0, 0]], jnp.int32)
        longer = jnp.array([[7, 8, 9, 0, 0, 0, 0, 0, 0, 0]], jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), short)
        np.testing.assert_allclose(model.apply(variables, short),
                                   model.apply(variables, longer), rtol=1e-6)

    def test_bow_learns_band_task(self):
        from edl_tpu.examples.nlp_distill import synthetic_sentiment
        from edl_tpu.examples.nlp_distill import _fit, _pure_ce_step, _acc
        from edl_tpu.train.classification import make_eval_step

        data = synthetic_sentiment(1024, seed=0, noise=0.0)
        model = BOWClassifier(vocab_size=4000, embed_dim=16)
        state = _fit(model, data, epochs=6, batch_size=128, lr=3e-3, seed=0,
                     step_builder=_pure_ce_step)
        acc = _acc(state, data, make_eval_step(input_key="ids"))
        assert acc > 0.8, acc


class TestDeepFM:
    def _batch(self, n=4):
        rng = np.random.default_rng(0)
        return (jnp.asarray(rng.normal(size=(n, 13)).astype(np.float32)),
                jnp.asarray(rng.integers(0, 50, size=(n, 26), dtype=np.int32)))

    def test_forward_shape(self):
        model = DeepFM(vocab_size=50, embed_dim=4, hidden=(8,))
        dense, sparse = self._batch()
        variables = model.init(jax.random.PRNGKey(0), dense, sparse)
        out = model.apply(variables, dense, sparse)
        assert out.shape == (4, 1)

    def test_fm_second_order_identity(self):
        """FM term equals the explicit pairwise-dot sum."""
        model = DeepFM(vocab_size=50, embed_dim=4, hidden=(8,))
        dense, sparse = self._batch(2)
        variables = model.init(jax.random.PRNGKey(0), dense, sparse)
        emb = variables["params"]["sparse_embed"]["embedding"]
        vecs = np.asarray(emb)[np.asarray(sparse)]  # (B, F, D)
        explicit = np.zeros(2)
        for b in range(2):
            for i in range(26):
                for j in range(i + 1, 26):
                    explicit[b] += float(vecs[b, i] @ vecs[b, j])
        s = vecs.sum(axis=1)
        identity = 0.5 * ((s * s).sum(-1) - (vecs * vecs).sum(-1).sum(-1))
        np.testing.assert_allclose(identity, explicit, rtol=1e-4)

    def test_bce_matches_naive(self):
        logits = jnp.array([-2.0, 0.0, 3.0])
        labels = jnp.array([0.0, 1.0, 1.0])
        p = jax.nn.sigmoid(logits)
        naive = -jnp.mean(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p))
        np.testing.assert_allclose(float(bce_with_logits(logits, labels)),
                                   float(naive), rtol=1e-5)

    def test_auc_known_values(self):
        # perfect ranking -> 1.0; inverted -> 0.0; random-ish -> 0.5 w/ ties
        assert auc([0.1, 0.2, 0.8, 0.9], [0, 0, 1, 1]) == 1.0
        assert auc([0.9, 0.8, 0.2, 0.1], [1, 1, 0, 0]) == 1.0
        assert auc([0.9, 0.8, 0.2, 0.1], [0, 0, 1, 1]) == 0.0
        assert auc([0.5, 0.5, 0.5, 0.5], [0, 1, 0, 1]) == 0.5
        assert np.isnan(auc([0.5, 0.4], [1, 1]))


class TestCtrPipeline:
    @pytest.mark.slow
    def test_ctr_train_end_to_end(self, tmp_path):
        """Full CLI path: synthesize files, dispense via TaskMaster, train,
        AUC improves over chance, benchmark log written."""
        import json
        from edl_tpu.examples.ctr_train import main

        rc = main(["--data-dir", str(tmp_path / "data"),
                   "--make-synthetic", "3", "--rows-per-file", "2048",
                   "--epochs", "6", "--hidden", "64", "--lr", "3e-3",
                   "--batch-size", "256",
                   "--benchmark-log", str(tmp_path / "blog")])
        assert rc == 0
        blog = json.load(open(tmp_path / "blog" / "log_0.json"))
        assert blog["model"] == "deepfm_ctr"
        assert len(blog["epochs"]) == 6
        assert blog["final"]["auc"] > 0.62, blog["final"]
        assert blog["max_examples_per_sec"] > 0

    def test_ctr_tasks_shared_across_trainers(self, tmp_path):
        """Two TaskDataLoaders on one store split an epoch exactly-once."""
        from edl_tpu.coord.store import InMemStore
        from edl_tpu.data.task_loader import TaskDataLoader, npz_loader
        from edl_tpu.data.task_master import TaskMaster, file_list_specs
        from edl_tpu.examples.ctr_train import make_synthetic_files

        import threading

        files = make_synthetic_files(str(tmp_path), 4, 512)
        store = InMemStore()
        masters = [TaskMaster(store, "j", f"t{i}") for i in range(2)]
        masters[0].init_epoch(0, file_list_specs(files))
        loaders = [TaskDataLoader(m, npz_loader, 128, poll=0.05)
                   for m in masters]
        rows = [0, 0]

        # one thread per trainer, like one process per pod: a loader may
        # block polling for the last pending task, which must not stall
        # the other trainer (the single-threaded round-robin version of
        # this test deadlocks by construction until leases expire).
        def run(i):
            for batch in loaders[i].epoch(0):
                rows[i] += len(batch["label"])

        threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert sum(rows) == 4 * 512  # every record exactly once
        assert (loaders[0].tasks_completed + loaders[1].tasks_completed) == 4
        assert loaders[0].tasks_lost == loaders[1].tasks_lost == 0
        counts = masters[0].counts(0)
        assert counts == {"todo": 0, "pending": 0, "done": 4, "failed": 0}


class TestNlpDistillPipeline:
    @pytest.mark.slow
    def test_distill_beats_alone(self):
        """The full wire pipeline at tiny scale: teacher serves over TCP,
        student distills through DistillReader; distilled student must not
        be (much) worse than the from-scratch baseline and the pipeline
        must complete cleanly."""
        from edl_tpu.examples.nlp_distill import main

        rc = main(["--all-in-one", "--samples", "512", "--epochs", "2",
                   "--teacher-epochs", "2", "--distill-extra", "512",
                   "--batch-size", "128", "--lr", "3e-3"])
        assert rc == 0
