"""InMemStore semantics: puts/gets, CAS, leases, expiry, events.

Test model: reference etcd_client_test.py (register/refresh/expiry — "key
must not alive when expired", watch events, lease keepalive, permanent keys).
"""

import pytest

from edl_tpu.coord.store import InMemStore
from edl_tpu.utils.exceptions import EdlLeaseExpired


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def store(clock):
    return InMemStore(clock=clock)


def test_put_get_delete(store):
    rev1 = store.put("/a/x", "1")
    rev2 = store.put("/a/y", "2")
    assert rev2 == rev1 + 1
    assert store.get("/a/x").value == "1"
    assert store.get("/missing") is None
    recs, rev = store.get_prefix("/a/")
    assert [r.key for r in recs] == ["/a/x", "/a/y"]
    assert rev == rev2
    assert store.delete("/a/x")
    assert not store.delete("/a/x")
    assert store.get("/a/x") is None


def test_put_overwrites_and_bumps_revision(store):
    r1 = store.put("/k", "v1")
    r2 = store.put("/k", "v2")
    assert r2 > r1
    assert store.get("/k").value == "v2"


def test_put_if_absent_races(store):
    assert store.put_if_absent("/rank/0", "pod-a")
    assert not store.put_if_absent("/rank/0", "pod-b")
    assert store.get("/rank/0").value == "pod-a"


def test_compare_and_swap(store):
    assert store.compare_and_swap("/k", None, "v1")
    assert not store.compare_and_swap("/k", None, "again")
    assert store.compare_and_swap("/k", "v1", "v2")
    assert not store.compare_and_swap("/k", "v1", "v3")
    assert store.get("/k").value == "v2"


def test_lease_expiry_deletes_keys(store, clock):
    lease = store.lease_grant(ttl=10.0)
    store.put("/svc/nodes/a", "meta", lease=lease)
    clock.advance(9.0)
    assert store.get("/svc/nodes/a") is not None
    clock.advance(2.0)
    # key must not be alive after the lease expired
    assert store.get("/svc/nodes/a") is None
    with pytest.raises(EdlLeaseExpired):
        store.put("/svc/nodes/b", "x", lease=lease)


def test_lease_keepalive_extends(store, clock):
    lease = store.lease_grant(ttl=10.0)
    store.put("/k", "v", lease=lease)
    for _ in range(5):
        clock.advance(8.0)
        assert store.lease_keepalive(lease)
    assert store.get("/k") is not None
    clock.advance(11.0)
    assert not store.lease_keepalive(lease)
    assert store.get("/k") is None


def test_lease_revoke(store):
    lease = store.lease_grant(ttl=100.0)
    store.put("/k", "v", lease=lease)
    assert store.lease_revoke(lease)
    assert store.get("/k") is None
    assert not store.lease_revoke(lease)


def test_permanent_key_outlives_leases(store, clock):
    store.put("/perm", "v")
    lease = store.lease_grant(ttl=1.0)
    store.put("/eph", "v", lease=lease)
    clock.advance(100.0)
    assert store.get("/perm") is not None
    assert store.get("/eph") is None


def test_overwrite_detaches_old_lease(store, clock):
    lease = store.lease_grant(ttl=5.0)
    store.put("/k", "v1", lease=lease)
    store.put("/k", "v2")  # now permanent
    clock.advance(10.0)
    assert store.get("/k").value == "v2"


def test_events_since(store, clock):
    r0 = store.put("/a", "1")
    store.put("/b", "2")
    store.delete("/a")
    evs, rev, compacted = store.events_since(r0)
    assert not compacted
    assert [(e.type, e.key) for e in evs] == [("PUT", "/b"), ("DELETE", "/a")]
    # prefix filter
    evs, _, _ = store.events_since(0, prefix="/a")
    assert [(e.type, e.key) for e in evs] == [("PUT", "/a"), ("DELETE", "/a")]
    # lease expiry shows up as DELETE events
    lease = store.lease_grant(ttl=1.0)
    store.put("/c", "3", lease=lease)
    clock.advance(2.0)
    evs, _, _ = store.events_since(rev)
    types = [(e.type, e.key) for e in evs]
    assert ("PUT", "/c") in types and ("DELETE", "/c") in types


def test_event_compaction(clock):
    store = InMemStore(clock=clock, max_events=4)
    for i in range(10):
        store.put(f"/k{i}", str(i))
    evs, rev, compacted = store.events_since(0)
    assert compacted
    # a recent revision still works
    evs, _, compacted = store.events_since(rev - 2)
    assert not compacted
    assert len(evs) == 2


def test_compact_trims_history_and_forces_resync(store):
    revs = [store.put(f"/c/{i}", str(i)) for i in range(20)]
    dropped = store.compact(revs[9], keep=4)
    assert dropped == 10
    # below the floor: compacted resync
    _, _, compacted = store.events_since(revs[4])
    assert compacted
    # at or above the floor: normal resume
    evs, _, compacted = store.events_since(revs[9])
    assert not compacted
    assert len(evs) == 10
    # the resume cushion is honoured: compacting "everything" keeps 4
    store.compact(revs[-1], keep=4)
    evs, _, compacted = store.events_since(revs[-5])
    assert not compacted
    assert len(evs) == 4


def test_delta_snapshot_round_trip(clock):
    leader = InMemStore(clock=clock)
    follower = InMemStore(clock=clock)
    for i in range(6):
        leader.put(f"/d/{i}", str(i))
    # follower holds a stale copy of /d/0 and an orphan the leader
    # never had
    follower.apply_put("/d/0", "stale", 1)
    follower.apply_put("/zombie", "x", 2)
    delta = leader.snapshot_delta(follower.state_digest())
    assert "/zombie" in delta["del"]
    assert len(delta["set"]) == 6          # /d/0 diverged + 5 missing
    follower.install_snapshot_delta(delta)
    assert follower.state_digest() == leader.state_digest()
    assert follower.get("/zombie") is None
    assert follower.get("/d/0").value == "0"


def test_delta_snapshot_skips_matching_records(clock):
    leader = InMemStore(clock=clock)
    for i in range(8):
        leader.put(f"/m/{i}", str(i))
    follower = InMemStore(clock=clock)
    follower.install_snapshot(leader.snapshot_state())
    delta = leader.snapshot_delta(follower.state_digest())
    assert delta["set"] == [] and delta["del"] == []
    leader.put("/m/3", "updated")
    delta = leader.snapshot_delta(follower.state_digest())
    assert [row[0] for row in delta["set"]] == ["/m/3"]


def test_digest_catches_same_revision_different_value(clock):
    # a dirty ex-leader can hold the SAME revision number with a
    # DIFFERENT value (its discarded uncommitted suffix) — the value
    # crc in the digest must flag it even though revisions match
    leader = InMemStore(clock=clock)
    dirty = InMemStore(clock=clock)
    rev = leader.put("/k", "committed")
    dirty.apply_put("/k", "doomed", rev)
    delta = leader.snapshot_delta(dirty.state_digest())
    assert [row[:2] for row in delta["set"]] == [["/k", "committed"]]
    dirty.install_snapshot_delta(delta)
    assert dirty.get("/k").value == "committed"


def test_install_snapshot_delta_resyncs_watchers(clock):
    leader = InMemStore(clock=clock)
    follower = InMemStore(clock=clock)
    w = follower.watch("/d/")
    for i in range(3):
        leader.put(f"/d/{i}", str(i))
    follower.install_snapshot_delta(
        leader.snapshot_delta(follower.state_digest()))
    batch = w.get(timeout=1.0)
    # history before the snapshot revision is unknowable: the watcher
    # gets the compacted resync, same contract as log compaction
    assert batch is not None and batch.compacted
    w.cancel()
