"""Full distill stack end-to-end: store + discovery + registrars + real
teacher servers + DistillReader in dynamic-discovery mode + elastic churn.

The working analogue of the reference's test_distill_reader.sh flow
(etcd + register + discovery_server + DistillReader, SURVEY.md §4) with a
teacher join AND a teacher kill mid-run — the "elastically resized teacher
pool, student unaffected" pillar (README.md:27-31).
"""

import time

import numpy as np

from edl_tpu.coord.store import InMemStore
from edl_tpu.distill.discovery_server import DiscoveryServer
from edl_tpu.distill.reader import DistillReader
from edl_tpu.distill.registrar import TeacherRegistrar
from edl_tpu.distill.teacher_server import TeacherServer


def ref_logits(images):
    return np.stack([images.sum(axis=1), images.max(axis=1)], axis=1)


def predict(feeds):
    time.sleep(0.005)
    return {"teacher_logits": ref_logits(feeds["image"])}


def make_batches(n_batches, rows=16, feat=8, seed=0):
    rng = np.random.default_rng(seed)
    return [{"image": rng.normal(size=(rows, feat)).astype(np.float32)}
            for _ in range(n_batches)]


def start_teacher(store):
    srv = TeacherServer(predict, host="127.0.0.1").start()
    endpoint = f"127.0.0.1:{srv.port}"
    registrar = TeacherRegistrar(store, "svc", endpoint, ttl=1.0,
                                 probe_timeout=10.0, probe_interval=0.05)
    registrar.start()
    return srv, registrar, endpoint


def test_discovery_driven_distill_with_churn():
    store = InMemStore()
    t1 = start_teacher(store)
    disco = DiscoveryServer(store, port=0, host="127.0.0.1",
                            tick_interval=0.1, client_ttl=10.0).start()
    batches = make_batches(n_batches=20)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"],
                       discovery=disco.endpoint, service="svc",
                       teacher_batch_size=4, manage_interval=0.05)
    t2 = None
    try:
        it = dr()
        got = [next(it)]

        # Teacher JOINS mid-epoch: discovery assigns it; throughput grows.
        t2 = start_teacher(store)
        got.append(next(it))

        # First teacher DIES mid-epoch (server + registrar): its lease
        # expires, discovery rebalances onto the survivor, in-flight tasks
        # re-queue. Student never notices.
        t1[0].stop()
        t1[1].stop()

        for item in it:
            got.append(item)

        assert len(got) == len(batches)
        for want, out in zip(batches, got):
            np.testing.assert_array_equal(out["image"], want["image"])
            np.testing.assert_allclose(out["teacher_logits"],
                                       ref_logits(want["image"]), rtol=1e-6)
    finally:
        dr.close()
        disco.stop()
        if t2 is not None:
            t2[0].stop()
            t2[1].stop()
