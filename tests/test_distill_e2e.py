"""Full distill stack end-to-end: store + discovery + registrars + real
teacher servers + DistillReader in dynamic-discovery mode + elastic churn.

The working analogue of the reference's test_distill_reader.sh flow
(etcd + register + discovery_server + DistillReader, SURVEY.md §4) with a
teacher join AND a teacher kill mid-run — the "elastically resized teacher
pool, student unaffected" pillar (README.md:27-31).
"""

import pytest

pytestmark = pytest.mark.slow  # the full distill stack with teacher churn

import time

import numpy as np

from edl_tpu.coord.store import InMemStore
from edl_tpu.distill.discovery_server import DiscoveryServer
from edl_tpu.distill.reader import DistillReader
from edl_tpu.distill.registrar import TeacherRegistrar
from edl_tpu.distill.teacher_server import TeacherServer


def ref_logits(images):
    return np.stack([images.sum(axis=1), images.max(axis=1)], axis=1)


def predict(feeds):
    time.sleep(0.005)
    return {"teacher_logits": ref_logits(feeds["image"])}


def make_batches(n_batches, rows=16, feat=8, seed=0):
    rng = np.random.default_rng(seed)
    return [{"image": rng.normal(size=(rows, feat)).astype(np.float32)}
            for _ in range(n_batches)]


def start_teacher(store):
    srv = TeacherServer(predict, host="127.0.0.1").start()
    endpoint = f"127.0.0.1:{srv.port}"
    registrar = TeacherRegistrar(store, "svc", endpoint, ttl=1.0,
                                 probe_timeout=10.0, probe_interval=0.05)
    registrar.start()
    return srv, registrar, endpoint


def test_discovery_driven_distill_with_churn():
    store = InMemStore()
    t1 = start_teacher(store)
    disco = DiscoveryServer(store, port=0, host="127.0.0.1",
                            tick_interval=0.1, client_ttl=10.0).start()
    batches = make_batches(n_batches=20)
    dr = DistillReader(lambda: iter(batches), feeds=["image"],
                       predicts=["teacher_logits"],
                       discovery=disco.endpoint, service="svc",
                       teacher_batch_size=4, manage_interval=0.05)
    t2 = None
    try:
        it = dr()
        got = [next(it)]

        # Teacher JOINS mid-epoch: discovery assigns it; throughput grows.
        t2 = start_teacher(store)
        got.append(next(it))

        # First teacher DIES mid-epoch (server + registrar): its lease
        # expires, discovery rebalances onto the survivor, in-flight tasks
        # re-queue. Student never notices.
        t1[0].stop()
        t1[1].stop()

        for item in it:
            got.append(item)

        assert len(got) == len(batches)
        for want, out in zip(batches, got):
            np.testing.assert_array_equal(out["image"], want["image"])
            np.testing.assert_allclose(out["teacher_logits"],
                                       ref_logits(want["image"]), rtol=1e-6)
    finally:
        dr.close()
        disco.stop()
        if t2 is not None:
            t2[0].stop()
            t2[1].stop()


def test_utilization_published_and_surfaced():
    """The scheduler data path (reference discovery/register.py:36-40 info
    field): teacher serving counters -> registrar stats loop -> registry
    info -> discovery server stats op."""
    import json

    from edl_tpu.distill.teacher_server import TeacherClient

    store = InMemStore()

    def predict(feeds):
        rows = next(iter(feeds.values())).shape[0]
        return {"logits": np.zeros((rows, 4), np.float32)}

    srv = TeacherServer(predict, host="127.0.0.1").start()
    endpoint = f"127.0.0.1:{srv.port}"
    reg = TeacherRegistrar(store, "svc", endpoint, ttl=5.0,
                           stats_interval=0.1).start()
    try:
        client = TeacherClient(endpoint)
        for _ in range(3):
            client.predict({"image": np.zeros((4, 8), np.float32)})
        raw = client.stats()
        assert raw["served_rows"] >= 12 and raw["served_requests"] >= 3
        client.close()

        deadline = time.time() + 5
        info = ""
        while time.time() < deadline:
            metas = reg.registry.get_service("svc")
            if metas and metas[0].info:
                info = metas[0].info
                break
            time.sleep(0.05)
        doc = json.loads(info)
        assert {"rows_per_sec", "util", "queue_depth",
                "batch_rows_mean"} <= set(doc)
        assert doc["rows_per_sec"] >= 0.0
        assert doc["batch_rows_mean"] >= 0.0

        # Surfaced through the discovery server's stats op.
        disco = DiscoveryServer(store, host="127.0.0.1",
                                tick_interval=0.1).start()
        try:
            disco.table.register("client-1", "svc")
            stats = disco.table.stats()
            assert endpoint in stats["svc"]["utilization"]
            assert stats["svc"]["utilization"][endpoint] == info or \
                json.loads(stats["svc"]["utilization"][endpoint]).keys() \
                == doc.keys()
        finally:
            disco.stop()
    finally:
        reg.stop()
        srv.stop()


def test_inflight_window_grows_when_teacher_joins():
    """D5's spirit (reference distill_reader.py:215 sizes the semaphore
    live): a teacher joining mid-epoch widens the in-flight window."""
    from edl_tpu.distill.reader import _EpochPipeline

    class _FakeReader:
        predicts = ("p",)
        _wire_predicts = ("p",)
        max_retries = 3
        pipeline_depth = 1              # depth 1 = the classic 2n+2 window
        compress_topk = 0
        sparse_predicts = False
        _client_factory = staticmethod(lambda ep: None)

        @staticmethod
        def _get_servers():
            return ["t0"]

    p = _EpochPipeline(_FakeReader())
    assert p._sem_slots == 4            # (1+1)*1+2
    p.resize_window(3)
    assert p._sem_slots == 8            # 2*3+2
    # 8 acquires must now succeed without blocking.
    got = sum(p.sem.acquire(blocking=False) for _ in range(9))
    assert got == 8
    for _ in range(got):
        p.sem.release()
    p.resize_window(1)                  # best-effort shrink
    assert p._sem_slots == 4
