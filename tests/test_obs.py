"""Observability plane (edl_tpu/obs): registry concurrency under the
lockgraph detector, windowed-vs-cumulative histogram contract, trace
context across BOTH wire seams (incl. 0-d tensors and garbled frames),
Prometheus text-format conformance, recorder overflow/dump, and the
jax-free import assert."""

from __future__ import annotations

import json
import os
import re
import socket
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

from edl_tpu.obs import metrics, recorder, trace


@pytest.fixture
def traced(tmp_path, monkeypatch):
    """Tracing on with a per-test sink dir; ring cleared both ways."""
    monkeypatch.setenv("EDL_TPU_TRACE", str(tmp_path / "trace"))
    trace.reconfigure()
    yield str(tmp_path / "trace")
    monkeypatch.delenv("EDL_TPU_TRACE", raising=False)
    trace.reconfigure()


# -- histogram: the windowed-vs-cumulative contract --------------------------

class TestHistogram:
    def test_snapshot_shape_matches_the_teacher_wire(self):
        h = metrics.Histogram(metrics.LOG_BUCKETS_MS)
        for v in (0.5, 3.0, 70.0, 99999.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap == {1.0: 1, 5.0: 1, 100.0: 1, float("inf"): 1}
        assert h.count == 4 and h.sum == pytest.approx(100072.5)

    def test_windowed_differencing_is_exact(self):
        """The registrar contract pinned as a regression: a teacher
        whose lifetime histogram says 10ms but whose WINDOW says
        1000ms must show the slow window, not the fast past."""
        h = metrics.Histogram(metrics.LOG_BUCKETS_MS)
        for _ in range(1000):
            h.observe(10.0)           # a long fast history
        fast_cum = h.snapshot()
        for _ in range(50):
            h.observe(900.0)          # this interval: slow
        win = metrics.Histogram.window(h.snapshot(), fast_cum)
        assert win == {1000.0: 50}
        # windowed p95 sees the regression; cumulative hides it
        assert metrics.Histogram.quantile(win, 0.95) == 1000.0
        assert metrics.Histogram.quantile(h.snapshot(), 0.5) == 10.0

    def test_window_accepts_wire_string_keys(self):
        win = metrics.Histogram.window({"5.0": 3, "inf": 1},
                                       {"5.0": 1})
        assert win == {5.0: 2, float("inf"): 1}

    def test_quantile_is_conservative_upper_edge(self):
        assert metrics.Histogram.quantile({"5.0": 1, "10.0": 1},
                                          0.5) == 5.0
        assert metrics.Histogram.quantile({}, 0.5) is None

    def test_teacher_buckets_are_the_shared_ladder(self):
        from edl_tpu.distill.teacher_server import (LATENCY_BUCKETS_MS,
                                                    latency_quantile)
        assert tuple(LATENCY_BUCKETS_MS) == metrics.LOG_BUCKETS_MS
        assert latency_quantile({"25.0": 3}, 0.95) == 25.0


# -- registry ----------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .*"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+=\"[^\"]*\""
    r"(,[a-zA-Z0-9_]+=\"[^\"]*\")*\})? [0-9eE.+-]+|\+Inf|-Inf)$")


class TestRegistry:
    def test_prometheus_text_conformance(self):
        reg = metrics.Registry()
        reg.counter("ops", "operations").inc(3)
        reg.gauge("depth").set(1.5)
        h = reg.histogram("lat_ms", (1.0, 10.0))
        for v in (0.5, 5.0, 50.0):
            h.observe(v)
        reg.register_stats("src", lambda: {
            "rows": 7, "flag": True, "skip_me": "strings dropped",
            "hist": {"4": 2}})
        text = reg.render()
        for line in text.strip().split("\n"):
            assert _PROM_LINE.match(line), f"malformed line: {line!r}"
        # histogram buckets are CUMULATIVE with a +Inf terminator
        assert 'edl_lat_ms_bucket{le="1"} 1' in text
        assert 'edl_lat_ms_bucket{le="10"} 2' in text
        assert 'edl_lat_ms_bucket{le="+Inf"} 3' in text
        assert "edl_lat_ms_count 3" in text
        # stats-dict sources render as gauges; bools as 0/1, strings
        # dropped, nested dicts as bucket-labeled samples
        assert 'edl_src_rows{iid="0"} 7' in text
        assert 'edl_src_flag{iid="0"} 1' in text
        assert "skip_me" not in text
        assert 'edl_src_hist{iid="0",bucket="4"} 2' in text

    def test_kind_clash_raises(self):
        reg = metrics.Registry()
        reg.counter("x")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x")

    def test_source_errors_do_not_break_the_scrape(self):
        reg = metrics.Registry()

        def dying():
            raise RuntimeError("subsystem mid-teardown")

        reg.register_stats("dead", dying)
        reg.counter("alive").inc()
        assert "edl_alive 1" in reg.render()

    def test_unregister_drops_the_source(self):
        reg = metrics.Registry()
        handle = reg.register_stats("gone", lambda: {"x": 1})
        reg.unregister(handle)
        assert "gone" not in reg.render()

    def test_scrape_endpoint_round_trip(self):
        reg = metrics.Registry()
        reg.counter("served").inc(9)
        srv = metrics.MetricsServer(reg, port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5).read()
            assert b"edl_served 9" in body
            snap = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/snapshot", timeout=5).read())
            assert snap["metrics"]["served"]["value"] == 9
        finally:
            srv.close()

    def test_store_published_snapshot(self):
        from edl_tpu.coord.store import InMemStore
        reg = metrics.Registry()
        reg.gauge("world").set(4)
        store = InMemStore()
        reg.publish(store, "/obs/metrics/pod0")
        doc = json.loads(store.get("/obs/metrics/pod0").value)
        assert doc["metrics"]["world"]["value"] == 4

    def test_registry_concurrency_under_lockgraph(self):
        """Writers on every metric type + scrapers + register/unregister
        churn, under the lock-order detector: 0 cycles, 0 hazards —
        and collection never runs a source callback while holding the
        registry lock (the callback takes a subsystem lock; a cycle
        would convict immediately)."""
        from edl_tpu.analysis import lockgraph
        graph = lockgraph.install(wrap_all=True)
        try:
            reg = metrics.Registry()
            sys_lock = threading.Lock()

            def stats():
                with sys_lock:   # a subsystem's own stats lock
                    return {"x": 1}

            reg.register_stats("sys", stats)
            c = reg.counter("ops")
            h = reg.histogram("lat", (1.0, 10.0))
            stop = threading.Event()
            errors: list[BaseException] = []

            def writer():
                try:
                    while not stop.is_set():
                        c.inc()
                        h.observe(3.0)
                        with sys_lock:  # subsystem work outside stats
                            pass
                except BaseException as exc:  # noqa: BLE001
                    errors.append(exc)

            def scraper():
                try:
                    for _ in range(50):
                        reg.render()
                        reg.snapshot()
                        handle = reg.register_stats("churn",
                                                    lambda: {"y": 2})
                        reg.unregister(handle)
                except BaseException as exc:  # noqa: BLE001
                    errors.append(exc)

            threads = [threading.Thread(target=writer) for _ in range(2)]
            threads += [threading.Thread(target=scraper) for _ in range(2)]
            for t in threads:
                t.start()
            for t in threads[2:]:
                t.join()
            stop.set()
            for t in threads[:2]:
                t.join()
            assert not errors
            rep = graph.report()
        finally:
            lockgraph.uninstall()
        assert rep["cycles"] == []
        assert rep["hazards"] == []


# -- trace: propagation across both wire seams -------------------------------

class TestTrace:
    def test_disabled_is_a_noop(self, monkeypatch):
        monkeypatch.delenv("EDL_TPU_TRACE", raising=False)
        trace.reconfigure()
        with trace.span("x") as s:
            assert s is None
            assert trace.current() is None
        assert trace.inject() is None
        d = {"op": "put"}
        assert trace.attach(d) is d  # no copy, no key

    def test_coord_wire_propagates_context(self, traced):
        """A request sent under a span arrives server-side carrying the
        context; the server adopts it and the op lands in the SAME
        trace as a child of the caller's span."""
        from edl_tpu.coord.client import StoreClient
        from edl_tpu.coord.server import StoreServer
        with StoreServer(port=0, host="127.0.0.1") as srv:
            client = StoreClient(f"127.0.0.1:{srv.port}")
            try:
                with trace.span("resize.request") as root:
                    client.put("/k", "v")
                    root_ctx = root.context
            finally:
                client.close()
        spans = trace.load_spans(traced)
        store_ops = [s for s in spans if s["name"] == "store.put"]
        assert len(store_ops) == 1
        assert store_ops[0]["tid"] == root_ctx[0]
        assert store_ops[0]["parent"] == root_ctx[1]

    def test_tensor_wire_propagates_context_with_0d_tensors(self, traced):
        """Context rides the tensor-frame meta without disturbing the
        payload contract — including 0-d tensors (the shape-intact
        scalar guarantee r12 pinned)."""
        from edl_tpu.data import tensor_wire
        a, b = socket.socketpair()
        try:
            with trace.span("resize.restore_peers") as sp:
                ctx = sp.context
                tensor_wire.send_tensors(
                    a, {"op": "fetch"},
                    {"scalar": np.array(3, np.int64),
                     "grid": np.arange(6, dtype=np.float32).reshape(2, 3)})
            meta, tensors = tensor_wire.recv_tensors(b)
            assert trace.extract(meta) == ctx
            assert meta == {"op": "fetch"}  # _tc popped, meta intact
            assert tensors["scalar"].shape == ()
            assert int(tensors["scalar"]) == 3
            assert tensors["grid"].shape == (2, 3)
        finally:
            a.close()
            b.close()

    def test_garbled_context_never_breaks_the_consumer(self, traced):
        """A garbled/hostile _tc value (wrong type, wrong arity, junk)
        degrades to 'no context' — the frame still parses."""
        from edl_tpu.data import tensor_wire
        for bad in ("junk", [1, 2], ["a"], ["x" * 100, "y"], None, {}):
            a, b = socket.socketpair()
            try:
                tensor_wire.send_tensors(
                    a, {"op": "fetch", "_tc": bad},
                    {"x": np.zeros(2, np.float32)})
                meta, tensors = tensor_wire.recv_tensors(b)
                assert trace.extract(meta) is None
                assert tensors["x"].shape == (2,)
            finally:
                a.close()
                b.close()

    def test_resize_actuation_is_one_causal_trace(self, traced):
        """request_resize -> /resize -> epoch publication: one trace id
        end to end, with the epoch doc carrying the context a trainer
        adopts (the decision->actuation->restore linkage)."""
        from edl_tpu.collective import migration as mig
        from edl_tpu.collective.job_server import (JobServer, JobState,
                                                   request_resize)
        from edl_tpu.coord.store import InMemStore
        store = InMemStore()
        state = JobState("tracejob", 1, 4, desired=2, store=store)
        server = JobServer(state, port=0).start()
        try:
            request_resize(f"127.0.0.1:{server.port}", 3)
        finally:
            server.stop()
        spans = trace.load_spans(traced)
        tids = {s["tid"] for s in spans}
        assert len(tids) == 1, f"split trace: {spans}"
        names = {s["name"] for s in spans}
        assert {"resize.request", "resize.actuate",
                "resize.publish_epoch"} <= names
        # the epoch doc carries a context from that same trace
        doc = json.loads(store.get(mig.epoch_key("tracejob")).value)
        ctx = trace.parse_context(doc.get("trace"))
        assert ctx is not None and ctx[0] in tids
        assert mig.resize_trace_ctx(store, "tracejob") == ctx
        # and the phase summary sees decision + actuation
        summary = trace.resize_phase_summary(spans)
        assert len(summary) == 1
        assert {"decision", "actuation"} <= set(summary[0]["phases"])

    def test_span_tree_orphans_surface(self, traced):
        with trace.span("parent"):
            with trace.span("child"):
                pass
        spans = trace.load_spans(traced)
        child = next(s for s in spans if s["name"] == "child")
        tree = trace.span_tree([child])  # parent record lost (killed pod)
        assert tree == [(child, 0)]

    def test_chrome_export_and_event(self, traced):
        trace.event("ckpt.write", 0.25, attrs={"version": 3})
        spans = trace.finished("ckpt.write")
        assert len(spans) == 1 and spans[0]["dur"] == 0.25
        chrome = trace.to_chrome(spans)
        ev = chrome["traceEvents"][0]
        assert ev["ph"] == "X" and ev["dur"] == pytest.approx(250000, rel=0.01)
        assert ev["args"]["version"] == 3

    def test_timeline_shim_routes_into_trace(self, traced, monkeypatch):
        from edl_tpu.utils import timeline as tl
        t = tl.timeline("ckpt")
        assert t.enabled
        with t.span("write"):
            pass
        assert trace.finished("ckpt.write")
        # profile off, trace off -> the zero-cost nop again
        monkeypatch.delenv("EDL_TPU_TRACE", raising=False)
        trace.reconfigure()
        assert not tl.timeline("ckpt").enabled


# -- flight recorder ---------------------------------------------------------

class TestRecorder:
    def test_ring_overflow_and_dump(self, tmp_path):
        rec = recorder.FlightRecorder(capacity=4)
        for i in range(10):
            rec.record("resize", to=i)
        assert [e["to"] for e in rec.events("resize")] == [6, 7, 8, 9]
        assert rec.dropped == 6
        path = rec.dump(str(tmp_path / "flight.json"), reason="test")
        doc = json.load(open(path))
        assert doc["dropped"] == 6 and len(doc["events"]) == 4
        assert doc["reason"] == "test"

    def test_capacity_zero_disables(self):
        rec = recorder.FlightRecorder(capacity=0)
        rec.record("resize", to=1)
        assert rec.events() == []

    def test_job_resize_lands_in_the_global_ring(self):
        from edl_tpu.collective.job_server import JobState
        recorder.recorder().clear()
        state = JobState("rj", 1, 4, desired=2)
        state.resize(3)
        events = recorder.recorder().events("resize")
        assert events and events[-1]["to"] == 3 \
            and events[-1]["plane"] == "job"

    def test_auditor_third_witness(self):
        """I2's recorder witness: agreement passes, a ring that saw a
        resize the journal/log pair did not breaches, an overflowed
        ring voids the comparison instead of lying."""
        from edl_tpu.chaos.audit import InvariantAuditor

        def auditor(events, dropped=0):
            return InvariantAuditor(
                injections=[], worker_reports={}, probe={},
                scaler_journal=[{"action": "resize", "applied": 3}],
                job_resize_log=[{"to": 3, "source": "resize"}],
                pool_journal=[], pool_resize_log=[], drain_log=[],
                drain_deadline_s=5.0,
                recorder={"events": events, "dropped": dropped})

        good = [{"kind": "resize", "plane": "job", "source": "resize",
                 "to": 3}]
        rep = auditor(good).audit()
        assert not [b for b in rep.breaches if "recorder" in b]
        assert rep.stats["recorder_witness"] == "ok"

        rep = auditor(good + [{"kind": "resize", "plane": "job",
                               "source": "resize", "to": 9}]).audit()
        assert any("flight recorder" in b for b in rep.breaches)

        rep = auditor([], dropped=5).audit()
        assert rep.stats["recorder_witness"] == "overflowed"
        assert not [b for b in rep.breaches if "recorder" in b]


# -- the stdlib-only contract ------------------------------------------------

class TestLayering:
    def test_obs_imports_jax_and_numpy_free(self):
        """The obs plane must be importable on a scheduler node / bare
        CI runner: importing it (fresh interpreter) pulls neither jax
        nor numpy."""
        code = ("import sys; import edl_tpu.obs; "
                "assert 'jax' not in sys.modules, 'jax leaked'; "
                "assert 'numpy' not in sys.modules, 'numpy leaked'; "
                "print('clean')")
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))))
        assert out.returncode == 0, out.stderr
        assert "clean" in out.stdout

    def test_selftest_gate_passes(self):
        from edl_tpu.obs.__main__ import selftest
        assert selftest(verbose=False) == 0
