"""JPEG decode/augment input plane (data/image.py + DataLoader pool).

Covers the reference's cv2 reader capability (reader_cv2.py file-list +
xmap decode pool; img_tool.py transform set) with the determinism the
reference lacks: identical streams across pool widths and restarts.
"""

import numpy as np
import pytest

from edl_tpu.data.image import (JpegFileListSource, center_crop, decode_jpeg,
                                encode_jpeg, eval_image_transform,
                                make_synthetic_jpeg_dataset,
                                random_resized_crop, resize_short,
                                train_image_transform)
from edl_tpu.data.pipeline import DataLoader
from edl_tpu.utils.exceptions import EdlDataError


@pytest.fixture(scope="module")
def jpeg_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("jpegs")
    list_file = make_synthetic_jpeg_dataset(str(d), 24, classes=5,
                                            hw=(80, 100), seed=3)
    return str(d), list_file


class TestCodecs:
    def test_roundtrip_shape_dtype(self):
        rng = np.random.default_rng(0)
        img = rng.integers(0, 256, (60, 40, 3), dtype=np.uint8)
        out = decode_jpeg(encode_jpeg(img, quality=95))
        assert out.shape == (60, 40, 3) and out.dtype == np.uint8

    def test_decode_is_rgb(self):
        # a pure-red image must come back red-dominant in channel 0
        img = np.zeros((32, 32, 3), np.uint8)
        img[..., 0] = 255  # RGB red
        out = decode_jpeg(encode_jpeg(img, quality=95))
        assert out[..., 0].mean() > 200 > out[..., 2].mean()

    def test_decode_garbage_raises(self):
        with pytest.raises(EdlDataError):
            decode_jpeg(b"not a jpeg")


class TestTransforms:
    def test_random_resized_crop_shape_and_determinism(self):
        rng = np.random.default_rng(0)
        img = rng.integers(0, 256, (90, 123, 3), dtype=np.uint8)
        a = random_resized_crop(img, np.random.default_rng(7), 32)
        b = random_resized_crop(img, np.random.default_rng(7), 32)
        c = random_resized_crop(img, np.random.default_rng(8), 32)
        assert a.shape == (32, 32, 3)
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)  # different seed, different crop

    def test_random_resized_crop_tiny_image(self):
        img = np.zeros((3, 2, 3), np.uint8)  # smaller than the crop
        out = random_resized_crop(img, np.random.default_rng(0), 16)
        assert out.shape == (16, 16, 3)

    def test_resize_short_and_center_crop(self):
        img = np.zeros((100, 200, 3), np.uint8)
        r = resize_short(img, 50)
        assert min(r.shape[:2]) == 50 and r.shape[1] == 100
        c = center_crop(r, 50)
        assert c.shape == (50, 50, 3)

    def test_random_rotate_deterministic_and_shaped(self):
        from edl_tpu.data.image import random_rotate
        rng = np.random.default_rng(0)
        img = rng.integers(0, 256, (50, 70, 3), dtype=np.uint8)
        a = random_rotate(img, np.random.default_rng(3))
        b = random_rotate(img, np.random.default_rng(3))
        c = random_rotate(img, np.random.default_rng(4))
        assert a.shape == img.shape
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_train_transform_with_rotate(self):
        rng = np.random.default_rng(0)
        img = rng.integers(0, 256, (60, 80, 3), dtype=np.uint8)
        s = {"jpeg": encode_jpeg(img), "label": np.int32(1)}
        t = train_image_transform(32, rotate=True)
        out = t(dict(s), np.random.default_rng(5))
        assert out["image"].shape == (32, 32, 3)
        # rotate changes the stream vs the rotate-free transform
        out2 = train_image_transform(32)(dict(s), np.random.default_rng(5))
        assert not np.array_equal(out["image"], out2["image"])

    def test_eval_transform_is_deterministic(self):
        rng = np.random.default_rng(0)
        img = rng.integers(0, 256, (70, 90, 3), dtype=np.uint8)
        s = {"jpeg": encode_jpeg(img), "label": np.int32(2)}
        t = eval_image_transform(size=32, short=40)
        a = t(dict(s), np.random.default_rng(0))
        b = t(dict(s), np.random.default_rng(99))
        np.testing.assert_array_equal(a["image"], b["image"])
        assert a["label"] == 2 and "jpeg" not in a


class TestFileListSource:
    def test_len_and_samples(self, jpeg_dir):
        root, list_file = jpeg_dir
        src = JpegFileListSource(list_file, root=root)
        assert len(src) == 24
        out = src.samples(np.array([0, 5, 23]))
        assert len(out) == 3
        for s in out:
            assert isinstance(s["jpeg"], bytes) and s["jpeg"][:2] == b"\xff\xd8"
            assert 0 <= int(s["label"]) < 5

    def test_list_parsing_rejects_empty(self, tmp_path):
        p = tmp_path / "empty.txt"
        p.write_text("\n")
        with pytest.raises(EdlDataError):
            JpegFileListSource(str(p))

    def test_entries_or_list_exclusive(self):
        with pytest.raises(EdlDataError):
            JpegFileListSource(None, entries=None)


class TestLoaderIntegration:
    def _loader(self, jpeg_dir, threads, seed=0):
        root, list_file = jpeg_dir
        src = JpegFileListSource(list_file, root=root)
        return DataLoader(src, 8, seed=seed,
                          sample_transforms=(train_image_transform(32),),
                          decode_threads=threads)

    def test_batch_shape_dtype(self, jpeg_dir):
        loader = self._loader(jpeg_dir, threads=2)
        batch = next(iter(loader.epoch(0)))
        assert batch["image"].shape == (8, 32, 32, 3)
        assert batch["image"].dtype == np.uint8
        assert batch["label"].shape == (8,)
        loader.close()

    def test_pool_width_does_not_change_stream(self, jpeg_dir):
        """Decode pool scheduling must be invisible: 0, 1 and 4 threads
        produce bit-identical epochs (the reference's order=False xmap
        cannot guarantee this — our elastic replay depends on it)."""
        batches = {}
        for threads in (0, 1, 4):
            loader = self._loader(jpeg_dir, threads=threads)
            batches[threads] = list(loader.epoch(2))
            loader.close()
        for threads in (1, 4):
            assert len(batches[threads]) == len(batches[0])
            for a, b in zip(batches[0], batches[threads]):
                np.testing.assert_array_equal(a["image"], b["image"])
                np.testing.assert_array_equal(a["label"], b["label"])

    def test_restart_replays_epoch(self, jpeg_dir):
        l1 = self._loader(jpeg_dir, threads=2)
        l2 = self._loader(jpeg_dir, threads=2)
        for a, b in zip(l1.epoch(1), l2.epoch(1)):
            np.testing.assert_array_equal(a["image"], b["image"])
        l1.close(), l2.close()

    def test_epochs_differ(self, jpeg_dir):
        loader = self._loader(jpeg_dir, threads=2)
        a = next(iter(loader.epoch(0)))
        b = next(iter(loader.epoch(1)))
        assert not np.array_equal(a["image"], b["image"])
        loader.close()

    def test_sample_transforms_need_samples_api(self):
        from edl_tpu.data.pipeline import ArraySource
        src = ArraySource({"x": np.zeros((4, 2), np.float32)})
        with pytest.raises(EdlDataError):
            DataLoader(src, 2, sample_transforms=(lambda s, r: s,))


class TestMidEpochResumeOverDecodePool:
    def test_interrupted_run_matches_straight(self, jpeg_dir, tmp_path):
        """Mid-epoch stop-resume over the POOLED jpeg plane reproduces
        the uninterrupted run's parameters exactly: the skip path
        re-generates (and re-seeds) the same per-sample augmentation
        stream, so a crash between step checkpoints loses nothing."""
        import jax.numpy as jnp
        import optax

        from edl_tpu.parallel.mesh import MeshSpec, make_mesh
        from edl_tpu.train.loop import LoopConfig, TrainLoop
        from edl_tpu.train.state import TrainState
        from edl_tpu.train.step import make_train_step

        root, list_file = jpeg_dir
        mesh = make_mesh(MeshSpec({"dp": -1}))  # all virtual devices

        def build():
            import jax

            def loss_fn(state, params, batch):
                img = batch["image"].astype(jnp.float32) / 255.0
                pred = jnp.mean(img, axis=(1, 2)) @ params["w"]
                tgt = jax.nn.one_hot(batch["label"], 5)
                return jnp.mean((pred - tgt) ** 2), {}

            params = {"w": jnp.zeros((3, 5), jnp.float32)}
            state = TrainState.create(apply_fn=None, params=params,
                                      tx=optax.sgd(0.5))
            return state, make_train_step(loss_fn, donate=False)

        src = JpegFileListSource(list_file, root=root)
        data = DataLoader(src, 8, seed=3,
                          sample_transforms=(train_image_transform(16),),
                          decode_threads=2)  # __call__(epoch) = data_fn

        # straight: 2 epochs, no interruption
        state, step = build()
        straight = TrainLoop(step, state, mesh=mesh,
                             config=LoopConfig(num_epochs=2))
        straight.run(data)

        # interrupted: crash mid-epoch-0 after the step-2 checkpoint
        class Crash(Exception):
            pass

        def crashing(epoch):
            for i, b in enumerate(data(epoch)):
                if i == 2:
                    raise Crash()
                yield b

        state2, step2 = build()
        run1 = TrainLoop(step2, state2, mesh=mesh,
                         config=LoopConfig(num_epochs=2,
                                           ckpt_dir=str(tmp_path / "ck"),
                                           ckpt_every_steps=2))
        with pytest.raises(Crash):
            run1.run(crashing)
        state3, step3 = build()
        run2 = TrainLoop(step3, state3, mesh=mesh,
                         config=LoopConfig(num_epochs=2,
                                           ckpt_dir=str(tmp_path / "ck"),
                                           ckpt_every_steps=2))
        run2.run(data)
        data.close()

        np.testing.assert_allclose(
            np.asarray(run2.state.params["w"]),
            np.asarray(straight.state.params["w"]), rtol=1e-6)


class TestFlagshipJpegMode:
    @pytest.mark.slow
    def test_imagenet_train_jpeg_end_to_end(self, tmp_path):
        """The flagship trainer over the JPEG plane: synthetic JPEGs +
        train.txt, pooled decode/augment, on-device normalization."""
        from edl_tpu.examples.imagenet_train import main

        data = str(tmp_path / "jpegs")
        rc = main(["--data-dir", data, "--data-format", "jpeg",
                   "--make-synthetic", "96", "--model", "ResNetTiny",
                   "--num-classes", "4", "--image-size", "24",
                   "--epochs", "2", "--batch-size", "32",
                   "--warmup-epochs", "0", "--lr-strategy", "cosine",
                   "--lr", "0.02", "--label-smoothing", "0",
                   "--decode-threads", "2",
                   "--ckpt-dir", str(tmp_path / "ckpt"),
                   "--benchmark-log", str(tmp_path / "blog")])
        assert rc == 0
        import json
        blog = json.load(open(tmp_path / "blog" / "log_0.json"))
        assert len(blog["epochs"]) == 2
        assert blog["epochs"][-1]["examples_per_sec"] > 0

    @pytest.mark.slow
    def test_jpeg_distill_with_normalized_teacher(self, tmp_path):
        """Distill over the JPEG plane: the student ships RAW uint8
        feeds, the teacher normalizes server-side (--input-normalize
        contract — a mismatched teacher would emit out-of-distribution
        logits). Asserts the teacher really saw uint8 and the run
        completes."""
        import json

        import numpy as np

        from edl_tpu.distill.teacher_server import (TeacherServer,
                                                    _build_model_predict)
        from edl_tpu.examples.imagenet_train import main

        predict, _ = _build_model_predict(
            "ResNetTiny", 4, "", "image", "logits", (24, 24, 3),
            "float32", input_normalize="imagenet")
        seen = {}

        def spy(feeds):
            seen["dtype"] = feeds["image"].dtype
            return predict(feeds)

        data = str(tmp_path / "jpegs")
        with TeacherServer(spy, host="127.0.0.1") as srv:
            rc = main(["--data-dir", data, "--data-format", "jpeg",
                       "--make-synthetic", "64", "--model", "ResNetTiny",
                       "--num-classes", "4", "--image-size", "24",
                       "--epochs", "1", "--batch-size", "32",
                       "--warmup-epochs", "0", "--label-smoothing", "0",
                       "--lr", "0.02", "--decode-threads", "2",
                       "--teachers", f"127.0.0.1:{srv.port}",
                       "--benchmark-log", str(tmp_path / "blog")])
        assert rc == 0
        assert seen["dtype"] == np.uint8  # raw wire feeds, as designed
        blog = json.load(open(tmp_path / "blog" / "log_0.json"))
        assert len(blog["epochs"]) == 1
