"""edl-lint + lockgraph: fixture snippets per checker (caught + clean),
suppression grammar, lockgraph seeded hazards, and the dogfood pins —
the real repo lints clean and the analysis package imports jax/numpy
free."""

import json
import os
import queue
import subprocess
import sys
import textwrap
import threading

import pytest

from edl_tpu.analysis import lockgraph
from edl_tpu.analysis.core import (Finding, LintResult, Project,
                                   load_toml_lite, run_lint)
from edl_tpu.analysis.checks import CHECKS

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_project(tmp_path, files: dict[str, str], config: dict) -> Project:
    for rel, text in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(text))
    config = dict(config)
    config.setdefault("lint", {"paths": sorted(
        {rel.split("/")[0] for rel in files if rel.endswith(".py")})})
    return Project(str(tmp_path), config)


def findings_of(project: Project, check: str) -> list[Finding]:
    return sorted(CHECKS[check](project), key=lambda f: (f.path, f.line))


# -- toml-lite ---------------------------------------------------------------


class TestTomlLite:
    def test_parses_the_layers_subset(self):
        cfg = load_toml_lite(
            '# comment\n[layers.coord]\npackages = ["a", "b"]\n'
            'n = 3\nf = 1.5\nflag = true\nname = "x"\n')
        assert cfg["layers"]["coord"]["packages"] == ["a", "b"]
        assert cfg["layers"]["coord"]["n"] == 3
        assert cfg["layers"]["coord"]["flag"] is True

    def test_rejects_what_it_cannot_parse(self):
        with pytest.raises(ValueError):
            load_toml_lite("key = [unquoted")
        with pytest.raises(ValueError):
            load_toml_lite("just a line\n")

    def test_the_real_layers_toml_loads(self):
        path = os.path.join(REPO_ROOT, "edl_tpu/analysis/layers.toml")
        with open(path) as f:
            cfg = load_toml_lite(f.read())
        assert "coord" in cfg["layers"]
        assert "edl_tpu/scaler/simulator.py" in cfg["determinism"]["files"]


# -- layering ----------------------------------------------------------------

_LAYER_CFG = {"layers": {"pure": {"packages": ["pkg/pure"],
                                  "forbidden": ["numpy"]}}}


class TestLayering:
    def test_direct_violation_caught_with_chain(self, tmp_path):
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/pure/__init__.py": "",
            "pkg/pure/mod.py": "import numpy as np\n",
        }, _LAYER_CFG)
        found = findings_of(project, "layering")
        assert len(found) == 1
        assert "must not import 'numpy'" in found[0].message
        assert found[0].path == "pkg/pure/mod.py" and found[0].line == 1

    def test_transitive_violation_names_the_chain(self, tmp_path):
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/pure/__init__.py": "",
            "pkg/pure/mod.py": "from pkg.helper import x\n",
            "pkg/helper.py": "import numpy\nx = 1\n",
        }, _LAYER_CFG)
        found = findings_of(project, "layering")
        assert len(found) == 1
        assert "pkg/helper.py" in found[0].message  # the chain hop
        # anchored at the ROOT file's import line (where the fix goes)
        assert found[0].path == "pkg/pure/mod.py" and found[0].line == 1

    def test_function_scoped_and_type_checking_imports_are_exempt(
            self, tmp_path):
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/pure/__init__.py": "",
            "pkg/pure/mod.py": """\
                from typing import TYPE_CHECKING
                if TYPE_CHECKING:
                    import numpy
                def f():
                    import numpy as np
                    return np
            """,
        }, _LAYER_CFG)
        assert findings_of(project, "layering") == []


# -- env-registry ------------------------------------------------------------

_ENV_CFG = {"env": {"config_module": "pkg/config.py", "doc": "doc.md",
                    "prefix": "EDL_TPU_"}}

_CONFIG_WITH = """\
    ENV_VARS = {"EDL_TPU_GOOD": "a documented knob"}
    import os
    def env_str(name, default=None):
        return os.environ.get(name, default)
"""


class TestEnvRegistry:
    def test_direct_read_outside_config_flagged(self, tmp_path):
        (tmp_path / "doc.md").write_text("| `EDL_TPU_GOOD` | ok |\n")
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/config.py": _CONFIG_WITH,
            "pkg/user.py": 'import os\nv = os.environ["EDL_TPU_GOOD"]\n',
        }, _ENV_CFG)
        msgs = [f.message for f in findings_of(project, "env-registry")]
        assert any("direct environment read" in m for m in msgs)

    def test_undeclared_and_undocumented_and_dead_row(self, tmp_path):
        (tmp_path / "doc.md").write_text(
            "| `EDL_TPU_GOOD` | ok |\n| `EDL_TPU_GONE` | dead row |\n")
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/config.py": _CONFIG_WITH.replace(
                '{"EDL_TPU_GOOD": "a documented knob"}',
                '{"EDL_TPU_GOOD": "ok", "EDL_TPU_UNDOC": "no doc row"}'),
            "pkg/user.py": """\
                from pkg.config import env_str
                a = env_str("EDL_TPU_GOOD")
                b = env_str("EDL_TPU_UNDOC")
                c = env_str("EDL_TPU_MYSTERY")
            """,
        }, _ENV_CFG)
        msgs = [f.message for f in findings_of(project, "env-registry")]
        assert any("'EDL_TPU_MYSTERY' is not declared" in m for m in msgs)
        assert any("'EDL_TPU_UNDOC' has no row" in m for m in msgs)
        assert any("'EDL_TPU_GONE'" in m and "dead doc row" in m
                   for m in msgs)

    def test_dead_declaration_flagged(self, tmp_path):
        (tmp_path / "doc.md").write_text(
            "| `EDL_TPU_GOOD` | ok |\n| `EDL_TPU_UNREAD` | doc |\n")
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/config.py": _CONFIG_WITH.replace(
                '{"EDL_TPU_GOOD": "a documented knob"}',
                '{"EDL_TPU_GOOD": "ok", "EDL_TPU_UNREAD": "nobody reads"}'),
            "pkg/user.py": 'from pkg.config import env_str\n'
                           'a = env_str("EDL_TPU_GOOD")\n',
        }, _ENV_CFG)
        msgs = [f.message for f in findings_of(project, "env-registry")]
        assert any("'EDL_TPU_UNREAD' is never read" in m for m in msgs)

    def test_clean_pass(self, tmp_path):
        (tmp_path / "doc.md").write_text("| `EDL_TPU_GOOD` | ok |\n")
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/config.py": _CONFIG_WITH,
            "pkg/user.py": 'from pkg.config import env_str\n'
                           'a = env_str("EDL_TPU_GOOD")\n',
        }, _ENV_CFG)
        assert findings_of(project, "env-registry") == []


# -- guarded-by --------------------------------------------------------------

_GUARDED_BAD = """\
    import threading
    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0   # guarded-by: _lock
        def bump(self):
            self._count += 1
"""

_GUARDED_GOOD = """\
    import threading
    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0   # guarded-by: _lock
            self._items = []  # guarded-by: _lock
        def bump(self):
            with self._lock:
                self._count += 1
                self._items.append(1)
        def _bump_locked(self):  # holds-lock: _lock
            self._count += 1
"""


class TestGuardedBy:
    def test_unlocked_mutation_caught(self, tmp_path):
        project = make_project(tmp_path, {"pkg/__init__.py": "",
                                          "pkg/m.py": _GUARDED_BAD}, {})
        found = findings_of(project, "guarded-by")
        assert len(found) == 1
        assert "self._count" in found[0].message
        assert found[0].line == 7

    def test_locked_and_holds_lock_clean(self, tmp_path):
        project = make_project(tmp_path, {"pkg/__init__.py": "",
                                          "pkg/m.py": _GUARDED_GOOD}, {})
        assert findings_of(project, "guarded-by") == []

    def test_closure_inside_with_is_not_blessed(self, tmp_path):
        # `with lock:` around a nested def does NOT protect the closure
        # body at runtime — the thread runs it after the lock is dropped
        project = make_project(tmp_path, {"pkg/__init__.py": "", "pkg/m.py": """\
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0   # guarded-by: _lock
                def go(self):
                    with self._lock:
                        def work():
                            self._n += 1
                        return work
        """}, {})
        found = findings_of(project, "guarded-by")
        assert len(found) == 1 and "self._n" in found[0].message

    def test_mutating_method_call_caught(self, tmp_path):
        project = make_project(tmp_path, {"pkg/__init__.py": "", "pkg/m.py": """\
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # guarded-by: _lock
                def add(self, x):
                    self._items.append(x)
        """}, {})
        found = findings_of(project, "guarded-by")
        assert len(found) == 1 and ".append() call" in found[0].message


# -- resource-lifecycle ------------------------------------------------------


class TestLifecycle:
    def test_keeping_class_without_teardown_caught(self, tmp_path):
        project = make_project(tmp_path, {"pkg/__init__.py": "", "pkg/m.py": """\
            import threading
            class Keeper:
                def __init__(self):
                    self._t = threading.Thread(target=lambda: None)
        """}, {})
        found = findings_of(project, "resource-lifecycle")
        assert len(found) == 1 and "'Keeper'" in found[0].message

    def test_method_local_joined_thread_is_not_ownership(self, tmp_path):
        project = make_project(tmp_path, {"pkg/__init__.py": "", "pkg/m.py": """\
            import threading
            class Scoped:
                def work(self):
                    t = threading.Thread(target=lambda: None)
                    t.start()
                    t.join()
        """}, {})
        assert findings_of(project, "resource-lifecycle") == []

    def test_leaky_instantiation_site_caught_and_fixes_pass(self, tmp_path):
        project = make_project(tmp_path, {"pkg/__init__.py": "", "pkg/m.py": """\
            import threading
            class Res:
                def __init__(self):
                    self._t = threading.Thread(target=lambda: None)
                def close(self):
                    pass
            def leak():
                r = Res()          # no finally, no owner: finding
                return 1
            def ok_with():
                with Res() as r:
                    return r
            def ok_finally():
                r = Res()
                try:
                    return 1
                finally:
                    r.close()
            def ok_factory():
                return Res()
            # lifecycle: long-lived(process singleton for the test)
            GLOBAL = Res()
        """}, {})
        found = findings_of(project, "resource-lifecycle")
        assert len(found) == 1
        assert "'Res' instantiated without bounded ownership" \
            in found[0].message

    def test_ownership_handoff_to_closeable_owner_passes(self, tmp_path):
        project = make_project(tmp_path, {"pkg/__init__.py": "", "pkg/m.py": """\
            import threading
            class Res:
                def __init__(self):
                    self._t = threading.Thread(target=lambda: None)
                def close(self):
                    pass
            class Owner:
                def __init__(self, res):
                    self._res = res
                def close(self):
                    self._res.close()
            def make():
                r = Res()
                return Owner(r)
        """}, {})
        assert findings_of(project, "resource-lifecycle") == []


# -- sim-determinism ---------------------------------------------------------

_DET_CFG = {"determinism": {"files": ["pkg/sim.py"]}}


class TestDeterminism:
    def test_wall_clock_and_global_rng_caught_transitively(self, tmp_path):
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/sim.py": "from pkg.helper import now\n",
            "pkg/helper.py": """\
                import time, random
                def now():
                    return time.time() + random.random()
            """,
        }, _DET_CFG)
        msgs = [f.message for f in findings_of(project, "sim-determinism")]
        assert any("time.time()" in m for m in msgs)
        assert any("random.random()" in m for m in msgs)

    def test_seeded_rngs_and_virtual_clock_clean(self, tmp_path):
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/sim.py": """\
                import random
                rng = random.Random(1234)
                def tick(clock):
                    return clock() + rng.random()
            """,
        }, _DET_CFG)
        assert findings_of(project, "sim-determinism") == []

    def test_argless_random_Random_caught(self, tmp_path):
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/sim.py": "import random\nrng = random.Random()\n",
        }, _DET_CFG)
        found = findings_of(project, "sim-determinism")
        assert len(found) == 1 and "argless random.Random()" \
            in found[0].message


# -- suppressions ------------------------------------------------------------


class TestSuppressions:
    def _cfg(self):
        return dict(_LAYER_CFG)

    def test_suppression_with_reason_honored(self, tmp_path):
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/pure/__init__.py": "",
            "pkg/pure/mod.py":
                "import numpy  # edl-lint: disable=layering(numpy needed"
                " for the fixture)\n",
        }, self._cfg())
        result = _run(project)
        assert result.ok
        assert len(result.suppressed) == 1
        assert result.suppressed[0][1].reason \
            == "numpy needed for the fixture"

    def test_reason_is_mandatory(self, tmp_path):
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/pure/__init__.py": "",
            "pkg/pure/mod.py":
                "import numpy  # edl-lint: disable=layering\n",
        }, self._cfg())
        assert any(f.check == "suppression" for f in project.errors)

    def test_unused_suppression_is_a_finding(self, tmp_path):
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/pure/__init__.py": "",
            "pkg/pure/mod.py":
                "x = 1  # edl-lint: disable=layering(stale reason)\n",
        }, self._cfg())
        result = _run(project)
        assert not result.ok
        assert result.findings[0].check == "unused-suppression"

    def test_wrong_check_name_does_not_suppress(self, tmp_path):
        project = make_project(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/pure/__init__.py": "",
            "pkg/pure/mod.py":
                "import numpy  # edl-lint: disable=guarded-by(wrong)\n",
        }, self._cfg())
        result = _run(project)
        checks = {f.check for f in result.findings}
        assert "layering" in checks and "unused-suppression" in checks


def _run(project: Project) -> LintResult:
    """run_lint against an in-memory Project (mirrors core.run_lint's
    suppression accounting without re-loading from disk)."""
    result = LintResult()
    result.findings.extend(project.errors)
    raw = []
    for name in sorted(CHECKS):
        raw.extend(CHECKS[name](project))
    for sf in project.files.values():
        for sups in sf.suppressions.values():
            result.suppressions.extend(sups)
    used = set()
    for f in raw:
        sf = project.files.get(f.path)
        match = None
        if sf is not None:
            for s in sf.suppressions.get(f.line, []):
                if s.check == f.check:
                    match = s
                    break
        if match is not None:
            result.suppressed.append((f, match))
            used.add((match.path, match.line, match.check))
        else:
            result.findings.append(f)
    for s in result.suppressions:
        if (s.path, s.line, s.check) not in used:
            result.findings.append(Finding(
                "unused-suppression", s.path, s.line, "unused"))
    return result


# -- lockgraph ---------------------------------------------------------------


class TestLockGraph:
    def test_selftest_catches_the_seeded_hazards(self):
        assert lockgraph.selftest(verbose=False) == 0

    def test_abba_cycle_detected_via_api(self):
        graph = lockgraph.install(wrap_all=True)
        try:
            a = threading.Lock()
            b = threading.Lock()

            def order(first, second):
                with first:
                    with second:
                        pass
            for args in ((a, b), (b, a)):
                t = threading.Thread(target=order, args=args)
                t.start()
                t.join()
            rep = graph.report()
            assert rep["cycles"], "ABBA ordering must form a cycle"
            assert rep["cycle_edges"][0]["stack"]  # stacks captured
        finally:
            lockgraph.uninstall()

    def test_same_site_instances_alias_to_a_self_edge_warning(self):
        # lock identity is the CREATION SITE (lockdep-style): two
        # instances born on one line share a node, so nesting them
        # reports a self-edge warning, not a cycle — the documented
        # granularity limitation (doc/design_analysis.md)
        graph = lockgraph.install(wrap_all=True)
        try:
            a, b = threading.Lock(), threading.Lock()  # ONE line: one site
            with a:
                with b:
                    pass
            rep = graph.report()
            assert not rep["cycles"]
            assert rep["self_edge_warnings"]
        finally:
            lockgraph.uninstall()

    def test_consistent_order_is_clean(self):
        graph = lockgraph.install(wrap_all=True)
        try:
            a, b = threading.Lock(), threading.Lock()
            for _ in range(3):
                def nested():
                    with a:
                        with b:
                            pass
                t = threading.Thread(target=nested)
                t.start()
                t.join()
            assert graph.report()["ok"]
        finally:
            lockgraph.uninstall()

    def test_condition_wait_releases_in_held_set(self):
        # a Condition wait must not leave the lock falsely 'held' — the
        # waiter parks with the lock RELEASED, so a second thread taking
        # (lock -> other) while the first is parked must not see
        # phantom edges from the parked thread
        graph = lockgraph.install(wrap_all=True)
        try:
            cond = threading.Condition()
            woke = threading.Event()

            def waiter():
                with cond:
                    woke.set()
                    cond.wait(timeout=5.0)

            t = threading.Thread(target=waiter)
            t.start()
            woke.wait(2.0)
            # while the waiter is parked, its held-set must be empty
            held_ids = [e for entries in graph._held.values()
                        for e in entries]
            deadline = 50
            while held_ids and deadline:
                import time as _t
                _t.sleep(0.01)
                deadline -= 1
                held_ids = [e for entries in graph._held.values()
                            for e in entries]
            assert not held_ids, "parked waiter still marked holding"
            with cond:
                cond.notify_all()
            t.join(5.0)
        finally:
            lockgraph.uninstall()

    def test_put_to_self_hazard(self):
        graph = lockgraph.install(wrap_all=True)
        try:
            q = queue.Queue(maxsize=8)
            q.put(1)
            q.get()
            q.put(2)   # same thread consumes AND block-puts: hazard
            assert any(h["kind"] == "put-to-self"
                       for h in graph.report()["hazards"])
        finally:
            lockgraph.uninstall()


# -- dogfood pins ------------------------------------------------------------


class TestDogfood:
    def test_the_repo_lints_clean(self):
        result = run_lint(REPO_ROOT)
        assert result.ok, "\n".join(f.render() for f in result.findings)
        # every surviving suppression carries its reason by construction
        assert all(s.reason for s in result.suppressions)

    def test_analysis_package_imports_jax_and_numpy_free(self):
        code = (
            "import sys\n"
            "import edl_tpu.analysis\n"
            "import edl_tpu.analysis.lockgraph\n"
            "import edl_tpu.analysis.core\n"
            "import edl_tpu.analysis.checks\n"
            "import edl_tpu.analysis.__main__\n"
            "banned = [m for m in ('jax', 'numpy', 'flax', 'optax')"
            " if m in sys.modules]\n"
            "assert not banned, f'analysis pulled in {banned}'\n"
            "print('PURE')\n")
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             cwd=REPO_ROOT, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "PURE" in out.stdout

    def test_lint_cli_json_report(self, tmp_path):
        out_json = tmp_path / "lint.json"
        proc = subprocess.run(
            [sys.executable, "-m", "edl_tpu.analysis", "lint",
             "--root", REPO_ROOT, "--json", str(out_json)],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(out_json.read_text())
        assert doc["ok"] is True
        assert set(doc["checks"]) == set(CHECKS)
