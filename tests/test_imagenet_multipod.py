"""Flagship imagenet trainer under a real multi-process world.

Also the deepest integration in the suite: the flagship trainer under
the REAL elastic launcher — store server, rank claims, one-world
formation, pod kill, stop-resume — in
`test_flagship_under_launcher_survives_pod_kill`.

multipod_demo proves the one-world mechanics on a linear model; this
proves the FLAGSHIP trainer (file-backed FileSource input, BN stats,
label pipeline, benchmark log) trains correctly when two launcher-style
processes form one jax.distributed world. Because each rank feeds
`perm[rank::world]` of the same seed-per-pass global order, every global
step consumes the same sample SET as a single-process run with the
global batch — so accuracy must match up to reduction order.
"""

import pytest

pytestmark = pytest.mark.slow  # the flagship trainer under the real elastic launcher

import json
import os
import subprocess
import sys
import time

import numpy as np

from edl_tpu.utils import net

TRAINER = "edl_tpu.examples.imagenet_train"


def cpu_env(extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({"JAX_PLATFORMS": "cpu", "JAX_NUM_CPU_DEVICES": "1"})
    env.update(extra or {})
    return env


def run_world(tmp_path, tag, world, data_dir, epochs=4, timeout=300,
              ckpt=None, schedule_epochs=0):
    port = net.free_port()
    blog_dir = tmp_path / f"blog-{tag}"
    extra = ["--ckpt-dir", str(ckpt)] if ckpt else []
    if schedule_epochs:
        extra += ["--schedule-epochs", str(schedule_epochs)]
    procs, logs = [], []
    for rank in range(world):
        env = cpu_env({
            "EDL_TPU_RANK": str(rank),
            "EDL_TPU_WORLD_SIZE": str(world),
            "EDL_TPU_COORDINATOR": f"127.0.0.1:{port}",
        })
        logs.append(open(tmp_path / f"{tag}.r{rank}.log", "wb"))
        procs.append(subprocess.Popen(
            [sys.executable, "-m", TRAINER, "--data-dir", str(data_dir),
             "--model", "ResNetTiny", "--num-classes", "8",
             "--image-size", "16", "--epochs", str(epochs),
             "--batch-size", "32", "--warmup-epochs", "1",
             "--lr-strategy", "cosine", "--lr", "0.05", "--no-augment",
             "--label-smoothing", "0",
             "--benchmark-log", str(blog_dir)] + extra,
            env=env, stdout=logs[-1], stderr=subprocess.STDOUT))
    deadline = time.time() + timeout
    try:
        for rank, p in enumerate(procs):
            rc = p.wait(timeout=max(1.0, deadline - time.time()))
            assert rc == 0, (tmp_path / f"{tag}.r{rank}.log").read_text()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()
    with open(blog_dir / "log_0.json") as f:
        return json.load(f)


def make_data(tmp_path):
    """Generate shards once (single process, deterministic)."""
    data_dir = tmp_path / "data"
    rc = subprocess.run(
        [sys.executable, "-m", TRAINER, "--data-dir", str(data_dir),
         "--make-synthetic", "2", "--rows-per-file", "128",
         "--model", "ResNetTiny", "--num-classes", "8",
         "--image-size", "16", "--epochs", "0", "--batch-size", "32"],
        env=cpu_env(), capture_output=True)
    assert rc.returncode == 0, rc.stdout.decode() + rc.stderr.decode()
    return data_dir


def test_flagship_two_process_world_matches_single(tmp_path):
    data_dir = make_data(tmp_path)
    solo = run_world(tmp_path, "solo", 1, data_dir)
    duo = run_world(tmp_path, "duo", 2, data_dir)
    assert solo["world_size"] == 1 and duo["world_size"] == 2
    acc_s = solo["final"]["acc1"]
    acc_d = duo["final"]["acc1"]
    # the task is learnable; both worlds must learn it and agree
    assert acc_s > 0.8, solo["final"]
    assert acc_d > 0.8, duo["final"]
    assert abs(acc_s - acc_d) < 0.1, (solo["final"], duo["final"])
    # global throughput figure uses the world multiplier
    assert duo["max_examples_per_sec_global"] > duo["max_examples_per_sec"]


def test_two_resizes_under_one_percent_acc_loss(tmp_path):
    """The BASELINE north-star clause: a real model surviving >= 2
    elastic resizes with < 1% acc1 loss vs the unresized run.

    World sequence 2 -> 1 -> 2, each phase resuming the shared
    checkpoint with --schedule-epochs pinned to the job's total (so all
    phases ride the SAME 5-epoch cosine curve), compared against a
    straight world=1 run of the same total epochs. The per-phase
    benchmark logs also prove each phase RESUMED (it trained only its
    own epochs) — a silent restore failure would otherwise make the
    comparison vacuous.
    """
    data_dir = make_data(tmp_path)
    ckpt = tmp_path / "ckpt"
    p1 = run_world(tmp_path, "p1", 2, data_dir, epochs=2, ckpt=ckpt,
                   schedule_epochs=5)
    p2 = run_world(tmp_path, "p2", 1, data_dir, epochs=3, ckpt=ckpt,
                   schedule_epochs=5)                            # resize 1
    resized = run_world(tmp_path, "p3", 2, data_dir, epochs=5,
                        ckpt=ckpt, schedule_epochs=5)            # resize 2
    straight = run_world(tmp_path, "straight", 1, data_dir, epochs=5)
    # resumes really happened: each phase trained only its own epochs
    assert [e["epoch"] for e in p1["epochs"]] == [0, 1]
    assert [e["epoch"] for e in p2["epochs"]] == [2]
    assert [e["epoch"] for e in resized["epochs"]] == [3, 4]
    acc_r = resized["final"]["acc1"]
    acc_s = straight["final"]["acc1"]
    assert acc_s > 0.85, straight["final"]
    assert abs(acc_r - acc_s) < 0.01, (resized["final"], straight["final"])


def _pids_with_env(**want):
    """PIDs whose /proc environ contains every given EDL var (the only
    reliable way to find a pod's trainer: launchers start trainers in
    their OWN session, so killing the launcher pgid alone leaves the
    trainer alive — and cmdline is identical across pods)."""
    out = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == os.getpid():
            continue
        try:
            env = open(f"/proc/{pid}/environ", "rb").read().decode(
                "utf-8", "replace")
        except OSError:
            continue
        if all(f"{k}={v}" in env for k, v in want.items()):
            out.append(int(pid))
    return out


def _kill_pod(launcher_proc, pod_id, job_id):
    """SIGKILL a pod: the launcher's process group AND its trainer
    session (found by environ, scoped to this job/pod only)."""
    import signal

    for pid in (launcher_proc.pid, *_pids_with_env(
            EDL_TPU_JOB_ID=job_id, EDL_TPU_POD_ID=pod_id)):
        try:
            os.killpg(os.getpgid(pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def test_flagship_under_launcher_survives_pod_kill(tmp_path):
    """imagenet_train under `edl_tpu.collective.launch`: two launchers
    claim ranks in a real store, form one world, train with per-epoch
    checkpoints; SIGKILLing one pod (launcher + its trainer session)
    stop-resumes the survivor into a 1-pod world that finishes the job
    from the shared checkpoint."""
    from edl_tpu.coord.client import StoreClient

    data_dir = make_data(tmp_path)
    port = net.free_port()
    logs = [open(tmp_path / "store.log", "wb")]
    store = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.coord.server", "--port", str(port)],
        env=cpu_env(), stdout=logs[0], stderr=subprocess.STDOUT)
    client = StoreClient(f"127.0.0.1:{port}")
    deadline = time.time() + 15
    while time.time() < deadline and not client.ping():
        time.sleep(0.2)
    assert client.ping(), "store never came up"

    def launcher(name):
        env = cpu_env({
            "EDL_TPU_JOB_ID": "imjob",
            "EDL_TPU_STORE_ENDPOINTS": f"127.0.0.1:{port}",
            "EDL_TPU_POD_ID": name,
            "EDL_TPU_CHECKPOINT_PATH": str(tmp_path / "ckpt"),
            "EDL_TPU_LOG_DIR": str(tmp_path / f"log_{name}"),
            "EDL_TPU_LEASE_TTL": "2.0",
            "EDL_TPU_BARRIER_STABLE": "0.5",
            "EDL_TPU_NODES_RANGE": "1:4",
        })
        logs.append(open(tmp_path / f"{name}.log", "wb"))
        return subprocess.Popen(
            [sys.executable, "-m", "edl_tpu.collective.launch", "--",
             sys.executable, "-m", TRAINER, "--data-dir", str(data_dir),
             "--model", "ResNetTiny", "--num-classes", "8",
             "--image-size", "16", "--epochs", "6", "--batch-size", "32",
             "--warmup-epochs", "1", "--lr-strategy", "cosine",
             "--lr", "0.05", "--no-augment", "--label-smoothing", "0",
             "--benchmark-log", str(tmp_path / "blog")],
            env=env, stdout=logs[-1], stderr=subprocess.STDOUT,
            start_new_session=True)

    a = launcher("podA")
    b = launcher("podB")
    try:
        from edl_tpu.collective.barrier import read_cluster

        def world_is(n):
            c = read_cluster(client, "imjob")
            return c is not None and c.world_size == n

        deadline = time.time() + 120
        while time.time() < deadline and not world_is(2):
            time.sleep(0.3)
        assert world_is(2), "2-pod world never formed"

        def has_ckpt():
            ckpt = tmp_path / "ckpt"
            return ckpt.is_dir() and any(p.name.startswith("ckpt-")
                                         for p in ckpt.iterdir())

        deadline = time.time() + 180
        while time.time() < deadline and not has_ckpt():
            time.sleep(0.3)
        assert has_ckpt(), "no sealed checkpoint from the 2-pod world"

        _kill_pod(b, "podB", "imjob")  # pod failure: launcher + trainer

        rc = a.wait(timeout=360)
        assert rc == 0, open(tmp_path / "podA.log").read()
        assert client.get("/imjob/complete") is not None
        blog = json.load(open(tmp_path / "blog" / "log_0.json"))
        assert blog["epochs"][-1]["epoch"] == 5  # job finished all epochs
        assert blog["epochs"][-1]["acc1"] > 0.85, blog["epochs"][-1]
    finally:
        _kill_pod(b, "podB", "imjob")
        _kill_pod(a, "podA", "imjob")
        store.terminate()
        store.wait(timeout=5)
        for f in logs:
            f.close()
