"""HostLeaseCoalescer: one host lease carrying many pod registrations.

The contract under test (doc/design_coord.md): coalescing reduces
keepalive WRITE volume, never failure-detection latency — a keepalive
re-arms deadline = now + ttl, never further; host-lease expiry sweeps
every attached key in ONE event batch; per-pod detach touches only its
own key.
"""

import threading
import time

import pytest

from edl_tpu.coord.client import HostLeaseCoalescer
from edl_tpu.coord.store import InMemStore


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def store(clock):
    return InMemStore(clock=clock)


def test_host_expiry_sweeps_all_keys_in_one_batch(store, clock):
    # interval is huge so the keepalive thread never writes: the lease
    # must expire purely on the fake clock
    co = HostLeaseCoalescer(store, "host-a", ttl=5.0, interval=3600.0)
    w = store.watch("/pods/")
    for p in range(8):
        lease = co.attach(f"/pods/{p}")
        store.put(f"/pods/{p}", f"pod-{p}", lease=lease)
    # drain the 8 PUT batches first
    puts = 0
    while puts < 8:
        b = w.get(timeout=1.0)
        assert b is not None
        puts += len(b.events)
    clock.advance(6.0)
    store.sweep()
    batch = w.get(timeout=1.0)
    assert batch is not None
    assert len(batch.events) == 8          # ONE sweep batch, not 8
    assert all(e.type == "DELETE" for e in batch.events)
    assert w.get(timeout=0.0) is None
    w.cancel()
    co.close()


def test_host_loss_fires_every_on_lost(store, clock):
    co = HostLeaseCoalescer(store, "host-b", ttl=2.0, interval=0.05)
    fired = set()
    done = threading.Event()

    def lost(p):
        fired.add(p)
        if len(fired) == 4:
            done.set()

    for p in range(4):
        lease = co.attach(f"/pods/{p}", on_lost=lambda p=p: lost(p))
        store.put(f"/pods/{p}", "x", lease=lease)
    clock.advance(3.0)  # past ttl: the next keepalive finds it expired
    assert done.wait(timeout=5.0)
    assert fired == {0, 1, 2, 3}
    assert co.stats()["leases_lost"] == 1
    assert co.stats()["active"] == 0
    assert store.get("/pods/0") is None    # swept with the lease
    co.close()


def test_detach_removes_only_that_key(store, clock):
    co = HostLeaseCoalescer(store, "host-c", ttl=30.0, interval=3600.0)
    for p in range(3):
        lease = co.attach(f"/pods/{p}")
        store.put(f"/pods/{p}", "x", lease=lease)
    co.detach("/pods/1", delete=True)
    assert store.get("/pods/1") is None
    assert store.get("/pods/0") is not None
    assert store.get("/pods/2") is not None
    assert co.stats()["lease_batch_size"] == 2
    assert co.stats()["active"] == 1       # siblings keep the lease
    co.close()


def test_last_detach_retires_the_host_lease(store, clock):
    co = HostLeaseCoalescer(store, "host-d", ttl=30.0, interval=3600.0)
    lease = co.attach("/pods/only")
    store.put("/pods/only", "x", lease=lease)
    co.detach("/pods/only", delete=False)
    assert co.stats()["active"] == 0
    # the revoke swept the still-attached key with the lease
    assert store.get("/pods/only") is None
    # a fresh attach re-grants a NEW lease
    lease2 = co.attach("/pods/again")
    assert lease2 != lease
    assert co.stats()["active"] == 1
    co.close()


def test_keepalive_rearms_to_now_plus_ttl_never_further(store, clock):
    lease = store.lease_grant(10.0)
    store.put("/k", "v", lease=lease)
    clock.advance(5.0)
    assert store.lease_keepalive(lease)    # deadline -> t=15, not t=20
    clock.advance(9.0)                     # t=14: still inside the ttl
    store.sweep()
    assert store.get("/k") is not None
    clock.advance(2.0)                     # t=16: one ttl past the LAST
    store.sweep()                          # keepalive — expired
    assert store.get("/k") is None
    assert not store.lease_keepalive(lease)


def test_keepalives_coalesce_to_one_write_per_interval(store, clock):
    # 16 pods on one host: the write volume is the HOST's keepalive
    # cadence, independent of how many pods attached
    co = HostLeaseCoalescer(store, "host-e", ttl=1.0, interval=0.05)
    for p in range(16):
        co.attach(f"/pods/{p}")
    before = store.op_count
    time.sleep(0.4)
    writes = co.stats()["keepalives_sent"]
    assert writes >= 2                     # the thread is actually running
    # every keepalive is ONE store op, not 16
    assert store.op_count - before <= writes + 2
    assert co.stats()["lease_batch_size"] == 16
    co.close()
