"""Reform state machine: phase ladder, deadlines, downgrades, fencing.

The contract under test (collective/reform.py + train/loop.py wiring):
a surviving trainer rides a true device-world change IN PLACE — quiesce
-> mesh-reform -> peer-restore -> re-jit -> first-step — and every
failure lands on its DEFINED downgrade: donor death mid-peer-restore
falls back to disk, a mesh-reform deadline overrun falls back to a
clean stop-resume (with generation fencing keeping a half-reformed
survivor from ever acking), and a second reform of an already-seen
shape performs zero fresh jits. The full multi-process loop runs in
`elastic_demo --resize-reform` (CI dryrun).
"""

import threading
import time

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from edl_tpu.collective import migration as mig
from edl_tpu.collective import reform as rf
from edl_tpu.collective import register as reg
from edl_tpu.collective.cluster import Cluster, Pod
from edl_tpu.coord.store import InMemStore
from edl_tpu.parallel import mesh as mesh_lib
from edl_tpu.train.loop import LoopConfig, TrainLoop
from edl_tpu.train.state import TrainStatus


# -- machine unit tests (no training loop) ----------------------------------


class TestReformMachine:
    def cfg(self, **kw):
        base = dict(quiesce_s=5.0, mesh_s=5.0, restore_s=5.0,
                    rejit_s=5.0)
        base.update(kw)
        return rf.ReformConfig(**base)

    def test_ladder_happy_path_records_phases_in_order(self):
        m = rf.ReformMachine(2, self.cfg())
        m.run_ladder(quiesce=lambda dl: None,
                     mesh_reform=lambda dl: None,
                     restore_peers=lambda dl: None,
                     restore_disk=lambda dl: None)
        assert m.result == rf.IN_PLACE
        assert m.restore == "peers"
        assert [p["phase"] for p in m.phases] == [
            "quiesce", "mesh-reform", "peer-restore"]
        assert all(p["ok"] for p in m.phases)

    def test_peer_failure_downgrades_to_disk(self):
        def bad_peers(dl):
            raise ConnectionError("donor died mid-transfer")
        m = rf.ReformMachine(2, self.cfg())
        m.run_ladder(mesh_reform=lambda dl: None,
                     restore_peers=bad_peers,
                     restore_disk=lambda dl: None)
        assert m.result == rf.IN_PLACE
        assert m.restore == "disk"
        names = [p["phase"] for p in m.phases]
        assert names == ["mesh-reform", "peer-restore", "disk-restore"]
        assert m.phases[1]["ok"] is False

    def test_disk_failure_lands_on_stop_resume(self):
        def bad(dl):
            raise OSError("no sealed version")
        m = rf.ReformMachine(2, self.cfg())
        m.run_ladder(restore_peers=bad, restore_disk=bad)
        assert m.result == rf.STOP_RESUME
        assert "disk-restore" in m.error

    def test_deadline_overrun_is_a_typed_failure(self):
        # cooperative enforcement is post-hoc: a phase that RETURNS
        # late still failed its budget
        m = rf.ReformMachine(2, self.cfg(mesh_s=0.05))
        m.run_ladder(mesh_reform=lambda dl: time.sleep(0.12))
        assert m.result == rf.STOP_RESUME
        assert "deadline exceeded" in m.error
        assert m.phases[0]["overrun"] is True

    def test_quiesce_failure_downgrades_to_stop_resume(self):
        def stuck(dl):
            raise TimeoutError("checkpoint writer did not drain")
        m = rf.ReformMachine(2, self.cfg())
        m.run_ladder(quiesce=stuck, restore_peers=lambda dl: None,
                     restore_disk=lambda dl: None)
        assert m.result == rf.STOP_RESUME
        assert m.restore is None  # never got to the restore phases

    def test_deferred_phases_flag_overruns_without_downgrade(self):
        m = rf.ReformMachine(3, self.cfg(rejit_s=0.01))
        m.run_ladder(quiesce=lambda dl: None)
        m.note_deferred("re-jit", 0.5)
        m.note_deferred("first-step", 0.001)
        doc = m.finish()
        assert doc["result"] == rf.IN_PLACE  # advisory past dispatch
        rejit = [p for p in doc["phases"] if p["phase"] == "re-jit"][0]
        assert rejit["overrun"] is True

    def test_finish_is_idempotent(self):
        m = rf.ReformMachine(2, self.cfg())
        m.run_ladder(quiesce=lambda dl: None)
        assert m.finish() == m.finish()


# -- generation fencing (the epoch-doc half) --------------------------------


class TestGenerationFencing:
    def _service(self, store):
        return mig.MigrationService(store, "fjob", "pod0",
                                    addr="127.0.0.1", generation=2)

    def _publish_cluster(self, store, version):
        pods = [Pod(pod_id="pod0", addr="127.0.0.1", claimed_rank=0,
                    rank=0)]
        store.put(reg.cluster_key("fjob"),
                  Cluster(job_id="fjob", version=version,
                          pods=pods).to_json())

    def test_stale_adoption_ack_bounces(self):
        store = InMemStore()
        svc = self._service(store)
        try:
            self._publish_cluster(store, 3)  # the world moved on
            assert svc.ack("adopted", generation=2) is False
            assert store.get(mig.ack_key("fjob", "pod0")) is None
        finally:
            svc.shutdown(linger=False)

    def test_current_generation_ack_lands(self):
        store = InMemStore()
        svc = self._service(store)
        try:
            self._publish_cluster(store, 3)
            assert svc.ack("adopted", generation=3) is True
            rec = store.get(mig.ack_key("fjob", "pod0"))
            assert rec is not None
        finally:
            svc.shutdown(linger=False)

    def test_non_adoption_acks_are_not_fenced(self):
        # a restore ack describes THIS pod's restart, not a claim about
        # the world's generation — it must land even mid-churn
        store = InMemStore()
        svc = self._service(store)
        try:
            self._publish_cluster(store, 9)
            assert svc.ack("peers", generation=2) is True
        finally:
            svc.shutdown(linger=False)


# -- loop-level fault matrix ------------------------------------------------


class FakeMigration:
    """The loop-facing surface of MigrationService, scriptable."""

    def __init__(self, store, job="rjob", pod="pod0"):
        self.stop_requested = threading.Event()
        self.generation = 1
        self.pod_id = pod
        self.job_id = job
        self.store = store
        self.pending: list = []       # Reform objects to deliver
        self.acks: list = []
        self.adopted_generations: list = []
        self.peer_restore = "ok"      # "ok" | "dead-donor"
        self.restores = 0

    def poll_reform(self):
        return self.pending[0] if self.pending else None

    def adopted(self, reform):
        self.generation = reform.generation
        self.adopted_generations.append(reform.generation)
        if self.pending and self.pending[0] is reform:
            self.pending.pop(0)

    def ack(self, mode, **kw):
        self.acks.append((mode, kw))
        return True

    def flush_advert(self):
        return True

    def restore_from_peers(self, target, **kw):
        self.restores += 1
        if self.peer_restore == "dead-donor":
            raise mig.PeerRestoreError("donor died mid-transfer")
        status = TrainStatus()
        return target, status, {"bytes_from_peers": 64, "version": 1,
                                "donors": ["pod0"], "restore_s": 0.01}

    def shutdown(self, linger=None):
        pass


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("dp",))


def _cluster(version):
    return Cluster(job_id="rjob", version=version,
                   pods=[Pod(pod_id="pod0", addr="127.0.0.1",
                             claimed_rank=0, rank=0)])


class ReformHarness:
    """A tiny loop wired exactly like the --local-mesh-by-world demo
    trainer: dp mesh sized by the 'world', traced step, scripted
    reform deliveries."""

    def __init__(self, tmp_path, reform_config=None, steps=12,
                 triggers=None):
        self.traces = []
        self.mesh_holder = {"mesh": _mesh(1)}

        @jax.jit
        def step(state, batch):
            self.traces.append(1)
            w = state["w"] + batch["x"].sum()
            return {"w": w}, {}

        self.step_jit = step

        state = {"w": np.zeros((4,), np.float32)}
        state = mesh_lib.replicate_host_tree(self.mesh_holder["mesh"],
                                             state)

        def reform_mesh(rank, world, cluster):
            new = _mesh(2 if world <= 1 else 1)
            if new.devices.size \
                    == self.mesh_holder["mesh"].devices.size:
                return None
            self.mesh_holder["mesh"] = new
            return new

        self.triggers = dict(triggers or {})

        def hook(loop, epoch, step_no, metrics):
            gen = self.triggers.pop(step_no, None)
            if gen is not None:
                # world flips 2 <-> 1 so the mesh hook flips 1 <-> 2
                world = 1 if gen % 2 == 0 else 2
                self.fake.pending.append(
                    mig.Reform(_cluster(gen), 0, world))

        self.loop = TrainLoop(
            step, state, mesh=self.mesh_holder["mesh"],
            config=LoopConfig(num_epochs=1, log_every_steps=1,
                              ckpt_dir=str(tmp_path / "ckpt")),
            batch_axes=("dp",),
            place_state=lambda t: mesh_lib.replicate_host_tree(
                self.mesh_holder["mesh"], t),
            reform_mesh=reform_mesh,
            reform_config=reform_config,
            hooks=[hook])
        self.fake = FakeMigration(InMemStore())
        self.loop._migration = self.fake
        self.steps = steps

    def data(self, epoch):
        for _ in range(self.steps):
            yield {"x": np.ones((8, 1), np.float32)}

    def run(self):
        return self.loop.run(self.data)


class TestLoopReformFaultMatrix:
    def test_reform_restores_from_peers_and_reacks(self, tmp_path):
        h = ReformHarness(tmp_path, triggers={3: 2})
        h.run()
        assert h.loop.reforms == 1
        assert h.fake.adopted_generations == [2]
        adopted = [kw for mode, kw in h.fake.acks if mode == "adopted"]
        assert len(adopted) == 1
        doc = adopted[0]["reform"]
        assert doc["result"] == "in-place"
        assert doc["restore"] == "peers"
        names = [p["phase"] for p in doc["phases"]]
        assert names == ["quiesce", "mesh-reform", "peer-restore",
                         "re-jit", "first-step"]
        assert adopted[0]["bytes_from_peers"] == 64

    def test_second_reform_of_same_shape_zero_fresh_jits(self, tmp_path):
        # shapes: mesh-1dev (start) -> mesh-2dev (gen 2) -> mesh-1dev
        # (gen 3, ALREADY COMPILED): across THREE device worlds the jit
        # executable cache must hold exactly two entries — the second
        # reform of an already-seen shape performs zero fresh jits
        h = ReformHarness(tmp_path, triggers={3: 2, 7: 3})
        h.run()
        assert h.loop.reforms == 2
        cache_size = h.step_jit._cache_size()
        assert cache_size == 2, (
            f"expected 2 compiled entries (1-dev + 2-dev shapes), got "
            f"{cache_size} — the cached-shape reform re-jitted")

    def test_donor_death_mid_peer_restore_falls_back_to_disk(
            self, tmp_path):
        h = ReformHarness(tmp_path, triggers={3: 2})
        h.fake.peer_restore = "dead-donor"
        h.run()
        assert h.loop.reforms == 1
        adopted = [kw for mode, kw in h.fake.acks if mode == "adopted"]
        doc = adopted[0]["reform"]
        assert doc["result"] == "in-place"
        assert doc["restore"] == "disk"
        names = [p["phase"] for p in doc["phases"]]
        assert "peer-restore" in names and "disk-restore" in names
        # the quiesce-sealed version is what disk restored: the loop
        # still holds a state (zeros target filled from its own seal)
        assert h.loop.restore_source == "disk"

    def test_mesh_deadline_exceeded_degrades_to_stop_resume(
            self, tmp_path):
        cfg = rf.ReformConfig(quiesce_s=5.0, mesh_s=0.05,
                              restore_s=5.0, rejit_s=5.0)
        h = ReformHarness(tmp_path, reform_config=cfg, triggers={3: 2})
        slow_inner = h.loop.reform_mesh

        def slow_mesh(rank, world, cluster):
            time.sleep(0.12)  # past the 0.05s mesh budget
            return slow_inner(rank, world, cluster)

        h.loop.reform_mesh = slow_mesh
        with pytest.raises(SystemExit) as exc:
            h.run()
        assert exc.value.code == 143  # the graceful-stop exit contract
        assert h.loop.stop_reason == "reform-downgrade"
        assert h.loop.last_reform["result"] == "stop-resume"
        assert "deadline exceeded" in h.loop.last_reform["error"]
        # never adopted, never acked adoption: the launcher's
        # wait_adopted times out into classic stop-resume
        assert h.fake.adopted_generations == []
        assert not any(m == "adopted" for m, _ in h.fake.acks)
        # generation fencing half two: the trainer's generation never
        # advanced, so a late ack through the REAL service would bounce
        # (TestGenerationFencing pins that path)
        assert h.fake.generation == 1

    def test_unchanged_device_set_keeps_the_fast_path(self, tmp_path):
        # a reform whose mesh hook answers None must not seal/restore
        h = ReformHarness(tmp_path, triggers={3: 2})

        h.loop.reform_mesh = lambda rank, world, cluster: None
        h.run()
        assert h.loop.reforms == 1
        adopted = [kw for mode, kw in h.fake.acks if mode == "adopted"]
        doc = adopted[0]["reform"]
        assert doc["result"] == "in-place"
        assert doc["restore"] is None
        # the run's startup try_restore is the only peer restore: the
        # unchanged-device-set reform itself never touched the wire
        assert h.fake.restores == 1
        names = [p["phase"] for p in doc["phases"]]
        assert "peer-restore" not in names and "disk-restore" not in names
