"""DataServer / RemoteSource: remote records through the local DataLoader.

Finishes the reference's WIP data-server pair (utils/data_server.py,
utils/distribute_reader.py) — these tests are its missing test suite:
protocol ops, error surfaces, loader equivalence, concurrent consumers,
reconnect after a server bounce.
"""

import socket
import threading

import numpy as np
import pytest

from edl_tpu.data.data_server import DataServer, RemoteSource
from edl_tpu.data.pipeline import ArraySource, DataLoader
from edl_tpu.utils.exceptions import EdlDataError


@pytest.fixture
def served():
    rng = np.random.default_rng(0)
    data = {"x": rng.normal(size=(64, 5)).astype(np.float32),
            "y": np.arange(64, dtype=np.int32)}
    server = DataServer(ArraySource(data), host="127.0.0.1").start()
    yield server, data
    server.stop()


class TestProtocol:
    def test_len_and_ping(self, served):
        server, _ = served
        src = RemoteSource(f"127.0.0.1:{server.port}")
        assert len(src) == 64
        assert src._call({"op": "ping"})[0]["ok"]

    def test_batch_matches_local(self, served):
        server, data = served
        src = RemoteSource(f"127.0.0.1:{server.port}")
        idx = np.array([3, 60, 7, 7, 0])
        got = src.batch(idx)
        np.testing.assert_array_equal(got["x"], data["x"][idx])
        np.testing.assert_array_equal(got["y"], data["y"][idx])

    def test_bad_indices_surface_as_errors(self, served):
        server, _ = served
        src = RemoteSource(f"127.0.0.1:{server.port}")
        with pytest.raises(EdlDataError, match="bad indices"):
            src.batch(np.array([999]))
        with pytest.raises(EdlDataError, match="bad indices"):
            src.batch(np.array([-1]))
        # connection still usable after an error reply
        assert len(src.batch(np.array([0]))["y"]) == 1

    def test_unknown_op(self, served):
        server, _ = served
        src = RemoteSource(f"127.0.0.1:{server.port}")
        with pytest.raises(EdlDataError, match="unknown op"):
            src._call({"op": "nope"})

    def test_corrupt_shard_surfaces_as_error(self, tmp_path):
        """A shard that turns unreadable after indexing must come back as
        an error frame (with the real cause), not a silent disconnect."""
        from edl_tpu.data.pipeline import FileSource

        p = str(tmp_path / "s.npz")
        np.savez(p, y=np.arange(8, dtype=np.int32))
        src = FileSource([p], cache_files=1)
        with open(p, "wb") as f:
            f.write(b"corrupt")
        server = DataServer(src, host="127.0.0.1").start()
        try:
            remote = RemoteSource(f"127.0.0.1:{server.port}")
            # numpy reports the unreadable file as ValueError or
            # BadZipFile depending on how it is corrupted — either way
            # the client must see the server-side cause
            with pytest.raises(EdlDataError,
                               match="BadZipFile|zip|ValueError"):
                remote.batch(np.array([0]))
        finally:
            server.stop()

    def test_garbage_bytes_do_not_kill_server(self, served):
        server, _ = served
        s = socket.create_connection(("127.0.0.1", server.port))
        s.sendall(b"NOT A FRAME AT ALL")
        s.close()
        src = RemoteSource(f"127.0.0.1:{server.port}")
        assert len(src) == 64


class TestLoaderIntegration:
    def test_remote_loader_identical_to_local(self, served):
        server, data = served
        local = DataLoader(ArraySource(data), 16, seed=5)
        remote = DataLoader(RemoteSource(f"127.0.0.1:{server.port}"), 16,
                            seed=5)
        for lb, rb in zip(local.epoch(1), remote.epoch(1)):
            np.testing.assert_array_equal(lb["x"], rb["x"])
            np.testing.assert_array_equal(lb["y"], rb["y"])

    def test_sharded_remote_consumers_partition(self, served):
        """Two ranks over one server: disjoint shards covering the epoch
        (the leader-served file-shard story)."""
        server, _ = served
        seen = []

        def consume(rank):
            src = RemoteSource(f"127.0.0.1:{server.port}")
            ld = DataLoader(src, 8, rank=rank, world=2, seed=2)
            ids = [int(y) for b in ld.epoch(0) for y in b["y"]]
            seen.append(ids)

        ts = [threading.Thread(target=consume, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert len(seen) == 2
        a, b = map(set, seen)
        assert a.isdisjoint(b)
        assert len(a | b) == 64

    def test_reconnect_after_server_bounce(self):
        data = {"y": np.arange(16, dtype=np.int32)}
        server = DataServer(ArraySource(data), host="127.0.0.1").start()
        port = server.port
        src = RemoteSource(f"127.0.0.1:{port}")
        assert len(src.batch(np.array([1]))["y"]) == 1
        server.stop()
        server2 = None
        for _ in range(50):  # old conns may hold the port briefly
            try:
                server2 = DataServer(ArraySource(data), host="127.0.0.1",
                                     port=port).start()
                break
            except OSError:
                import time
                time.sleep(0.1)
        assert server2 is not None, "could not rebind port"
        try:
            got = src.batch(np.array([2]))  # reconnect-and-retry path
            assert int(got["y"][0]) == 2
        finally:
            server2.stop()
