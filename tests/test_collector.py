"""Collector + UtilizationPublisher: the scheduler data path.

Unit tier (InMemStore, no processes); the live-elastic-job integration
assertion rides test_multipod.py's launcher test (slow tier).
Capability of /root/reference/example/fit_a_line/collector.py:51-130 +
the reserved registry info field (discovery/register.py:36-40).
"""

import json
import time

import numpy as np

from edl_tpu.collective.cluster import Cluster, Pod
from edl_tpu.collective.register import cluster_key, rank_key
from edl_tpu.coord.collector import (Collector, UtilizationPublisher,
                                     util_key)
from edl_tpu.coord.store import InMemStore


def _seed_job(store, job="j1"):
    for i, pod_id in enumerate(("podA", "podB")):
        lease = store.lease_grant(5.0)
        store.put(rank_key(job, i),
                  Pod(pod_id=pod_id, addr="10.0.0.%d" % i, n_devices=4,
                      claimed_rank=i, rank=i).to_json(), lease=lease)
    cluster = Cluster(job_id=job, version=3,
                      pods=[Pod(pod_id="podA", addr="10.0.0.0", rank=0),
                            Pod(pod_id="podB", addr="10.0.0.1", rank=1)])
    store.put(cluster_key(job), cluster.to_json())


class TestCollector:
    def test_job_snapshot_pods_generation_utilization(self):
        store = InMemStore()
        _seed_job(store)
        store.put(util_key("j1", "podA"),
                  json.dumps({"step": 40, "samples_seen": 640,
                              "examples_per_sec": 93.5}),
                  lease=store.lease_grant(5.0))
        snap = Collector(store, job_id="j1").snapshot()
        job = snap["job"]
        assert job["generation"] == 3 and job["world_size"] == 2
        assert not job["complete"]
        pods = {p["pod_id"]: p for p in job["pods"]}
        assert pods["podA"]["utilization"]["examples_per_sec"] == 93.5
        assert pods["podB"]["utilization"] is None  # none published yet
        assert snap["store"]["revision"] > 0
        assert snap["store"]["leased_keys"] >= 3

    def test_service_snapshot_surfaces_teacher_counters(self):
        """A teacher's busy_s / served_rows reach the collector through
        the registrar's info field (the done-criterion of VERDICT r4
        next-step 7)."""
        from edl_tpu.coord.registry import ServiceRegistry
        store = InMemStore()
        registry = ServiceRegistry(store)
        registration = registry.register("svc", "10.0.0.9:2390", ttl=5.0)
        registration.update_info(json.dumps(
            {"busy_s": 12.5, "served_rows": 4096, "rows_per_sec": 327.0}))
        try:
            snap = Collector(store, services=("svc",)).snapshot()
            (meta,) = snap["services"]["svc"]
            assert meta["server"] == "10.0.0.9:2390"
            assert meta["info"]["busy_s"] == 12.5
            assert meta["info"]["served_rows"] == 4096
        finally:
            registration.stop()

    def test_cli_once_emits_one_json_line(self, capsys):
        """The CLI path over a real TCP store server."""
        import subprocess
        import sys

        from edl_tpu.coord.client import StoreClient
        from edl_tpu.utils import net
        port = net.free_port()
        proc = subprocess.Popen(
            [sys.executable, "-m", "edl_tpu.coord.server",
             "--port", str(port)], stderr=subprocess.DEVNULL)
        try:
            client = StoreClient(f"127.0.0.1:{port}")
            deadline = time.time() + 15
            while time.time() < deadline and not client.ping():
                time.sleep(0.2)
            _seed_job(client, job="jcli")
            from edl_tpu.coord.collector import main
            assert main(["--store", f"127.0.0.1:{port}", "--job", "jcli",
                         "--once"]) == 0
            line = capsys.readouterr().out.strip()
            doc = json.loads(line)
            assert doc["job"]["generation"] == 3
            assert {p["pod_id"] for p in doc["job"]["pods"]} == \
                {"podA", "podB"}
            client.close()
        finally:
            proc.terminate()
            proc.wait(timeout=5)


def test_collector_over_redis_flavor():
    """The collector's reads (get_prefix/get) are within RedisStore's
    scope, so the SAME scrape works over the redis discovery flavor."""
    from edl_tpu.coord.redis_store import RedisStore
    from edl_tpu.coord.resp import MiniRedis
    srv = MiniRedis().start()
    try:
        store = RedisStore(srv.endpoint)
        _seed_job(store, job="jr")
        snap = Collector(store, job_id="jr").snapshot()
        assert snap["job"]["generation"] == 3
        assert len(snap["job"]["pods"]) == 2
        assert snap["store"]["leased_keys"] >= 2
        store.close()
    finally:
        srv.stop()


class TestUtilizationPublisher:
    class _Loop:
        class status:
            samples_seen = 0

    def test_publishes_rate_and_samples(self):
        store = InMemStore()
        pub = UtilizationPublisher(store, "j1", "podA", rank=1,
                                   min_interval=0.0)
        loop = self._Loop()
        loop.status.samples_seen = 128
        pub(loop, epoch=0, step=10, metrics={})
        assert pub.flush()   # r6: store writes ride a background thread
        rec = store.get(util_key("j1", "podA"))
        doc = json.loads(rec.value)
        assert doc["samples_seen"] == 128 and doc["rank"] == 1
        assert doc["step"] == 10
        assert rec.lease  # leased: stale records self-clean
        loop.status.samples_seen = 256
        pub(loop, epoch=0, step=20, metrics={})
        assert pub.flush()
        doc = json.loads(store.get(util_key("j1", "podA")).value)
        assert doc["samples_seen"] == 256
        assert doc["examples_per_sec"] > 0
        pub.stop()
        assert store.get(util_key("j1", "podA")) is None  # lease revoked

    def test_doc_carries_scaler_contract_fields(self):
        """The autoscaler's staleness + correlation anchors: a
        monotonic `published_unix` and the world size the rate was
        measured under (edl_tpu/scaler reads both). `world_size` is the
        ELASTIC world (pod count, what the launcher exports) — NOT the
        device world in loop.status — because the scaler compares it
        against Cluster.world_size, which counts pods."""

        class _Loop:
            class status:
                samples_seen = 128
                world_size = 8   # device world (2 pods x 4 devices)

        store = InMemStore()
        pub = UtilizationPublisher(store, "j1", "podA", min_interval=0.0,
                                   generation=7, world_size=2)
        loop = _Loop()
        stamps = []
        for step in (1, 2, 3):
            loop.status.samples_seen = 128 * step
            pub(loop, 0, step, {})
            assert pub.flush()
            doc = json.loads(store.get(util_key("j1", "podA")).value)
            stamps.append(doc["published_unix"])
            assert doc["world_size"] == 2   # pod count, never 8
            assert doc["generation"] == 7
        assert stamps == sorted(stamps)
        assert len(set(stamps)) == 3  # strictly increasing
        pub.stop()

    def test_world_size_unknown_published_as_null(self):
        """A standalone hook (no launcher context) doesn't know the
        elastic world: the doc carries null, which the scaler treats as
        'cannot correlate' rather than filtering the record out."""
        store = InMemStore()
        pub = UtilizationPublisher(store, "j1", "podA", min_interval=0.0)
        loop = self._Loop()
        pub(loop, 0, 1, {})
        assert pub.flush()
        doc = json.loads(store.get(util_key("j1", "podA")).value)
        assert doc["world_size"] is None
        pub.stop()

    def test_from_env_reads_elastic_world(self, monkeypatch):
        """from_env wires EDL_TPU_WORLD_SIZE (the launcher's pod count)
        into the published world_size."""
        import edl_tpu.coord.redis_store as redis_store
        store = InMemStore()
        monkeypatch.setattr(redis_store, "connect_store",
                            lambda ep: store)
        monkeypatch.setenv("EDL_TPU_RANK", "0")
        monkeypatch.setenv("EDL_TPU_WORLD_SIZE", "3")
        monkeypatch.setenv("EDL_TPU_STORE_ENDPOINTS", "127.0.0.1:1")
        monkeypatch.setenv("EDL_TPU_JOB_ID", "jenv")
        monkeypatch.setenv("EDL_TPU_POD_ID", "podE")
        pub = UtilizationPublisher.from_env()
        assert pub is not None and pub.world_size == 3
        pub._owns_store = False  # InMemStore: nothing to close
        pub.stop()

    def test_store_failure_never_raises(self):
        class _Broken:
            def lease_grant(self, ttl):
                raise OSError("store down")

        pub = UtilizationPublisher(_Broken(), "j", "p", min_interval=0.0)
        loop = self._Loop()
        pub(loop, 0, 1, {})  # must swallow, training goes on
        pub.flush()
        pub.stop()

    def test_hung_store_never_stalls_training_thread(self):
        """The r6 redesign's acceptance: __call__ does NO store I/O, so
        a store hanging for seconds costs the train step nothing (before,
        every log point rode the store's multi-second timeout)."""
        class _Hung:
            def lease_grant(self, ttl):
                time.sleep(0.5)
                raise OSError("store hung then down")

        pub = UtilizationPublisher(_Hung(), "j", "p", min_interval=0.0)
        loop = self._Loop()
        t0 = time.monotonic()
        for step in range(20):
            pub(loop, 0, step, {})
        assert time.monotonic() - t0 < 0.4  # never blocked on the store
        pub.stop()

    def test_drop_latest_keeps_newest_snapshot(self):
        """A stalled publisher drops OLD snapshots: after it unwedges,
        the stored record is the newest one, not a backlog replay."""
        store = InMemStore()
        gate = time.monotonic() + 0.3

        class _SlowStore:
            def lease_grant(self, ttl):
                while time.monotonic() < gate:   # wedge the first put
                    time.sleep(0.01)
                return store.lease_grant(ttl)

            def __getattr__(self, name):  # keepalive/put/... pass through
                return getattr(store, name)

        pub = UtilizationPublisher(_SlowStore(), "j1", "podA",
                                   min_interval=0.0)
        loop = self._Loop()
        for step in range(1, 6):
            loop.status.samples_seen = 128 * step
            pub(loop, 0, step, {})
            time.sleep(0.02)
        assert pub.flush()
        doc = json.loads(store.get(util_key("j1", "podA")).value)
        assert doc["step"] == 5   # latest wins
        pub.stop()

    def test_from_env_requires_launcher_context(self, monkeypatch):
        monkeypatch.delenv("EDL_TPU_RANK", raising=False)
        assert UtilizationPublisher.from_env() is None
        monkeypatch.setenv("EDL_TPU_PUBLISH_UTIL", "0")
        monkeypatch.setenv("EDL_TPU_RANK", "0")
        assert UtilizationPublisher.from_env() is None


def test_publisher_as_trainloop_hook_end_to_end():
    """TrainLoop auto-installs nothing standalone; with an explicit
    publisher hook, a short real training run publishes utilization."""
    import jax
    import jax.numpy as jnp
    import optax

    from edl_tpu.train.loop import LoopConfig, TrainLoop
    from edl_tpu.train.state import TrainState
    from edl_tpu.train.step import make_train_step

    store = InMemStore()
    pub = UtilizationPublisher(store, "jobX", "podX", rank=0,
                               min_interval=0.0)
    params = {"w": jnp.zeros((4,))}
    state = TrainState.create(apply_fn=None, params=params,
                              tx=optax.sgd(0.1))

    def loss_fn(state, params, batch):
        return jnp.sum((batch["x"] @ params["w"] - batch["y"]) ** 2), {}

    step = make_train_step(loss_fn, donate=False)
    rng = np.random.default_rng(0)
    batches = [{"x": rng.normal(size=(8, 4)).astype(np.float32),
                "y": rng.normal(size=(8,)).astype(np.float32)}
               for _ in range(4)]
    loop = TrainLoop(step, state, config=LoopConfig(num_epochs=1,
                                                    log_every_steps=1),
                     hooks=[pub])
    loop.run(lambda epoch: iter(batches))
    # stop() ran inside run()? no — explicit hooks are caller-owned
    assert pub.flush()
    doc = json.loads(store.get(util_key("jobX", "podX")).value)
    assert doc["samples_seen"] == 32
    pub.stop()
