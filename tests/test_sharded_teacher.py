"""Multi-chip teacher serving (distill/sharded_teacher.py): a tp x dp
sharded teacher forward must serve value-identical predictions to the
single-device one, through the padding path and the real TCP server."""

import jax
import numpy as np
import pytest

from edl_tpu.distill.sharded_teacher import (parse_local_mesh,
                                             sharded_predict_fn)
from edl_tpu.parallel import mesh as mesh_lib
from edl_tpu.parallel import sharding as shd

VOCAB, SEQ = 64, 16


def _teacher():
    import jax.numpy as jnp

    from edl_tpu.models.transformer import Transformer, TransformerConfig
    cfg = TransformerConfig(vocab_size=VOCAB, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_len=SEQ,
                            dtype=jnp.float32)
    return Transformer(cfg)


def _toks(rows, seed=0):
    return np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (rows, SEQ), 0, VOCAB))


class TestShardedPredict:
    def setup_method(self, method):
        self.mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(
            {"dp": 2, "tp": 4}))
        self.model = _teacher()
        init_toks = _toks(2)
        self.variables = shd.init_sharded(
            lambda: self.model.init(jax.random.PRNGKey(0), init_toks,
                                    train=False), self.mesh)

    def _apply(self, v, x):
        return self.model.apply(v, x, train=False)

    def _dense_ref(self, toks):
        host_vars = jax.device_get(self.variables)
        return np.asarray(self.model.apply(host_vars, toks, train=False))

    def test_dense_predictions_match_single_device(self):
        predict, meta = sharded_predict_fn(
            self._apply, self.variables, self.mesh, input_key="tokens",
            output_key="logits", batch_axes=("dp",))
        assert meta is None
        toks = _toks(4)
        out = predict({"tokens": toks})["logits"]
        assert out.shape == (4, SEQ, VOCAB)
        np.testing.assert_allclose(out, self._dense_ref(toks), atol=2e-5)

    def test_ragged_rows_pad_and_trim(self):
        """5 rows over dp=2: the pad row must not leak into results."""
        predict, _ = sharded_predict_fn(
            self._apply, self.variables, self.mesh, input_key="tokens",
            output_key="logits", batch_axes=("dp",))
        toks = _toks(5, seed=3)
        out = predict({"tokens": toks})["logits"]
        assert out.shape == (5, SEQ, VOCAB)
        np.testing.assert_allclose(out, self._dense_ref(toks), atol=2e-5)

    def test_serve_topk_over_vocab_parallel_head(self):
        """Distributed top-k on the tp-sharded vocab axis: indices/values
        must match the dense single-device top-k."""
        predict, meta = sharded_predict_fn(
            self._apply, self.variables, self.mesh, input_key="tokens",
            output_key="logits", batch_axes=("dp",), serve_topk=4,
            classes=VOCAB)
        assert meta == {"logits": {"topk": 4, "classes": VOCAB,
                                   "values": "<f2"}}
        toks = _toks(2, seed=5)
        out = predict({"tokens": toks})
        idx, val = out["logits.idx"], out["logits.val"]
        assert idx.shape == (2, SEQ, 4) and val.dtype == np.float16
        ref = self._dense_ref(toks)
        ref_idx = np.argsort(-ref, axis=-1)[..., :4]
        np.testing.assert_array_equal(idx, ref_idx)
        np.testing.assert_allclose(
            val.astype(np.float32),
            np.take_along_axis(ref, ref_idx, axis=-1), atol=2e-3)

    def test_topk_requires_classes(self):
        with pytest.raises(ValueError, match="classes"):
            sharded_predict_fn(self._apply, self.variables, self.mesh,
                               serve_topk=4)

    def test_topk_clamped_to_classes(self):
        """K > head width must clamp + announce the clamped K, not die
        in lax.top_k on the first predict."""
        predict, meta = sharded_predict_fn(
            self._apply, self.variables, self.mesh, input_key="tokens",
            output_key="logits", batch_axes=("dp",),
            serve_topk=VOCAB + 100, classes=VOCAB)
        assert meta["logits"]["topk"] == VOCAB
        out = predict({"tokens": _toks(2)})
        assert out["logits.idx"].shape == (2, SEQ, VOCAB)

    def test_through_real_tcp_server(self):
        """Full path: sharded predict behind TeacherServer, sparse
        TeacherClient consumes idx/val."""
        from edl_tpu.distill.teacher_server import (TeacherClient,
                                                    TeacherServer)
        predict, meta = sharded_predict_fn(
            self._apply, self.variables, self.mesh, input_key="tokens",
            output_key="logits", batch_axes=("dp",), serve_topk=4,
            classes=VOCAB)
        with TeacherServer(predict, host="127.0.0.1",
                           compressed_meta=meta) as srv:
            c = TeacherClient(f"127.0.0.1:{srv.port}", expand=False)
            out = c.predict({"tokens": _toks(2, seed=7)})
            assert out["logits.idx"].shape == (2, SEQ, 4)
            c.close()
            # a DEFAULT client must scatter-expand the rank-3 sparse
            # response transparently (regression: expand_outputs was
            # 2-D-only and crashed on sequence teachers)
            dense_c = TeacherClient(f"127.0.0.1:{srv.port}")
            toks = _toks(2, seed=7)
            dense = dense_c.predict({"tokens": toks})["logits"]
            assert dense.shape == (2, SEQ, VOCAB)
            ref = self._dense_ref(toks)
            ref_idx = np.argsort(-ref, axis=-1)[..., :4]
            np.testing.assert_allclose(
                np.take_along_axis(dense, ref_idx, axis=-1),
                np.take_along_axis(ref, ref_idx, axis=-1), atol=2e-3)
            dense_c.close()


def test_parse_local_mesh():
    mesh = parse_local_mesh("dp=4, tp=2")
    assert dict(mesh.shape) == {"dp": 4, "tp": 2}


def test_cli_local_mesh_builder_serves_dp_sharded():
    """The teacher CLI's --local-mesh flavor: zoo model, replicated
    params, dp-sharded batch over all local devices."""
    from edl_tpu.distill.teacher_server import _build_model_predict
    predict, meta = _build_model_predict("mlp", 10, "", "image", "logits",
                                         (8, 8, 1), "float32",
                                         serve_topk=0, local_mesh="dp=8")
    assert meta is None
    out = predict({"image": np.zeros((6, 8, 8, 1), np.float32)})
    assert out["logits"].shape == (6, 10)
