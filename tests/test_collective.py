"""Elastic launcher components on InMemStore (no processes, no network).

Mirrors the reference's WIP register/launch test intent
(register_test.py env fixture, SURVEY.md §4) with the working machinery.
"""

import threading
import time

import pytest

from edl_tpu.collective import barrier as bar
from edl_tpu.collective import register as reg
from edl_tpu.collective.cluster import Cluster, Pod, form_cluster
from edl_tpu.collective.job_env import JobEnv, TrainerEnv, trainer_environ
from edl_tpu.collective.watcher import ClusterWatcher
from edl_tpu.coord.store import InMemStore

JOB = "testjob"


def make_pod(i: int, **kw) -> Pod:
    kw.setdefault("addr", "127.0.0.1")
    kw.setdefault("port", 20000 + i)
    return Pod(pod_id=f"pod{i}", **kw)


def test_cluster_round_trip_and_ranks():
    pods = [make_pod(2, claimed_rank=7), make_pod(1, claimed_rank=3)]
    c = form_cluster(JOB, 1, pods)
    assert [p.pod_id for p in c.pods] == ["pod1", "pod2"]  # by claimed rank
    assert [p.rank for p in c.pods] == [0, 1]              # dense
    c2 = Cluster.from_json(c.to_json())
    assert c2.pod_ids() == {"pod1", "pod2"}
    assert c2.rank_of("pod2") == 1
    assert c2.coordinator == "127.0.0.1:20001"
    assert c2.same_membership(c)


def test_rank_claim_smallest_free_slot():
    store = InMemStore()
    r0 = reg.PodRegister(store, JOB, make_pod(0), ttl=5.0)
    r1 = reg.PodRegister(store, JOB, make_pod(1), ttl=5.0)
    assert r0.claim() == 0
    assert r1.claim() == 1
    r0.release()
    r2 = reg.PodRegister(store, JOB, make_pod(2), ttl=5.0)
    assert r2.claim() == 0  # hole filled
    for r in (r1, r2):
        r.release()


def test_rank_claim_concurrent_unique():
    store = InMemStore()
    results, regs = [], []

    def claim(i):
        r = reg.PodRegister(store, JOB, make_pod(i), ttl=5.0)
        results.append(r.claim())
        regs.append(r)

    threads = [threading.Thread(target=claim, args=(i,)) for i in range(6)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert sorted(results) == list(range(6))
    [r.release() for r in regs]


def test_claim_expires_on_lease_timeout():
    store = InMemStore()
    r0 = reg.PodRegister(store, JOB, make_pod(0), ttl=0.3)
    r0.claim()
    r0._keeper.stop(revoke=False)  # simulate pod death (no keepalive)
    time.sleep(0.7)
    pods, _ = reg.live_pods(store, JOB)
    assert pods == []


def test_barrier_three_pods_one_leader():
    store = InMemStore()
    regs = []
    for i in range(3):
        r = reg.PodRegister(store, JOB, make_pod(i), ttl=5.0)
        r.claim()
        regs.append(r)
    out = {}

    def wait(i):
        out[i] = bar.cluster_barrier(store, JOB, f"pod{i}",
                                     stable_secs=0.2, timeout=10.0)

    threads = [threading.Thread(target=wait, args=(i,)) for i in range(3)]
    [t.start() for t in threads]
    [t.join(15.0) for t in threads]
    assert len(out) == 3
    versions = {c.version for c in out.values()}
    assert versions == {1}
    assert all(c.world_size == 3 for c in out.values())
    ranks = sorted(out[i].rank_of(f"pod{i}") for i in range(3))
    assert ranks == [0, 1, 2]
    [r.release() for r in regs]


def test_barrier_resize_bumps_version():
    store = InMemStore()
    regs = [reg.PodRegister(store, JOB, make_pod(i), ttl=5.0)
            for i in range(2)]
    [r.claim() for r in regs]
    c1 = bar.cluster_barrier(store, JOB, "pod0", stable_secs=0.1,
                             timeout=10.0)
    assert c1.version == 1 and c1.world_size == 2

    regs[1].release()  # pod1 departs
    c2 = bar.cluster_barrier(store, JOB, "pod0", after_version=c1.version,
                             stable_secs=0.1, timeout=10.0)
    assert c2.version == 2
    assert c2.pod_ids() == {"pod0"}
    assert c2.rank_of("pod0") == 0
    regs[0].release()


def test_barrier_waits_for_min_nodes():
    store = InMemStore()
    r = reg.PodRegister(store, JOB, make_pod(0), ttl=5.0)
    r.claim()
    with pytest.raises(Exception):
        bar.cluster_barrier(store, JOB, "pod0", min_nodes=2,
                            stable_secs=0.1, timeout=1.0)
    r.release()


def test_watcher_fires_on_change():
    store = InMemStore()
    regs = [reg.PodRegister(store, JOB, make_pod(i), ttl=5.0)
            for i in range(2)]
    [r.claim() for r in regs]
    cluster = bar.cluster_barrier(store, JOB, "pod0", stable_secs=0.1,
                                  timeout=10.0)
    w = ClusterWatcher(store, cluster, interval=0.1).start()
    assert not w.changed.wait(0.4)
    regs[1].release()
    assert w.changed.wait(3.0)
    w.stop()
    regs[0].release()


def test_watcher_fires_on_new_generation_without_membership_blip():
    # A pod that crashes and rejoins between two watcher polls produces no
    # membership diff; peers must still notice the new cluster generation.
    store = InMemStore()
    regs = [reg.PodRegister(store, JOB, make_pod(i), ttl=5.0)
            for i in range(2)]
    [r.claim() for r in regs]
    cluster = bar.cluster_barrier(store, JOB, "pod0", stable_secs=0.1,
                                  timeout=10.0)
    w = ClusterWatcher(store, cluster, interval=0.1).start()
    assert not w.changed.wait(0.4)
    # Same membership, newer version published (as the rejoined pod's
    # barrier would do).
    pods, _ = reg.live_pods(store, JOB)
    nxt = form_cluster(JOB, cluster.version + 1, pods)
    store.put(reg.cluster_key(JOB), nxt.to_json())
    assert w.changed.wait(3.0)
    w.stop()
    [r.release() for r in regs]


def test_trainer_environ_round_trip(monkeypatch):
    pods = [make_pod(0, claimed_rank=0, n_devices=4),
            make_pod(1, claimed_rank=1, n_devices=4)]
    cluster = form_cluster(JOB, 3, pods)
    job = JobEnv(job_id=JOB, checkpoint_path="/tmp/ckpt",
                 store_endpoints="127.0.0.1:2379")
    env = trainer_environ(cluster, "pod1", job)
    for k, v in env.items():
        if k.startswith("EDL_TPU_"):
            monkeypatch.setenv(k, v)
    te = TrainerEnv.from_environ()
    assert te.rank == 1 and te.world_size == 2
    assert te.coordinator == "127.0.0.1:20000"
    assert te.cluster_version == 3
    assert te.cluster.n_devices == 8
    assert not te.is_leader
    assert te.checkpoint_path == "/tmp/ckpt"


def test_job_env_nodes_range(monkeypatch):
    monkeypatch.setenv("EDL_TPU_NODES_RANGE", "2:8")
    job = JobEnv.from_environ()
    assert (job.min_nodes, job.max_nodes) == (2, 8)
    assert job.pod_id  # auto-generated
