"""Checkpoint manager: versioning, atomicity, GC, restore."""

import os

import jax.numpy as jnp
import optax
import pytest

from edl_tpu.train.checkpoint import CheckpointManager
from edl_tpu.train.state import TrainState, TrainStatus


def _state(value: float) -> TrainState:
    params = {"w": jnp.full((4,), value), "b": jnp.zeros((2, 2))}
    tx = optax.sgd(0.1)
    return TrainState.create(apply_fn=lambda *a: None, params=params, tx=tx)


def test_save_restore_roundtrip(tmp_path):
    mgr = CheckpointManager(str(tmp_path), process_index=0)
    state = _state(1.5)
    v = mgr.save(state, TrainStatus(epoch=3, step=120, world_size=8))
    assert v == 0
    restored, status = mgr.restore(_state(0.0))
    assert float(restored.params["w"][0]) == 1.5
    assert status.epoch == 3 and status.step == 120 and status.world_size == 8


def test_versions_increase_and_gc(tmp_path):
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2, process_index=0)
    for i in range(5):
        mgr.save(_state(float(i)), TrainStatus(epoch=i))
    assert mgr.versions() == [3, 4]
    restored, status = mgr.restore(_state(0.0))
    assert status.epoch == 4
    # restore a specific older version
    restored, status = mgr.restore(_state(0.0), version=3)
    assert status.epoch == 3


def test_no_checkpoint_returns_none(tmp_path):
    mgr = CheckpointManager(str(tmp_path), process_index=0)
    assert mgr.restore(_state(0.0)) is None
    assert mgr.latest_version() is None


def test_nonzero_rank_does_not_write(tmp_path):
    mgr = CheckpointManager(str(tmp_path), process_index=1)
    assert mgr.save(_state(1.0), TrainStatus(epoch=0)) is None
    assert mgr.versions() == []


def test_crashed_partial_write_invisible(tmp_path):
    mgr = CheckpointManager(str(tmp_path), process_index=0)
    mgr.save(_state(1.0), TrainStatus(epoch=0))
    # simulate a crash mid-save: orphan temp dir with partial contents
    orphan = tmp_path / ".tmp-ckpt-dead"
    orphan.mkdir()
    (orphan / "state.msgpack").write_bytes(b"garbage")
    restored, status = mgr.restore(_state(0.0))
    assert status.epoch == 0  # only the complete version is visible
    mgr.save(_state(2.0), TrainStatus(epoch=1))  # gc cleans the orphan
    assert not orphan.exists()


def test_restore_raw_ignores_optimizer_structure(tmp_path):
    """A serving process restores params from a trainer's checkpoint
    without knowing (or matching) the trainer's optimizer chain — the
    structure-bound restore() rejects the opt_state mismatch
    (regression: teacher_server --params died on ValueError)."""
    import jax
    model_params = {"w": jnp.ones((4,)) * 3.0}
    trainer_state = TrainState.create(
        apply_fn=lambda *a: None, params=model_params,
        tx=optax.chain(optax.add_decayed_weights(1e-4),
                       optax.sgd(0.1, momentum=0.9)))
    mgr = CheckpointManager(str(tmp_path), process_index=0)
    mgr.save(trainer_state, TrainStatus(epoch=5, step=50, world_size=2))

    # a different-optimizer target makes restore() raise...
    server_state = TrainState.create(apply_fn=lambda *a: None,
                                     params={"w": jnp.zeros((4,))},
                                     tx=optax.identity())
    with pytest.raises(Exception):
        mgr.restore(server_state)
    # ...restore_raw serves the params regardless
    raw, status = mgr.restore_raw()
    assert status.epoch == 5
    assert float(jax.tree.leaves(raw["params"]["w"])[0][0]) == 3.0
    server_state = server_state.replace(params=raw["params"])
    assert float(server_state.params["w"][0]) == 3.0


def test_restore_raw_none_when_empty(tmp_path):
    assert CheckpointManager(str(tmp_path),
                             process_index=0).restore_raw() is None


def test_corrupt_meta_raises(tmp_path):
    mgr = CheckpointManager(str(tmp_path), process_index=0)
    mgr.save(_state(1.0), TrainStatus(epoch=0))
    path = os.path.join(str(tmp_path), "ckpt-0", "meta.json")
    with open(path, "w") as f:
        f.write("{not json")
    with pytest.raises(Exception):
        mgr.restore(_state(0.0))
