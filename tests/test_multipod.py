"""One-world multi-pod data parallelism: the flagship capability proof.

The reference's collective mode forms ONE NCCL world across trainers
(train_with_fleet.py:376-377 `fleet.init(is_collective=True)` over the
launcher's PADDLE_TRAINER_* env); here N processes form one jax.distributed
world (gloo CPU collectives stand in for ICI) and a global-mesh jitted step
carries the gradient all-reduce. Tests assert:

  1. loss/param parity: a 2-process world trains to the SAME parameters as
     a single-process run on the same global batch stream;
  2. elastic resize: a world trained 2-process, then resumed 1-process from
     its checkpoint, matches an unresized 1-process run end-to-end;
  3. the full launcher path: two launchers -> one 2-pod world -> pod kill
     -> stop-resume into a 1-pod world -> completion with parity.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow  # multi-process jax.distributed worlds

from edl_tpu.utils import net

DEMO = "edl_tpu.examples.multipod_demo"


def cpu_env(extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU tunnel
    env.update({"JAX_PLATFORMS": "cpu", "JAX_NUM_CPU_DEVICES": "1"})
    env.update(extra or {})
    return env


def run_world(tmp_path, tag, world, epochs=3, ckpt=None, steps=8,
              global_batch=16, timeout=120):
    """Spawn `world` trainer processes forming one world; return rank-0 out."""
    port = net.free_port()
    out_path = tmp_path / f"{tag}.json"
    procs = []
    for rank in range(world):
        env = cpu_env({
            "EDL_TPU_RANK": str(rank),
            "EDL_TPU_WORLD_SIZE": str(world),
            "EDL_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "EDL_TPU_CHECKPOINT_PATH": str(ckpt) if ckpt else "",
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-m", DEMO, "--epochs", str(epochs),
             "--steps-per-epoch", str(steps),
             "--global-batch", str(global_batch), "--out", str(out_path)],
            env=env, stdout=open(tmp_path / f"{tag}.r{rank}.log", "wb"),
            stderr=subprocess.STDOUT))
    deadline = time.time() + timeout
    try:
        for rank, p in enumerate(procs):
            rc = p.wait(timeout=max(1.0, deadline - time.time()))
            assert rc == 0, (tmp_path / f"{tag}.r{rank}.log").read_text()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    with open(out_path) as f:
        return json.load(f)


def test_two_process_parity_with_single(tmp_path):
    solo = run_world(tmp_path, "solo", world=1)
    duo = run_world(tmp_path, "duo", world=2)
    assert duo["world"] == 2 and solo["world"] == 1
    assert duo["step"] == solo["step"]  # same number of global steps
    assert abs(duo["w"] - solo["w"]) < 1e-5, (solo, duo)
    assert abs(duo["b"] - solo["b"]) < 1e-5, (solo, duo)
    # and it moved decisively toward the generating function (w*=3, b*=-1.5)
    assert solo["w"] > 2.0 and solo["b"] < -1.0


def test_resize_resume_parity(tmp_path):
    # Train epochs 0-1 in a 2-process world, checkpointing...
    first = run_world(tmp_path, "phase1", world=2, epochs=2,
                      ckpt=tmp_path / "ckpt")
    assert first["epoch"] == 1
    # ...then "resize" to a 1-process world resuming the same checkpoint.
    second = run_world(tmp_path, "phase2", world=1, epochs=4,
                       ckpt=tmp_path / "ckpt")
    assert second["epoch"] == 3
    # An unresized 1-process run over all 4 epochs must land on the same
    # parameters (global-batch-deterministic data + epoch-atomic resume).
    straight = run_world(tmp_path, "straight", world=1, epochs=4)
    assert abs(second["w"] - straight["w"]) < 1e-5, (second, straight)
    assert abs(second["b"] - straight["b"]) < 1e-5, (second, straight)


@pytest.fixture
def store_server(tmp_path):
    from edl_tpu.coord.client import StoreClient
    port = net.free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.coord.server", "--port", str(port)],
        env=cpu_env(), stdout=open(tmp_path / "store.log", "wb"),
        stderr=subprocess.STDOUT)
    client = StoreClient(f"127.0.0.1:{port}")
    deadline = time.time() + 15
    while time.time() < deadline:
        if client.ping():
            break
        time.sleep(0.2)
    else:
        proc.kill()
        pytest.fail("store server never came up")
    yield f"127.0.0.1:{port}", client
    proc.terminate()
    proc.wait(timeout=5)


def start_launcher(store_addr, tmp_path, name, epochs, step_time):
    env = cpu_env({
        "EDL_TPU_JOB_ID": "mpjob",
        "EDL_TPU_STORE_ENDPOINTS": store_addr,
        "EDL_TPU_POD_ID": name,
        "EDL_TPU_CHECKPOINT_PATH": str(tmp_path / "ckpt"),
        "EDL_TPU_LOG_DIR": str(tmp_path / f"log_{name}"),
        "EDL_TPU_LEASE_TTL": "2.0",
        "EDL_TPU_BARRIER_STABLE": "0.5",
        "EDL_TPU_NODES_RANGE": "1:4",
    })
    return subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.collective.launch", "--",
         sys.executable, "-m", DEMO,
         "--epochs", str(epochs), "--steps-per-epoch", "6",
         "--global-batch", "16", "--step-time", str(step_time),
         "--out", str(tmp_path / "launched.json")],
        env=env, stdout=open(tmp_path / f"{name}.log", "wb"),
        stderr=subprocess.STDOUT, start_new_session=True)


def test_launcher_forms_one_world_and_survives_resize(store_server, tmp_path):
    from edl_tpu.collective.barrier import read_cluster
    store_addr, client = store_server
    # step_time sized so the kill (after ckpt + first utilization poll)
    # lands MID-training: the resized world must still have epochs left
    # to publish fresh utilization from. The publisher fires at TrainLoop
    # log points — multipod_demo logs once per epoch (~4s here).
    a = start_launcher(store_addr, tmp_path, "podA", epochs=5, step_time=0.7)
    b = start_launcher(store_addr, tmp_path, "podB", epochs=5, step_time=0.7)
    try:
        def two_up():
            c = read_cluster(client, "mpjob")
            return c is not None and c.world_size == 2
        deadline = time.time() + 90
        while time.time() < deadline and not two_up():
            time.sleep(0.3)
        assert two_up(), "2-pod cluster never formed"

        # Wait until the 2-pod world has actually trained (a checkpoint
        # exists), so the resize exercises restore-on-new-world.
        ckpt = tmp_path / "ckpt"
        deadline = time.time() + 120
        while time.time() < deadline and not (
                ckpt.is_dir() and any(p.name.startswith("ckpt-")
                                      for p in ckpt.iterdir())):
            time.sleep(0.3)
        assert ckpt.is_dir() and any(p.name.startswith("ckpt-")
                                     for p in ckpt.iterdir()), \
            "no checkpoint from the 2-pod world"

        # Trainer utilization is published into leased /mpjob/util/
        # records (TrainLoop auto-installs the publisher under the
        # launcher) and surfaced by the Collector — the scheduler data
        # path (reference discovery/register.py:36-40 info field).
        from edl_tpu.coord.collector import Collector
        deadline = time.time() + 90
        util_docs = {}
        while time.time() < deadline and not util_docs:
            snap = Collector(client, job_id="mpjob").snapshot()
            util_docs = {p["pod_id"]: p["utilization"]
                         for p in snap["job"]["pods"]
                         if p["utilization"]}
            time.sleep(0.3)
        assert util_docs, "no trainer utilization ever published"
        doc = next(iter(util_docs.values()))
        assert doc["samples_seen"] > 0 and doc["step"] > 0

        t_kill = time.time()
        os.killpg(os.getpgid(b.pid), signal.SIGKILL)  # pod failure

        def resized():
            c = read_cluster(client, "mpjob")
            return (c is not None and c.world_size == 1
                    and c.pod_ids() == {"podA"})
        deadline = time.time() + 90
        while time.time() < deadline and not resized():
            time.sleep(0.3)
        assert resized(), "no stop-resume into 1-pod world"

        # The RESIZED 1-pod world keeps publishing fresh utilization
        # (records survive the resize). Freshness = publish timestamp
        # after the kill; samples_seen restores from the checkpoint so
        # it is NOT monotonic across the resize.
        deadline = time.time() + 120
        fresh = None
        while time.time() < deadline and fresh is None \
                and a.poll() is None:
            snap = Collector(client, job_id="mpjob").snapshot()
            for p in snap["job"]["pods"]:
                u = p["utilization"]
                if p["pod_id"] == "podA" and u and u["ts"] > t_kill:
                    fresh = u
            time.sleep(0.2)
        assert fresh is not None, \
            "resized world published no fresh utilization"

        rc = a.wait(timeout=240)
        assert rc == 0, open(tmp_path / "podA.log").read()
        assert client.get("/mpjob/complete") is not None

        with open(tmp_path / "launched.json") as f:
            result = json.load(f)
        assert result["epoch"] == 4 and result["world"] == 1
        # Parity with an unresized single-process run of the same recipe.
        straight = run_world(tmp_path, "straight", world=1, epochs=5,
                             steps=6, global_batch=16)
        assert abs(result["w"] - straight["w"]) < 1e-5, (result, straight)
        assert abs(result["b"] - straight["b"]) < 1e-5, (result, straight)

        # The 2-pod generation really ran one world: rank-0's log shows a
        # world of 2 and rank-1 joined it.
        logs = "".join(
            open(tmp_path / f"log_{n}" / f).read()
            for n in ("podA", "podB") if (tmp_path / f"log_{n}").is_dir()
            for f in os.listdir(tmp_path / f"log_{n}"))
        assert "world=2" in logs, "trainers never formed a 2-pod world"
    finally:
        for p in (a, b):
            if p.poll() is None:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
        subprocess.run(["pkill", "-9", "-f", DEMO], capture_output=True)
