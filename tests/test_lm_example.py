"""lm_train example: transformer pretraining over file-backed shards."""

import pytest

pytestmark = pytest.mark.slow  # LM trainer end-to-end epochs

import json

import numpy as np


class TestLmTrain:
    def test_end_to_end_learns_and_logs(self, tmp_path):
        from edl_tpu.examples.lm_train import main

        rc = main(["--data-dir", str(tmp_path / "d"), "--make-synthetic",
                   "2", "--rows-per-file", "256", "--vocab", "128",
                   "--seq-len", "64", "--d-model", "64", "--n-heads", "4",
                   "--n-layers", "1", "--d-ff", "128", "--epochs", "4",
                   "--batch-size", "32", "--lr", "3e-3",
                   "--ckpt-dir", str(tmp_path / "ckpt"),
                   "--benchmark-log", str(tmp_path / "blog")])
        assert rc == 0
        blog = json.load(open(tmp_path / "blog" / "log_0.json"))
        # markov task: ideal loss ln(8)=2.08, chance ln(128)=4.85 — the
        # model must be clearly below chance after 4 tiny epochs
        assert blog["final"]["eval_loss"] < 4.2, blog["final"]
        assert blog["final"]["tokens_per_sec"] > 0

    def test_sequence_parallel_mesh(self, tmp_path):
        """--mesh sp: ring attention over the 8-device sequence axis
        through the CLI (long-context mode)."""
        from edl_tpu.examples.lm_train import main

        rc = main(["--data-dir", str(tmp_path / "d"), "--make-synthetic",
                   "1", "--rows-per-file", "64", "--vocab", "64",
                   "--seq-len", "64", "--d-model", "32", "--n-heads", "2",
                   "--n-layers", "1", "--d-ff", "64", "--epochs", "1",
                   "--batch-size", "16", "--mesh", "sp"])
        assert rc == 0

    def test_resume(self, tmp_path):
        from edl_tpu.examples.lm_train import main

        common = ["--data-dir", str(tmp_path / "d"), "--rows-per-file",
                  "128", "--vocab", "64", "--seq-len", "32", "--d-model",
                  "32", "--n-heads", "2", "--n-layers", "1", "--d-ff",
                  "64", "--batch-size", "16",
                  "--ckpt-dir", str(tmp_path / "ckpt")]
        assert main(["--make-synthetic", "1", "--epochs", "1"]
                    + common) == 0
        assert main(["--epochs", "2"] + common) == 0  # resumes epoch 1
