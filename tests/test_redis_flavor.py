"""Redis discovery flavor (coord/resp.py + coord/redis_store.py).

The reference's second balancer flavor (C10-C14: redis TTL-hash registry
+ hand-rolled TCP server + registrar, `distill/redis/`) — here one Store
backend that the existing discovery stack runs over unchanged. Mirrors
the reference's test_redis_distill_reader.sh flow: registry + registrar
+ discovery server + DistillReader, all over the RESP store.
"""

import time

import numpy as np
import pytest

from edl_tpu.coord.redis_store import (EdlRedisError, RedisStore,
                                       connect_store)
from edl_tpu.coord.registry import ServiceRegistry
from edl_tpu.coord.resp import MiniRedis, RespClient, RespError
from edl_tpu.utils.exceptions import EdlLeaseExpired


@pytest.fixture()
def server():
    srv = MiniRedis().start()
    yield srv
    srv.stop()


@pytest.fixture()
def store(server):
    st = RedisStore(server.endpoint)
    yield st
    st.close()


class TestRespWire:
    def test_roundtrip_commands(self, server):
        c = RespClient(server.endpoint)
        assert c.command("PING") == "PONG"
        assert c.command("SET", "a", "1") == "OK"
        assert c.command("GET", "a") == "1"
        assert c.command("GET", "missing") is None
        assert c.command("INCR", "n") == 1
        assert c.command("INCR", "n") == 2
        assert c.command("DEL", "a", "n") == 2
        c.close()

    def test_set_nx(self, server):
        c = RespClient(server.endpoint)
        assert c.command("SET", "k", "v", "NX") == "OK"
        assert c.command("SET", "k", "w", "NX") is None
        assert c.command("GET", "k") == "v"
        c.close()

    def test_keys_glob_and_expiry(self, server):
        c = RespClient(server.endpoint)
        c.command("SET", "/svc/a", "1")
        c.command("SET", "/svc/b", "2")
        c.command("SET", "/other", "3")
        assert c.command("KEYS", "/svc/*") == ["/svc/a", "/svc/b"]
        assert c.command("PEXPIRE", "/svc/a", 30) == 1
        time.sleep(0.08)
        assert c.command("GET", "/svc/a") is None
        assert c.command("KEYS", "/svc/*") == ["/svc/b"]
        c.close()

    def test_set_replaces_set_key_and_nx_is_type_agnostic(self, server):
        c = RespClient(server.endpoint)
        # real redis's NX existence check is type-agnostic: a set key
        # blocks SET NX
        c.command("SADD", "k1", "m")
        assert c.command("SET", "k1", "v", "NX") is None
        # plain SET replaces a key of ANY type (the set is gone after)
        c.command("SADD", "k2", "m")
        assert c.command("SET", "k2", "v") == "OK"
        assert c.command("GET", "k2") == "v"
        assert c.command("KEYS", "k2") == ["k2"]  # listed exactly once
        c.close()

    def test_unknown_command_is_error(self, server):
        c = RespClient(server.endpoint)
        with pytest.raises(RespError):
            c.command("WHATEVER")
        # connection still usable after an -ERR reply
        assert c.command("PING") == "PONG"
        c.close()

    def test_garbage_bytes_drop_connection_not_server(self, server):
        import socket

        s = socket.create_connection(("127.0.0.1", server.port), timeout=2)
        s.sendall(b"not resp at all\r\n")
        s.close()
        c = RespClient(server.endpoint)
        assert c.command("PING") == "PONG"  # server survived
        c.close()


class TestRedisStore:
    def test_put_get_revisions_monotonic(self, store):
        r1 = store.put("/k1", "a")
        r2 = store.put("/k2", "b")
        assert r2 > r1
        rec = store.get("/k1")
        assert rec.value == "a" and rec.revision == r1
        assert store.get("/nope") is None

    def test_get_prefix_sorted_and_rev(self, store):
        store.put("/p/b", "2")
        store.put("/p/a", "1")
        store.put("/q/x", "3")
        recs, rev = store.get_prefix("/p/")
        assert [r.key for r in recs] == ["/p/a", "/p/b"]
        assert rev >= max(r.revision for r in recs)

    def test_put_if_absent(self, store):
        assert store.put_if_absent("/once", "first")
        assert not store.put_if_absent("/once", "second")
        assert store.get("/once").value == "first"

    def test_delete_and_prefix(self, store):
        store.put("/d/a", "1")
        store.put("/d/b", "2")
        assert store.delete("/d/a")
        assert not store.delete("/d/a")
        assert store.delete_prefix("/d/") == 1

    def test_lease_expiry_removes_keys(self, store):
        lease = store.lease_grant(0.08)
        store.put("/leased", "v", lease=lease)
        assert store.get("/leased") is not None
        time.sleep(0.15)
        assert store.get("/leased") is None
        assert not store.lease_keepalive(lease)

    def test_lease_keepalive_extends(self, store):
        lease = store.lease_grant(0.15)
        store.put("/ka", "v", lease=lease)
        for _ in range(4):
            time.sleep(0.07)
            assert store.lease_keepalive(lease)
        assert store.get("/ka") is not None  # outlived 2x its ttl

    def test_key_written_late_in_lease_expires_with_lease(self, store):
        """A key SET near the END of a lease window must inherit the
        lease's REMAINING ttl, not a fresh full one — a dead teacher
        must not stay routable past its lease."""
        lease = store.lease_grant(3.0)
        time.sleep(1.8)  # most of the window gone, wide margin left
        store.put("/late", "v", lease=lease)
        assert store.get("/late") is not None
        time.sleep(1.5)  # past the lease deadline, < full ttl from SET
        assert not store.lease_keepalive(lease)  # lease itself is gone
        assert store.get("/late") is None  # ...and so is the late key

    def test_lease_revoke_deletes(self, store):
        lease = store.lease_grant(5.0)
        store.put("/r1", "a", lease=lease)
        store.put("/r2", "b", lease=lease)
        assert store.lease_revoke(lease)
        assert store.get("/r1") is None and store.get("/r2") is None

    def test_put_with_dead_lease_raises_and_writes_nothing(self, store):
        lease = store.lease_grant(0.05)
        time.sleep(0.12)
        with pytest.raises(EdlLeaseExpired):
            store.put("/x", "v", lease=lease)
        # the lease is validated BEFORE the SET: a dead teacher's key
        # must not be resurrected TTL-less (it would stay routable
        # forever)
        assert store.get("/x") is None

    def test_prefix_with_glob_chars_in_service_name(self, store):
        # service names containing glob metacharacters must round-trip
        # (escape semantics must agree between client and server)
        store.put("/svc[1]/nodes/a", "1")
        store.put("/svc[1]/nodes/b", "2")
        recs, _ = store.get_prefix("/svc[1]/nodes/")
        assert [r.key for r in recs] == ["/svc[1]/nodes/a",
                                        "/svc[1]/nodes/b"]

    def test_client_recovers_after_transport_error(self, server, store):
        # sabotage the socket mid-stream, then verify the next command
        # reconnects instead of reading a stale reply
        store._client._sock.close()
        assert store.ping()  # reconnected transparently
        store.put("/after", "ok")
        assert store.get("/after").value == "ok"

    def test_cas_single_writer_semantics(self, store):
        assert store.compare_and_swap("/c", None, "v1")  # absent -> set
        assert not store.compare_and_swap("/c", "wrong", "v2")
        assert store.compare_and_swap("/c", "v1", "v2")
        assert store.get("/c").value == "v2"

    def test_cas_rebinds_lease(self, store):
        """The Registration owned-key reclaim path: cas with a fresh
        lease after the old one lapsed — the key must carry the NEW
        lease's ttl."""
        l1 = store.lease_grant(0.1)
        store.put("/own", "tok", lease=l1)
        time.sleep(0.05)
        l2 = store.lease_grant(0.5)
        assert store.compare_and_swap("/own", "tok", "tok2", lease=l2)
        time.sleep(0.2)  # old lease long dead; new one keeps it alive
        assert store.lease_keepalive(l2)
        assert store.get("/own").value == "tok2"

    def test_overwrite_detaches_old_lease(self, store):
        """Re-putting a key lease-less must detach it: the old lease's
        expiry/revoke must no longer touch it (InMemStore semantics)."""
        lease = store.lease_grant(0.2)
        store.put("/det", "a", lease=lease)
        store.put("/det", "b")  # now persistent
        store.lease_revoke(lease)
        assert store.get("/det").value == "b"  # revoke didn't delete it
        time.sleep(0.3)
        assert store.get("/det") is not None  # no stale TTL either

    def test_events_since_out_of_scope(self, store):
        with pytest.raises(EdlRedisError):
            store.events_since(0)

    def test_connect_store_scheme(self, server):
        st = connect_store(f"redis://{server.endpoint}")
        assert isinstance(st, RedisStore)
        assert st.ping()
        st.close()


class TestRegistryOverRedis:
    def test_register_heartbeat_expiry(self, store):
        reg = ServiceRegistry(store, root="edl_distill")
        registration = reg.register("svc", "10.0.0.1:9000",
                                    info="{}", ttl=0.4)
        try:
            metas = reg.get_service("svc")
            assert [m.server for m in metas] == ["10.0.0.1:9000"]
            time.sleep(0.9)  # heartbeats must be keeping it alive
            assert [m.server for m in reg.get_service("svc")] \
                == ["10.0.0.1:9000"]
        finally:
            registration.stop()
        deadline = time.time() + 3
        while time.time() < deadline and reg.get_service("svc"):
            time.sleep(0.05)
        assert reg.get_service("svc") == []  # lease lapsed after stop

    def test_update_info_visible(self, store):
        reg = ServiceRegistry(store, root="edl_distill")
        registration = reg.register("svc", "t:1", info="old", ttl=2.0)
        try:
            registration.update_info("new")
            deadline = time.time() + 2
            while time.time() < deadline:
                metas = reg.get_service("svc")
                if metas and metas[0].info == "new":
                    break
                time.sleep(0.05)
            assert reg.get_service("svc")[0].info == "new"
        finally:
            registration.stop()


def test_distill_stack_over_redis(server):
    """The reference's test_redis_distill_reader flow: teachers register
    in the redis registry, the discovery server balances them, a
    DistillReader consumes through dynamic discovery — all over RESP."""
    from edl_tpu.distill.discovery_server import DiscoveryServer
    from edl_tpu.distill.reader import DistillReader
    from edl_tpu.distill.registrar import TeacherRegistrar
    from edl_tpu.distill.teacher_server import TeacherServer

    def predict(feeds):
        return {"logits": feeds["x"] * 2.0}

    store = RedisStore(server.endpoint)
    teacher = TeacherServer(predict, host="127.0.0.1").start()
    endpoint = f"127.0.0.1:{teacher.port}"
    registrar = TeacherRegistrar(store, "svc", endpoint, ttl=1.0,
                                 probe_timeout=10.0, probe_interval=0.05)
    registrar.start()
    disco = DiscoveryServer(RedisStore(server.endpoint), port=0,
                            host="127.0.0.1", tick_interval=0.1,
                            client_ttl=10.0).start()
    rng = np.random.default_rng(0)
    batches = [{"x": rng.normal(size=(8, 3)).astype(np.float32)}
               for _ in range(6)]
    dr = DistillReader(lambda: iter(batches), feeds=["x"],
                       predicts=["logits"], discovery=disco.endpoint,
                       service="svc", teacher_batch_size=4,
                       manage_interval=0.05)
    try:
        out = list(dr())
        assert len(out) == 6
        for got, fed in zip(out, batches):
            np.testing.assert_allclose(got["logits"], fed["x"] * 2.0,
                                       rtol=1e-6)
    finally:
        dr.close()
        disco.stop()
        registrar.stop()
        teacher.stop()
        store.close()
