"""Multi-process shared-memory loader (data/mp_loader.py + shm_ring.py).

The acceptance surface of the mp input plane:
- bit-identical batch streams across ALL execution modes (inline,
  decode_threads thread pool, num_workers process pool),
- replay-after-restart from a mid-epoch cursor,
- a SIGKILL'd worker loses nothing (batches arrive in order, exactly
  once) and a poisoned sample surfaces the worker traceback,
- every shm segment is unlinked on close / GC / TrainLoop teardown
  (the /dev/shm leak check).

No pytest-timeout in the image: hang-prone paths run under a SIGALRM
`deadline()` so a wedged queue fails the test instead of the suite.
"""

import contextlib
import gc
import os
import signal
import numpy as np
import pytest

from edl_tpu.data.pipeline import (ArraySource, DataLoader,
                                   prefetch_to_device, random_crop,
                                   random_flip_lr)
from edl_tpu.utils.exceptions import EdlDataError


def shm_segments() -> set:
    # rings are always created by the parent, so OUR segments carry this
    # process's pid in the name — scoping the leak check to them keeps
    # it meaningful when other edl processes run on the host
    prefix = f"edl_mp_{os.getpid()}_"
    try:
        return {n for n in os.listdir("/dev/shm")
                if n.startswith(prefix)}
    except FileNotFoundError:  # pragma: no cover - non-Linux
        return set()


@contextlib.contextmanager
def deadline(seconds: int):
    """Fail (don't hang) if the block exceeds `seconds`."""

    def fire(signum, frame):
        raise TimeoutError(f"test exceeded {seconds}s deadline")

    old = signal.signal(signal.SIGALRM, fire)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def array_source(n=64, hw=12):
    rng = np.random.default_rng(0)
    return ArraySource({
        "image": rng.integers(0, 256, size=(n, hw, hw, 3), dtype=np.uint8),
        "label": np.arange(n, dtype=np.int32)})


AUG = (random_flip_lr, lambda b, r: random_crop(b, r, pad=2))


def copy_stream(it):
    return [{k: np.array(v) for k, v in b.items()} for b in it]


def assert_streams_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert set(x) == set(y)
        for k in x:
            np.testing.assert_array_equal(x[k], y[k])


@pytest.fixture(scope="module")
def jpeg_dir(tmp_path_factory):
    from edl_tpu.data.image import make_synthetic_jpeg_dataset
    d = tmp_path_factory.mktemp("mp_jpegs")
    list_file = make_synthetic_jpeg_dataset(str(d), 24, classes=5,
                                            hw=(60, 80), seed=7)
    return str(d), list_file


def jpeg_loader(jpeg_dir, **kw):
    from edl_tpu.data.image import JpegFileListSource, train_image_transform
    root, list_file = jpeg_dir
    return DataLoader(JpegFileListSource(list_file, root=root), 4, seed=5,
                      sample_transforms=(train_image_transform(16),), **kw)


class TestDeterminismAcrossModes:
    """One contract, three executors: the batch stream is a pure
    function of (seed, epoch, rank, step) whatever runs it."""

    @pytest.mark.parametrize("mode", [dict(decode_threads=2),
                                      dict(num_workers=1),
                                      dict(num_workers=3)])
    def test_jpeg_plane_bit_identical(self, jpeg_dir, mode):
        with deadline(120):
            with jpeg_loader(jpeg_dir) as inline:
                want = copy_stream(inline.epoch(3))
            with jpeg_loader(jpeg_dir, **mode) as ld:
                got = copy_stream(ld.epoch(3))
        assert_streams_equal(want, got)
        assert not shm_segments()

    @pytest.mark.parametrize("workers", [1, 2])
    def test_batch_transform_plane_bit_identical(self, workers):
        src = array_source()
        with deadline(120):
            with DataLoader(src, 4, seed=3, transforms=AUG) as inline:
                want = copy_stream(inline.epoch(1))
            with DataLoader(src, 4, seed=3, transforms=AUG,
                            num_workers=workers) as ld:
                got = copy_stream(ld.epoch(1))
        assert_streams_equal(want, got)
        assert not shm_segments()

    def test_epoch_reuses_pool_and_streams_differ_by_epoch(self):
        src = array_source()
        with deadline(120), DataLoader(src, 4, seed=3, transforms=AUG,
                                       num_workers=2) as ld:
            a = copy_stream(ld.epoch(0))
            pool = ld._mp_pool
            b = copy_stream(ld.epoch(1))
            assert ld._mp_pool is pool  # workers survive epochs
        assert not np.array_equal(a[0]["image"], b[0]["image"])
        assert not shm_segments()


class TestReplayAfterRestart:
    def test_mid_epoch_cursor_replays_remainder(self):
        src = array_source()
        with deadline(120):
            with DataLoader(src, 4, seed=9, transforms=AUG) as inline:
                full = copy_stream(inline.epoch(2))
            # first process consumes 3 batches then "dies"
            with DataLoader(src, 4, seed=9, transforms=AUG,
                            num_workers=2) as before:
                it = before.epoch(2)
                head = [next(it) for _ in range(3)]
                head = [{k: np.array(v) for k, v in b.items()}
                        for b in head]
                it.close()  # mid-epoch abandon (stop-resume)
            # restarted process resumes from the step_in_epoch cursor
            with DataLoader(src, 4, seed=9, transforms=AUG,
                            num_workers=2) as after:
                tail = copy_stream(after.epoch(2, start_step=3))
        assert_streams_equal(head + tail, full)
        assert not shm_segments()

    def test_skip_by_iteration_matches_cursor(self, jpeg_dir):
        """TrainLoop skips by consuming; epoch(start_step=) must land on
        the same stream (same parent-side seed draws either way)."""
        with deadline(120), jpeg_loader(jpeg_dir, num_workers=2) as ld:
            it = ld.epoch(1)
            for _ in range(2):
                next(it)
            want = copy_stream(it)
            got = copy_stream(ld.epoch(1, start_step=2))
        assert_streams_equal(want, got)
        assert not shm_segments()


class TestWorkerDeath:
    def test_sigkill_mid_epoch_exactly_once_in_order(self):
        src = array_source(n=96)
        with deadline(120):
            with DataLoader(src, 4, seed=3, transforms=AUG) as inline:
                want = copy_stream(inline.epoch(7))
            with DataLoader(src, 4, seed=3, transforms=AUG,
                            num_workers=2) as ld:
                got = copy_stream(ld.epoch(0))  # builds the pool
                it = ld.epoch(7)
                got = [{k: np.array(v) for k, v in next(it).items()}]
                os.kill(ld._mp_pool._procs[0].pid, signal.SIGKILL)
                got += copy_stream(it)
        assert_streams_equal(want, got)  # nothing lost, nothing doubled
        assert not shm_segments()

    def test_all_workers_dead_raises_instead_of_hanging(self):
        src = array_source()
        with deadline(60):
            with DataLoader(src, 4, seed=3, num_workers=1) as ld:
                list(ld.epoch(0))  # pool up
                it = ld.epoch(1)
                next(it)
                os.kill(ld._mp_pool._procs[0].pid, signal.SIGKILL)
                with pytest.raises(EdlDataError, match="died"):
                    list(it)
        assert not shm_segments()

    def test_poisoned_sample_surfaces_worker_traceback(self):
        src = array_source()

        def poison(batch, rng):
            if (batch["label"] == 13).any():
                raise ValueError("pixel 13 is cursed")
            return batch

        with deadline(60):
            with DataLoader(src, 4, seed=3, transforms=(poison,),
                            num_workers=2) as ld:
                with pytest.raises(EdlDataError) as err:
                    list(ld.epoch(0))
        assert "pixel 13 is cursed" in str(err.value)
        assert "Traceback" in str(err.value)  # the WORKER's stack
        assert not shm_segments()


class TestLifecycle:
    def test_close_is_idempotent_and_loader_reusable(self):
        src = array_source()
        with deadline(120):
            ld = DataLoader(src, 4, seed=0, num_workers=1)
            a = copy_stream(ld.epoch(0))
            ld.close()
            ld.close()
            assert not shm_segments()
            b = copy_stream(ld.epoch(0))  # pool rebuilds lazily
            ld.close()
        assert_streams_equal(a, b)
        assert not shm_segments()

    def test_gc_of_abandoned_loader_unlinks_shm(self):
        with deadline(60):
            ld = DataLoader(array_source(), 4, seed=0, num_workers=1)
            it = ld.epoch(0)
            next(it)  # pool + ring live, iterator abandoned mid-epoch
            del it, ld
            gc.collect()
        assert not shm_segments()

    def test_train_loop_closes_the_loader_it_drives(self):
        from edl_tpu.train.loop import LoopConfig, TrainLoop

        ld = DataLoader(array_source(), 8, seed=1, num_workers=1)
        seen = []

        def step(state, batch):
            seen.append(int(batch["label"][0]))
            return state, {"loss": 0.0}

        with deadline(120):
            loop = TrainLoop(step, state=0, mesh=None,
                             config=LoopConfig(num_epochs=1,
                                               log_every_steps=1000))
            loop.run(ld)  # DataLoader IS the data_fn (callable)
        assert len(seen) == ld.steps_per_epoch()
        assert ld._mp_pool is None  # run()'s finally closed it
        assert not shm_segments()

    def test_prefetch_to_device_over_mp_views(self):
        """The bench/train feed: prefetch_to_device COPIES borrowed ring
        views before jax.device_put (which zero-copy aliases aligned
        numpy memory on the CPU backend), so slot recycling cannot
        rewrite a batch already handed to the step."""
        import jax

        from edl_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 8}))
        sharding = mesh_lib.data_sharding(mesh)
        src = array_source()
        with deadline(120):
            with DataLoader(src, 8, seed=4, transforms=AUG) as inline:
                want = copy_stream(inline.epoch(0))
            with DataLoader(src, 8, seed=4, transforms=AUG,
                            num_workers=2) as ld:
                got = [jax.device_get(b) for b in
                       prefetch_to_device(ld.epoch(0), sharding, size=2)]
        assert_streams_equal(want, got)
        assert not shm_segments()

    def test_placed_batches_do_not_alias_the_ring(self):
        """Regression: jax.device_put zero-copies aligned numpy views on
        the CPU backend (the placed Array aliases the shm pages), so
        prefetch_to_device must copy ring views before placement —
        otherwise recycling the slot rewrites a batch the step already
        owns."""
        import jax

        from edl_tpu.data import shm_ring
        from edl_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 8}))
        sharding = mesh_lib.data_sharding(mesh)
        batch = {"image": np.arange(8 * 4 * 4 * 3, dtype=np.uint8)
                 .reshape(8, 4, 4, 3)}
        ring = shm_ring.ShmRing(shm_ring.batch_nbytes(batch), 1)
        try:
            meta = shm_ring.write_batch(ring.buf(0), batch)
            views = shm_ring.read_batch(ring.buf(0), meta)
            [placed] = list(prefetch_to_device(iter([views]), sharding))
            jax.block_until_ready(placed["image"])
            views["image"][...] = 0  # the slot recycles and is rewritten
            np.testing.assert_array_equal(jax.device_get(placed["image"]),
                                          batch["image"])
            del views, placed
        finally:
            ring.close()
        assert not shm_segments()


class TestShmRing:
    def test_write_read_roundtrip_and_alignment(self):
        from edl_tpu.data import shm_ring

        batch = {"image": np.arange(48, dtype=np.uint8).reshape(4, 4, 3),
                 "label": np.arange(4, dtype=np.int64)}
        ring = shm_ring.ShmRing(shm_ring.batch_nbytes(batch), 2)
        try:
            meta = shm_ring.write_batch(ring.buf(0), batch)
            assert meta is not None
            assert all(off % 64 == 0 for _, _, _, off in meta)
            out = shm_ring.read_batch(ring.buf(0), meta)
            assert_streams_equal([batch], [out])
        finally:
            ring.close()
        assert not shm_segments()

    def test_oversized_batch_returns_none(self):
        from edl_tpu.data import shm_ring

        ring = shm_ring.ShmRing(64, 1)
        try:
            big = {"x": np.zeros(1024, np.float32)}
            assert shm_ring.write_batch(ring.buf(0), big) is None
        finally:
            ring.close()
        assert not shm_segments()

    def test_close_tolerates_live_views_and_is_idempotent(self):
        from edl_tpu.data import shm_ring

        batch = {"x": np.arange(16, dtype=np.float32)}
        ring = shm_ring.ShmRing(shm_ring.batch_nbytes(batch), 1)
        meta = shm_ring.write_batch(ring.buf(0), batch)
        view = shm_ring.read_batch(ring.buf(0), meta)["x"]
        ring.close()  # view still alive: name must go, no crash
        ring.close()
        assert not shm_segments()
        np.testing.assert_array_equal(view, batch["x"])  # mapping lives

    def test_spill_fallback_keeps_stream_correct(self):
        """A batch that outgrows its slot ships over the queue instead
        of failing (shape drift after the sizing probe)."""
        from edl_tpu.data.mp_loader import MpLoaderPool

        src = array_source(n=32)
        pool = MpLoaderPool(src, (), (), num_workers=1, slot_bytes=64)
        try:
            descs = [(i, np.arange(i * 4, i * 4 + 4), None, None)
                     for i in range(8)]
            with deadline(60):
                got = copy_stream(pool.imap(descs))
            want = [src.batch(np.arange(i * 4, i * 4 + 4))
                    for i in range(8)]
            assert_streams_equal(want, got)
        finally:
            pool.close()
        assert not shm_segments()


@pytest.mark.slow
class TestStress:
    def test_churny_epochs_stay_deterministic(self):
        """10 epochs at 4 workers with a worker SIGKILL'd each even
        epoch: every stream bit-identical to inline, no leaks."""
        src = array_source(n=128)
        with deadline(300):
            with DataLoader(src, 4, seed=11, transforms=AUG) as inline, \
                    DataLoader(src, 4, seed=11, transforms=AUG,
                               num_workers=4) as ld:
                list(ld.epoch(0))  # pool up
                for epoch in range(10):
                    want = copy_stream(inline.epoch(epoch))
                    it = ld.epoch(epoch)
                    got = [{k: np.array(v) for k, v in next(it).items()}]
                    if epoch % 2 == 0:
                        victims = [p for p in ld._mp_pool._procs
                                   if p.is_alive()]
                        if len(victims) > 1:
                            os.kill(victims[0].pid, signal.SIGKILL)
                    got += copy_stream(it)
                    assert_streams_equal(want, got)
        assert not shm_segments()
