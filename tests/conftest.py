"""Test-wide environment: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective code is
validated on host-platform virtual devices (the analogue of the reference's
fake-backend trick — distill_worker.py:34-42 `_NOP_PREDICT_TEST` — which runs
the whole multiprocess pipeline with zero network/GPUs).

Env vars are too late here (the interpreter's sitecustomize may already have
imported jax to register a TPU plugin), so use jax.config directly — it works
as long as no backend has been initialized yet.
"""

import os

os.environ.setdefault("EDL_TPU_TEST_DEVICES", "8")

# -- lockgraph plugin (EDL_TPU_LOCKGRAPH=1) ---------------------------------
# Install the lock-order recorder BEFORE any edl_tpu module is imported so
# module-level locks are created through the patched factories. The whole
# run then doubles as a deadlock audit: pytest_sessionfinish (below)
# analyzes the global lock-order graph and FAILS the session on any cycle
# (potential ABBA deadlock), with both acquisition stacks in the report.
# See edl_tpu/analysis/lockgraph.py and doc/design_analysis.md.
_LOCKGRAPH = None
if os.environ.get("EDL_TPU_LOCKGRAPH", "") == "1":
    from edl_tpu.analysis import lockgraph as _lockgraph_mod
    _LOCKGRAPH = _lockgraph_mod.install()

# Keep the ambient env consistent with the config below: in-process code
# that applies the env contract (parallel/distributed.py
# force_platform_from_env, e.g. examples run inside tests) must re-apply
# the SAME platform, not a sitecustomize tunnel backend.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_NUM_CPU_DEVICES"] = os.environ["EDL_TPU_TEST_DEVICES"]
# jax < 0.5 has no jax_num_cpu_devices option; the XLA flag is the
# portable spelling of the same virtual-device fan-out (read at backend
# init, so setting it here is still early enough).
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count="
        + os.environ["EDL_TPU_TEST_DEVICES"]).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices",
                      int(os.environ["EDL_TPU_TEST_DEVICES"]))
except AttributeError:  # jax < 0.5: XLA_FLAGS above already applies
    pass


# -- test tiers ------------------------------------------------------------
# `pytest -q` = the fast tier (minutes on one core); the multi-process
# integration suites are @pytest.mark.slow and run with `--runslow`
# (CI runs both tiers — .github/workflows/ci.yml).

def pytest_addoption(parser):
    parser.addoption("--runslow", action="store_true", default=False,
                     help="also run tests marked slow (multi-process "
                          "integration; ~15 extra minutes on one core)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process integration tests excluded from "
                   "the default run (enable with --runslow)")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow tier: pass --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


def pytest_sessionfinish(session, exitstatus):
    if _LOCKGRAPH is None:
        return
    rep = _lockgraph_mod.write_report(_LOCKGRAPH,
                                      _lockgraph_mod.default_report_path())
    print(f"\nlockgraph: {rep['locks_tracked']} lock sites, "
          f"{rep['edges']} order edges, {len(rep['cycles'])} cycle(s), "
          f"{len(rep['hazards'])} hazard(s) -> "
          f"{_lockgraph_mod.default_report_path()}")
    if not rep["ok"]:
        print(_lockgraph_mod.render_failure(rep))
        session.exitstatus = 1
