"""Test-wide environment: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective code is
validated on host-platform virtual devices (the analogue of the reference's
fake-backend trick — distill_worker.py:34-42 `_NOP_PREDICT_TEST` — which runs
the whole multiprocess pipeline with zero network/GPUs).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
