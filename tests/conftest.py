"""Test-wide environment: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective code is
validated on host-platform virtual devices (the analogue of the reference's
fake-backend trick — distill_worker.py:34-42 `_NOP_PREDICT_TEST` — which runs
the whole multiprocess pipeline with zero network/GPUs).

Env vars are too late here (the interpreter's sitecustomize may already have
imported jax to register a TPU plugin), so use jax.config directly — it works
as long as no backend has been initialized yet.
"""

import os

os.environ.setdefault("EDL_TPU_TEST_DEVICES", "8")

# Keep the ambient env consistent with the config below: in-process code
# that applies the env contract (parallel/distributed.py
# force_platform_from_env, e.g. examples run inside tests) must re-apply
# the SAME platform, not a sitecustomize tunnel backend.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_NUM_CPU_DEVICES"] = os.environ["EDL_TPU_TEST_DEVICES"]
# jax < 0.5 has no jax_num_cpu_devices option; the XLA flag is the
# portable spelling of the same virtual-device fan-out (read at backend
# init, so setting it here is still early enough).
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count="
        + os.environ["EDL_TPU_TEST_DEVICES"]).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices",
                      int(os.environ["EDL_TPU_TEST_DEVICES"]))
except AttributeError:  # jax < 0.5: XLA_FLAGS above already applies
    pass


# -- test tiers ------------------------------------------------------------
# `pytest -q` = the fast tier (minutes on one core); the multi-process
# integration suites are @pytest.mark.slow and run with `--runslow`
# (CI runs both tiers — .github/workflows/ci.yml).

def pytest_addoption(parser):
    parser.addoption("--runslow", action="store_true", default=False,
                     help="also run tests marked slow (multi-process "
                          "integration; ~15 extra minutes on one core)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process integration tests excluded from "
                   "the default run (enable with --runslow)")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow tier: pass --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
