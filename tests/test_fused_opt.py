"""Fused optimizer kernels + quantized resident moments.

The contracts under test (ops/opt_kernels.py + train/fused_opt.py):

- the interpret-mode Pallas kernel is BITWISE-identical to the
  plain-XLA fallback for every optimizer x quant mode (the structural
  guarantee the TPU path inherits), and fused-fp32 momentum-SGD is
  bitwise vs the optax chain;
- error feedback conserves update mass: the quantized two-plane moment
  reconstructs to within the second-order bound, and residuals carry
  across steps so the quantized trajectory tracks the fp32 one;
- quantized (q, scale) moment leaves round-trip BITWISE through the
  replicated checkpoint, the sharded checkpoint across mesh shapes
  (4 -> 2 and 4 -> 8 devices) and the peer-migration wire;
- the fused step donates every state buffer (params AND quantized
  planes alias in place — the raw-speed point of the exercise);
- the knobs route: --fused-opt modes map to the right tx, env vars
  reach LoopConfig, invalid combos raise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.ops import opt_kernels as ok
from edl_tpu.train import comm as comm_lib
from edl_tpu.train import fused_opt as fo
from edl_tpu.train import sharded_checkpoint as sc
from edl_tpu.train.checkpoint import CheckpointManager
from edl_tpu.train.state import TrainState, TrainStatus
from edl_tpu.train.step import donation_coverage, make_train_step

QUANTS = ["int8"] + (["fp8"] if ok.fp8_dtype() else [])


def host_tree(t):
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), t)


def assert_trees_bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype, (x.dtype, y.dtype)
        np.testing.assert_array_equal(x, y)


# -- kernel == XLA equivalence (the parity gate CI also runs) ---------------


class TestKernelEquivalence:
    def test_parity_gate_green(self):
        report = fo.update_parity_gate(steps=2)
        # named asserts so a regression says WHICH leg broke
        assert report["sgdm_fp32_vs_optax_bitwise"]
        assert report["adam_fp32_vs_optax_close"], \
            report["adam_fp32_vs_optax_max_err"]
        for q in ["off"] + QUANTS:
            assert report[f"sgdm_{q}_kernel_bitwise"], q
            assert report[f"adam_{q}_kernel_bitwise"], q
        assert report["ok"]

    def test_schedule_feeds_from_step_count(self):
        """A callable learning rate sees count 0, 1, ... (the
        scale_by_schedule convention optax trains with)."""
        params, grads = fo._gate_world(3)
        seen = []

        def sched(count):
            seen.append(count)
            return 0.1

        tx = fo.fused_sgd(sched, 0.9, bucket_mb=0.05)
        state = tx.init(params)
        for _ in range(3):
            params, state = tx.fused_apply(grads, state, params)
        assert [int(c) for c in seen] == [0, 1, 2]
        assert int(state.count) == 3


# -- error feedback ----------------------------------------------------------


class TestErrorFeedback:
    @pytest.mark.parametrize("quant", QUANTS)
    def test_two_plane_reconstruction_bound(self, quant):
        """payload + residual behaves like ~16-bit fixed precision:
        the reconstruction error is second-order (residual-plane
        rounding), far below a single int8 plane's."""
        rng = np.random.default_rng(0)
        m = jnp.asarray(rng.normal(0, 0.05, size=(4096,))
                        .astype(np.float32))
        plane = ok.quant_plane(m, quant)
        recon = ok.dequant_plane(plane, quant)
        err2 = float(jnp.max(jnp.abs(m - recon)))
        one_plane = (ok.dequantize_int8(plane.q, plane.scale)
                     if quant == "int8"
                     else ok._dequantize_fp8(plane.q, plane.scale))
        err1 = float(jnp.max(jnp.abs(m - one_plane)))
        if quant == "int8":
            assert err2 < err1 / 50
            assert err2 <= float(plane.scale) / 254  # second-order bound
        else:
            # e4m3 keeps ~6% relative precision: the residual plane
            # still buys an order of magnitude, not int8's two
            assert err2 < err1 / 10

    def test_zero_plane_is_exact(self):
        for quant in QUANTS:
            plane = ok.zero_plane(256, quant)
            np.testing.assert_array_equal(
                np.asarray(ok.dequant_plane(plane, quant)),
                np.zeros(256, np.float32))

    @pytest.mark.parametrize("quant", QUANTS)
    def test_residual_carryover_tracks_fp32_moments(self, quant):
        """Across steps the residual re-contributes what requant
        rounded away: the quantized moment trajectory stays glued to
        the fp32 fused one (no drift), and so do the params."""
        params, grads = fo._gate_world(1)
        dense = fo.fused_sgd(0.1, 0.9, 1e-4, bucket_mb=0.05)
        quantized = fo.fused_sgd(0.1, 0.9, 1e-4, quant=quant,
                                 bucket_mb=0.05)
        p_a, s_a = fo._run_fused(dense, params, grads, 6)
        p_b, s_b = fo._run_fused(quantized, params, grads, 6)
        for m_fp32, plane in zip(s_a.m, s_b.m):
            m_q = ok.dequant_plane(plane, quant)
            assert float(jnp.max(jnp.abs(m_fp32 - m_q))) < 1e-3
        err = max(float(jnp.max(jnp.abs(a - b)))
                  for a, b in zip(jax.tree.leaves(p_a),
                                  jax.tree.leaves(p_b)))
        assert err < 1e-3


# -- (q, scale) leaves through checkpoint / reshard / migration -------------


def _simple_fused_state(n_devices=None, quant="int8", optimizer="adam",
                        seed=0):
    """A small TrainState on a fused tx; dp-sharded params when a
    device count is given, single-device otherwise."""
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(16, 128)).astype(np.float32)
    b = rng.normal(size=(128,)).astype(np.float32)
    if n_devices is not None:
        mesh = Mesh(np.array(jax.devices()[:n_devices]), ("dp",))
        params = {"w": jax.device_put(w, NamedSharding(mesh, P("dp"))),
                  "b": jax.device_put(b, NamedSharding(mesh, P()))}
    else:
        params = {"w": jnp.asarray(w), "b": jnp.asarray(b)}
    factory = fo.fused_adam if optimizer == "adam" else fo.fused_sgd
    tx = factory(1e-2, quant=quant, bucket_mb=0.05)
    return TrainState.create(apply_fn=None, params=params, tx=tx)


def _grads_like(params, seed=9):
    rng = np.random.default_rng(seed)
    return jax.tree.map(
        lambda p: jnp.asarray(rng.normal(0, 0.02, size=p.shape)
                              .astype(np.float32)), params)


class TestQuantizedStateSerialization:
    def test_replicated_roundtrip_bitwise(self, tmp_path):
        state = _simple_fused_state()
        grads = _grads_like(state.params)
        for _ in range(2):
            state = state.apply_gradients(grads=grads)
        mgr = CheckpointManager(str(tmp_path / "c"), process_index=0)
        mgr.save(state, TrainStatus(epoch=0, step=2))
        restored, status = mgr.restore(_simple_fused_state(seed=5))
        assert status.step == 2
        assert_trees_bitwise(host_tree(state), host_tree(restored))
        # ... and the restored run CONTINUES bitwise (residuals intact)
        assert_trees_bitwise(
            host_tree(state.apply_gradients(grads=grads)),
            host_tree(restored.apply_gradients(grads=grads)))

    @pytest.mark.parametrize("tgt_n", [2, 8])
    def test_sharded_reshard_roundtrip_bitwise(self, tmp_path, tgt_n):
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-virtual-device test mesh")
        state = _simple_fused_state(n_devices=4)
        state = state.apply_gradients(grads=_grads_like(state.params))
        sc.save_sharded(str(tmp_path / "s"), state)
        fresh = _simple_fused_state(n_devices=tgt_n, seed=5)
        restored = sc.restore_sharded(str(tmp_path / "s"), fresh)
        assert_trees_bitwise(host_tree(state), host_tree(restored))

    def test_peer_restore_bitwise_and_byte_accounted(self, tmp_path):
        """A joiner assembling the fused state from a live donor gets
        the int8 planes bitwise — and pays quantized bytes on the wire
        (the donor advert quotes as-stored nbytes)."""
        import time

        from edl_tpu.collective import migration as mig
        from edl_tpu.coord.store import InMemStore

        if len(jax.devices()) < 4:
            pytest.skip("needs the 8-virtual-device test mesh")
        state = _simple_fused_state(n_devices=4)
        state = state.apply_gradients(grads=_grads_like(state.params))
        store = InMemStore()
        mgr = CheckpointManager(str(tmp_path / "c"), process_index=0,
                                sharded=True)
        svc = mig.MigrationService(store, "fjob", "pod0",
                                   addr="127.0.0.1")
        svc.attach(mgr)
        try:
            mgr.save(state, TrainStatus(epoch=0, step=1))
            deadline = time.monotonic() + 5.0
            while (not mig.live_donors(store, "fjob")
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            donors = mig.live_donors(store, "fjob")
            assert donors, "donor advert never appeared"
            state_nbytes = sum(x.nbytes
                               for x in jax.tree.leaves(host_tree(state)))
            assert donors[0]["nbytes"] == state_nbytes
            peer, _, stats = mig.restore_from_peers(
                store, "fjob", _simple_fused_state(n_devices=4, seed=5))
            assert_trees_bitwise(host_tree(state), host_tree(peer))
            assert stats["bytes_from_peers"] == state_nbytes
        finally:
            svc.shutdown(linger=False)

    def test_snapshot_nbytes_counts_as_stored(self):
        state = _simple_fused_state()
        snap = sc.snapshot_host_tree(state)
        expect = sum(x.nbytes for x in jax.tree.leaves(host_tree(state)))
        assert sc.snapshot_nbytes(snap) == expect
        # dict layout (sealed_snapshot's chunk map) counts the same
        assert sc.snapshot_nbytes(
            {"chunks": dict(snap["chunks"])}) == expect


# -- donation ----------------------------------------------------------------


def _tiny_loss(state, params, batch):
    return jnp.mean((batch["x"] @ params["w"]) ** 2), {}


class TestDonation:
    @pytest.mark.parametrize("mode", ["fp32", "int8"])
    def test_fused_step_donates_every_state_buffer(self, mode):
        quant = "off" if mode == "fp32" else mode
        params = {"w": jnp.ones((8, 128), jnp.float32)}
        batch = {"x": jnp.ones((4, 8), jnp.float32)}
        for tx in (fo.fused_sgd(0.1, 0.9, quant=quant, bucket_mb=0.05),
                   fo.fused_adam(1e-2, quant=quant, bucket_mb=0.05)):
            state = TrainState.create(apply_fn=None, params=params,
                                      tx=tx)
            cov = donation_coverage(make_train_step(_tiny_loss),
                                    state, batch)
            assert cov["full"], cov
            assert cov["aliased"] >= cov["state_leaves"]

    def test_donate_false_aliases_nothing(self):
        params = {"w": jnp.ones((8, 128), jnp.float32)}
        batch = {"x": jnp.ones((4, 8), jnp.float32)}
        state = TrainState.create(
            apply_fn=None, params=params,
            tx=fo.fused_sgd(0.1, 0.9, bucket_mb=0.05))
        cov = donation_coverage(
            make_train_step(_tiny_loss, donate=False), state, batch)
        assert cov["aliased"] == 0
        assert not cov["full"]


# -- remat knob --------------------------------------------------------------


class TestRematKnob:
    def test_choose_remat_by_footprint(self):
        from edl_tpu.models.transformer import (TransformerConfig,
                                                auto_remat, choose_remat)

        big = TransformerConfig(vocab_size=1000, d_model=1024,
                                n_heads=8, n_layers=24, d_ff=4096,
                                max_len=2048, dtype=jnp.float32)
        tiny = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                 n_layers=2, d_ff=64, max_len=64,
                                 dtype=jnp.float32)
        hbm = 16 * 2**30
        assert choose_remat(big, batch_size=64, hbm_bytes=hbm)
        assert not choose_remat(tiny, batch_size=4, hbm_bytes=hbm)
        assert auto_remat(big, 64, hbm_bytes=hbm).remat
        assert not auto_remat(tiny, 4, hbm_bytes=hbm).remat


# -- knobs -------------------------------------------------------------------


class TestKnobs:
    def test_make_fused_tx_modes(self):
        assert fo.make_fused_tx("sgdm", 0.1, "off") is None
        tx = fo.make_fused_tx("sgdm", 0.1, "fp32", momentum=0.8)
        assert isinstance(tx, fo.FusedOptimizer)
        assert tx.quant == "off" and tx.momentum == 0.8
        tx = fo.make_fused_tx("adam", 0.1, "int8")
        assert tx.optimizer == "adam" and tx.quant == "int8"
        with pytest.raises(ValueError, match="fused mode"):
            fo.make_fused_tx("sgdm", 0.1, "int4")

    def test_validation(self):
        with pytest.raises(ValueError, match="optimizer"):
            fo.FusedOptimizer("rmsprop", 0.1)
        with pytest.raises(ValueError, match="quant"):
            fo.FusedOptimizer("sgdm", 0.1, quant="int4")
        with pytest.raises(ValueError, match="bucket_mb"):
            fo.FusedOptimizer("sgdm", 0.1, bucket_mb=0)
        with pytest.raises(NotImplementedError, match="fused_apply"):
            fo.fused_sgd(0.1).update({}, None)
        with pytest.raises(ValueError, match="float params only"):
            fo.fused_sgd(0.1).init({"ids": jnp.zeros((8,), jnp.int32)})

    def test_loop_config_env_knobs(self, monkeypatch):
        from edl_tpu.train.loop import LoopConfig
        from edl_tpu.utils.config import from_env

        monkeypatch.setenv("EDL_TPU_FUSED_OPT", "int8")
        monkeypatch.setenv("EDL_TPU_OPT_QUANT", "fp8")
        cfg = from_env(LoopConfig)
        assert cfg.fused_opt == "int8"
        assert cfg.opt_quant == "fp8"

    def test_opt_state_bytes_cut(self):
        params, _ = fo._gate_world(0)
        dense = fo.fused_sgd(0.1, bucket_mb=0.05).init(params)
        quant = fo.fused_sgd(0.1, quant="int8",
                             bucket_mb=0.05).init(params)
        assert (fo.opt_state_bytes(dense)
                >= 1.8 * fo.opt_state_bytes(quant))
