"""End-to-end elastic training: real store server, launchers, trainers.

The working analogue of the reference's flagship demo flow (SURVEY.md §3.1:
JobServer/JobClient -> launch -> register/barrier -> trainers -> resize ->
stop-resume from checkpoint), shrunk to pytest scale: 2 launcher processes
on one host, each spawning the elastic_demo trainer on CPU; killing one
launcher (pod failure) forces the survivor through a stop-resume into a
1-pod world, and training still completes with a checkpoint-resumed epoch
cursor.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow  # real process kills under the launcher

from edl_tpu.coord.client import StoreClient
from edl_tpu.collective import register as reg
from edl_tpu.collective.barrier import read_cluster
from edl_tpu.utils import net


CPU_ENV = {"JAX_PLATFORMS": "cpu", "JAX_NUM_CPU_DEVICES": "1"}


def cpu_env(extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU tunnel
    env.update(CPU_ENV)
    env.update(extra or {})
    return env


NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native", "store")


def start_store(flavor, tmp_path, port=None):
    """Start a coordination store: the Python reference server or the
    production C++ `edl-store --data-dir` daemon (durable)."""
    port = port or net.free_port()
    if flavor == "native":
        binary = os.path.join(NATIVE_DIR, "edl-store")
        build = subprocess.run(["make", "-C", NATIVE_DIR],
                               capture_output=True, text=True)
        assert build.returncode == 0, f"native build failed:\n{build.stderr}"
        cmd = [binary, "--host", "127.0.0.1", "--port", str(port),
               "--sweep-interval", "0.05",
               "--data-dir", str(tmp_path / "store-data")]
    else:
        cmd = [sys.executable, "-m", "edl_tpu.coord.server",
               "--port", str(port)]
    proc = subprocess.Popen(
        cmd, env=cpu_env(), stdout=open(tmp_path / "store.log", "ab"),
        stderr=subprocess.STDOUT)
    client = StoreClient(f"127.0.0.1:{port}")
    deadline = time.time() + 15
    while time.time() < deadline:
        if client.ping():
            return proc, client, port
        time.sleep(0.2)
    proc.kill()
    pytest.fail(f"{flavor} store server never came up")


# The launcher/trainer stack must behave identically against the Python
# server and the durable C++ daemon — the latter is the production store.
@pytest.fixture(params=["python", "native"])
def store_server(request, tmp_path):
    proc, client, port = start_store(request.param, tmp_path)
    yield f"127.0.0.1:{port}", client
    client.close()
    proc.terminate()
    proc.wait(timeout=5)


def start_launcher(store_addr, tmp_path, name, epochs=3, step_time=0.05):
    env = cpu_env({
        "EDL_TPU_JOB_ID": "itjob",
        "EDL_TPU_STORE_ENDPOINTS": store_addr,
        "EDL_TPU_POD_ID": name,
        "EDL_TPU_CHECKPOINT_PATH": str(tmp_path / "ckpt"),
        "EDL_TPU_LOG_DIR": str(tmp_path / f"log_{name}"),
        "EDL_TPU_LEASE_TTL": "2.0",
        "EDL_TPU_BARRIER_STABLE": "0.5",
        "EDL_TPU_NODES_RANGE": "1:4",
        # This suite pins the BASELINE stop-resume recipe (kill world ->
        # re-form -> restore from disk); with p2p live migration on,
        # survivors adopt in place and the restart-banner assertions
        # below would see no restart. The p2p plane has its own suite
        # (test_state_migration.py + elastic_demo --resize-p2p).
        "EDL_TPU_RESIZE_P2P": "0",
    })
    return subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.collective.launch", "--",
         sys.executable, "-m", "edl_tpu.examples.elastic_demo",
         "--epochs", str(epochs), "--steps-per-epoch", "10",
         "--step-time", str(step_time)],
        env=env, stdout=open(tmp_path / f"{name}.log", "wb"),
        stderr=subprocess.STDOUT, start_new_session=True)


def wait_for(cond, timeout, what):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.3)
    pytest.fail(f"timeout waiting for {what}")


def test_single_pod_completes(store_server, tmp_path):
    store_addr, client = store_server
    p = start_launcher(store_addr, tmp_path, "solo", epochs=2,
                       step_time=0.0)
    try:
        wait_for(lambda: p.poll() is not None, 120, "launcher exit")
        assert p.returncode == 0, open(tmp_path / "solo.log").read()
        assert client.get("/itjob/complete") is not None
        cluster = read_cluster(client, "itjob")
        assert cluster.world_size == 1
    finally:
        if p.poll() is None:
            os.killpg(os.getpgid(p.pid), signal.SIGKILL)


def test_sigterm_launcher_leaves_no_orphan_trainer(store_server, tmp_path):
    # A JobClient shrink SIGTERMs the launcher only (the trainer is in its
    # own session); the launcher must kill the trainer tree and release its
    # rank claim instead of orphaning a trainer that keeps training.
    store_addr, client = store_server
    p = start_launcher(store_addr, tmp_path, "victim", epochs=100,
                       step_time=0.5)
    try:
        def demo_procs():
            # Matches the launcher too (the trainer module appears in its
            # argv), so "orphan-free" below means zero matches once the
            # launcher has exited.
            out = subprocess.run(["pgrep", "-f", "edl_tpu.examples.elastic_demo"],
                                 capture_output=True)
            return [x for x in out.stdout.split() if x.strip()]

        wait_for(lambda: read_cluster(client, "itjob") is not None, 60,
                 "cluster formation")
        wait_for(lambda: len(demo_procs()) >= 2, 60, "trainer start")
        assert len(reg.live_pods(client, "itjob")[0]) == 1

        os.kill(p.pid, signal.SIGTERM)  # launcher only, not the group
        wait_for(lambda: p.poll() is not None, 30, "launcher exit")
        wait_for(lambda: not demo_procs(), 30, "trainer cleanup")
        # Rank claim released immediately (lease revoked, not TTL-drained).
        assert reg.live_pods(client, "itjob")[0] == []
    finally:
        if p.poll() is None:
            os.killpg(os.getpgid(p.pid), signal.SIGKILL)
        subprocess.run(["pkill", "-9", "-f", "edl_tpu.examples.elastic_demo"],
                       capture_output=True)


def test_two_pods_then_pod_failure_stop_resume(store_server, tmp_path):
    store_addr, client = store_server
    a = start_launcher(store_addr, tmp_path, "podA", epochs=4,
                       step_time=0.25)
    b = start_launcher(store_addr, tmp_path, "podB", epochs=4,
                       step_time=0.25)
    try:
        # Both pods join one cluster (v>=1, world=2).
        def two_up():
            c = read_cluster(client, "itjob")
            return c is not None and c.world_size == 2
        wait_for(two_up, 60, "2-pod cluster formation")

        # Kill pod B's whole tree: lease drains, survivor must stop-resume.
        os.killpg(os.getpgid(b.pid), signal.SIGKILL)

        def resized():
            c = read_cluster(client, "itjob")
            return (c is not None and c.world_size == 1
                    and c.pod_ids() == {"podA"})
        wait_for(resized, 60, "stop-resume into 1-pod world")

        wait_for(lambda: a.poll() is not None, 120, "survivor completion")
        assert a.returncode == 0, open(tmp_path / "podA.log").read()
        assert client.get("/itjob/complete") is not None

        # Trainer really restarted: the survivor's worker log has at least
        # two generations (start banner per spawn).
        logdir = tmp_path / "log_podA"
        banners = sum(open(logdir / f).read().count("==== start rank=")
                      for f in os.listdir(logdir))
        assert banners >= 2, "no trainer restart recorded"
    finally:
        for p in (a, b):
            if p.poll() is None:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)


def test_coordinator_restart_mid_job(tmp_path):
    """Kill -9 the durable edl-store mid-job and restart it on the same
    data dir/port: rank leases replay with a grace TTL, the pods' keepalive
    loops ride out the outage, and the job completes without a restart —
    the coordinator is no longer a job-killing single point of failure
    (reference relies on etcd's own durability for this,
    pkg/master/etcd_client.go:49-176)."""
    proc, client, port = start_store("native", tmp_path)
    addr = f"127.0.0.1:{port}"
    p = start_launcher(addr, tmp_path, "solo", epochs=4, step_time=0.3)
    try:
        def cluster_up():
            c = read_cluster(client, "itjob")
            return c is not None and c.world_size == 1
        wait_for(cluster_up, 60, "cluster formation")

        os.kill(proc.pid, signal.SIGKILL)      # coordinator crash
        proc.wait(timeout=5)
        client.close()
        time.sleep(0.5)                        # real downtime window
        proc, client, _ = start_store("native", tmp_path, port=port)

        # The job survives the outage: same cluster (no re-registration
        # storm), training runs to completion.
        cluster = read_cluster(client, "itjob")
        assert cluster is not None and cluster.pod_ids() == {"solo"}, \
            "cluster state lost across coordinator restart"
        wait_for(lambda: p.poll() is not None, 180, "job completion")
        assert p.returncode == 0, open(tmp_path / "solo.log").read()
        assert client.get("/itjob/complete") is not None
        # Single generation throughout — the outage caused no stop-resume.
        logdir = tmp_path / "log_solo"
        banners = sum(open(logdir / f).read().count("==== start rank=")
                      for f in os.listdir(logdir))
        assert banners == 1, f"unexpected trainer restarts: {banners}"
    finally:
        if p.poll() is None:
            os.killpg(os.getpgid(p.pid), signal.SIGKILL)
        client.close()
        proc.terminate()
        proc.wait(timeout=5)
