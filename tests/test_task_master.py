"""Task-dispenser master: lease/requeue state machine + file-backed loader.

Covers the reference master's contract (pkg/master/service.go:17-66,
95-208): GetTask/TaskFinished/TaskErrored semantics, timeout->requeue with
bounded failures, epoch accounting — plus the elastic headline: a consumer
dying with claimed shards loses its lease and survivors re-serve exactly
those shards, no record lost or doubled in completed-task accounting.
"""

import json
import threading
import time

import numpy as np
import pytest

from edl_tpu.coord.store import InMemStore
from edl_tpu.data.task_loader import TaskDataLoader, npz_loader, text_loader
from edl_tpu.data.task_master import (TaskMaster, file_list_specs)


class Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


@pytest.fixture
def store():
    return InMemStore()


def master(store, owner, clock=None, **kw):
    kw.setdefault("lease_timeout", 10.0)
    return TaskMaster(store, "job", owner, clock=clock or time.time, **kw)


def specs(n):
    return [{"file": f"f{i}"} for i in range(n)]


def test_dispense_finish_epoch_done(store):
    m = master(store, "podA")
    assert m.init_epoch(0, specs(3))
    assert not m.init_epoch(0, specs(3))  # idempotent
    seen = []
    while True:
        t = m.get_task()
        if t is None:
            break
        seen.append(t.spec["file"])
        assert m.finished(t)
    assert sorted(seen) == ["f0", "f1", "f2"]
    assert m.counts() == {"todo": 0, "pending": 0, "done": 3, "failed": 0}
    assert m.epoch_done()


def test_new_epoch_replaces_table(store):
    m = master(store, "podA")
    m.init_epoch(0, specs(2))
    assert m.init_epoch(1, specs(4))
    assert m.current_epoch() == 1
    assert m.counts() == {"todo": 4, "pending": 0, "done": 0, "failed": 0}
    assert not m.init_epoch(1, specs(4))
    assert not m.init_epoch(0, specs(2))  # can't go back


def test_lease_timeout_requeue_counts_failure(store):
    clock = Clock()
    a = master(store, "podA", clock, lease_timeout=5.0)
    b = master(store, "podB", clock, lease_timeout=5.0)
    a.init_epoch(0, specs(1))
    ta = a.get_task()
    assert ta is not None
    assert b.get_task() is None          # still leased
    clock.t += 6.0                        # lease expires
    tb = b.get_task()
    assert tb is not None and tb.spec == ta.spec
    assert tb.failures == 1               # timeout counted against the task
    assert b.finished(tb)
    # The dead pod's late finish must NOT double-complete.
    assert not a.finished(ta)
    assert b.counts()["done"] == 1


def test_expired_task_fails_past_max(store):
    clock = Clock()
    m = master(store, "podA", clock, lease_timeout=1.0, max_failures=2)
    m.init_epoch(0, specs(1))
    for expected_failures in (0, 1, 2):
        t = m.get_task()
        assert t is not None and t.failures == expected_failures
        clock.t += 2.0  # abandon
    assert m.get_task() is None
    assert m.counts() == {"todo": 0, "pending": 0, "done": 0, "failed": 1}
    assert m.epoch_done()  # failed tasks don't wedge the epoch


def test_errored_requeues_then_fails(store):
    m = master(store, "podA", max_failures=1)
    m.init_epoch(0, specs(1))
    t = m.get_task()
    m.errored(t, "boom")
    assert m.counts()["todo"] == 1
    t = m.get_task()
    assert t.failures == 1
    m.errored(t, "boom again")
    assert m.counts() == {"todo": 0, "pending": 0, "done": 0, "failed": 1}


def test_heartbeat_extends_lease(store):
    clock = Clock()
    a = master(store, "podA", clock, lease_timeout=5.0)
    b = master(store, "podB", clock, lease_timeout=5.0)
    a.init_epoch(0, specs(1))
    t = a.get_task()
    clock.t += 4.0
    assert a.heartbeat(t)
    clock.t += 4.0                        # 8s total, but lease was renewed
    assert b.get_task() is None
    assert a.finished(t)


def test_contending_consumers_get_disjoint_tasks(store):
    n = 40
    m0 = master(store, "pod0")
    m0.init_epoch(0, specs(n))
    results = {w: [] for w in range(4)}

    def worker(w):
        m = master(store, f"pod{w}")
        while True:
            t = m.get_task()
            if t is None:
                if m.epoch_done():
                    return
                time.sleep(0.01)
                continue
            if m.finished(t):
                results[w].append(t.spec["file"])

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    got = sum((results[w] for w in results), [])
    assert sorted(got) == sorted(s["file"] for s in specs(n))  # exactly once


def test_claims_amortize_prefix_scans(store):
    """The todo-candidate cache: N sequential claims must not do N full
    prefix scans (O(all tasks) per claim was VERDICT r4 weak #7 — it
    binds at record-range granularity, 10^5+ tasks)."""
    n = 50
    m = master(store, "pod0")
    m.init_epoch(0, specs(n))
    scans = {"n": 0}
    orig = store.get_prefix

    def counting(prefix):
        if "/task/" in prefix:
            scans["n"] += 1
        return orig(prefix)

    store.get_prefix = counting
    claimed = 0
    while True:
        t = m.get_task()
        if t is None:
            break
        m.finished(t)
        claimed += 1
    assert claimed == n
    # one populating scan + the final empty-confirming scan(s); NOT one
    # per claim
    assert scans["n"] <= 3, scans["n"]


def test_cache_invalidated_on_new_epoch(store):
    m = master(store, "pod0")
    m.init_epoch(0, specs(4))
    assert m.get_task() is not None  # populates the epoch-0 cache
    m.init_epoch(1, specs(2))
    t = m.get_task()
    assert t is not None and t.epoch == 1  # stale keys never served


def test_file_list_specs_record_ranges():
    assert file_list_specs(["a", "b"]) == [{"file": "a"}, {"file": "b"}]
    ranged = file_list_specs(["a"], records_per_task=4, counts=[10])
    assert ranged == [{"file": "a", "start": 0, "stop": 4},
                      {"file": "a", "start": 4, "stop": 8},
                      {"file": "a", "start": 8, "stop": 10}]


# -- TaskDataLoader over real files -----------------------------------------

def write_npz_dataset(tmp_path, n_files=4, rows=8):
    files = []
    for i in range(n_files):
        path = tmp_path / f"shard{i}.npz"
        np.savez(path,
                 x=np.arange(i * rows, (i + 1) * rows, dtype=np.int64),
                 y=np.full((rows,), i, dtype=np.int32))
        files.append(str(path))
    return files


def test_task_loader_consumes_all_records_exactly_once(tmp_path, store):
    files = write_npz_dataset(tmp_path)
    m = master(store, "podA")
    m.init_epoch(0, file_list_specs(files))
    loader = TaskDataLoader(m, npz_loader, batch_size=3)
    seen = np.concatenate([b["x"] for b in loader.epoch(0)])
    assert sorted(seen.tolist()) == list(range(32))
    assert loader.tasks_completed == 4 and loader.tasks_lost == 0
    assert m.epoch_done()


def test_task_loader_drop_remainder(tmp_path, store):
    files = write_npz_dataset(tmp_path, n_files=1, rows=8)
    m = master(store, "podA")
    m.init_epoch(0, file_list_specs(files))
    loader = TaskDataLoader(m, npz_loader, batch_size=3, drop_remainder=True)
    batches = list(loader.epoch(0))
    assert [len(b["x"]) for b in batches] == [3, 3]


def test_text_loader(tmp_path, store):
    p = tmp_path / "data.txt"
    p.write_bytes(b"r0\nr1\nr2\nr3\n")
    arrays = text_loader({"file": str(p), "start": 1, "stop": 3})
    assert arrays["line"].tolist() == [b"r1", b"r2"]


def test_killed_pod_shards_redispensed_no_loss_no_double(tmp_path, store):
    """The elastic headline: pod dies holding claimed shards; survivors
    re-serve exactly those shards after lease expiry."""
    files = write_npz_dataset(tmp_path, n_files=6, rows=4)
    dead = master(store, "dead", lease_timeout=0.5)
    dead.init_epoch(0, file_list_specs(files))

    # The dying pod claims two shards and consumes part of one, then dies
    # (never calls finished).
    t1 = dead.get_task()
    t2 = dead.get_task()
    assert t1 is not None and t2 is not None
    _ = npz_loader(t1.spec)  # it even read the data — doesn't matter

    survivor = master(store, "live", lease_timeout=0.5)
    loader = TaskDataLoader(survivor, npz_loader, batch_size=4, poll=0.05)
    seen = np.concatenate([b["x"] for b in loader.epoch(0)])

    # Every record trained exactly once across completed tasks: the dead
    # pod's claimed shards were re-dispensed, nothing lost, nothing doubled.
    assert sorted(seen.tolist()) == list(range(24))
    assert loader.tasks_completed == 6
    assert survivor.counts()["done"] == 6
    # And the dead pod's zombie finish is rejected.
    assert not dead.finished(t1)
