"""Peer-to-peer state migration: restore-path equivalence + fencing.

The contract under test (collective/migration.py): a state restored
from live donors over the tensor wire is BITWISE identical to the same
version restored from disk — replicated and sharded layouts, including
cross-mesh resharding — and every failure mode (donor death
mid-transfer, stale donors, a donor resealing mid-restore) degrades to
the disk path without corrupting the world. The full multi-process loop
(launchers + scripted /resize shrink/grow with the in-place-adoption
audit) runs in the slow tier via `elastic_demo --resize-p2p`.
"""

import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.collective import migration as mig
from edl_tpu.coord.store import InMemStore
from edl_tpu.train import sharded_checkpoint as sc
from edl_tpu.train.checkpoint import CheckpointManager
from edl_tpu.train.state import TrainStatus


def wait_until(pred, timeout=5.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    pytest.fail(f"timeout waiting for {what}")


def make_service(store, ckpt=None, pod="pod0", job="mjob"):
    svc = mig.MigrationService(store, job, pod, addr="127.0.0.1")
    if ckpt is not None:
        svc.attach(ckpt)
    return svc


def assert_trees_bitwise(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype, (x.dtype, y.dtype)
        assert np.array_equal(x, y), "peer- and disk-restored leaves differ"


def rep_state():
    rng = np.random.default_rng(7)
    return {"w": rng.normal(size=(8, 16)).astype(np.float32),
            "b": rng.normal(size=(16,)).astype(np.float64),
            "step": 41}


def rep_target():
    return {"w": np.zeros((8, 16), np.float32),
            "b": np.zeros((16,), np.float64), "step": 0}


class TestPeerRestoreEquivalence:
    def test_replicated_peer_restore_bitwise_identical_to_disk(
            self, tmp_path):
        store = InMemStore()
        mgr = CheckpointManager(str(tmp_path / "c"), process_index=0)
        svc = make_service(store, mgr)
        try:
            mgr.save(rep_state(), TrainStatus(epoch=2, step=41))
            wait_until(lambda: mig.live_donors(store, "mjob"),
                       what="donor advert")
            peer, pstatus, stats = mig.restore_from_peers(
                store, "mjob", rep_target())
            disk, dstatus = mgr.restore(rep_target())
            assert_trees_bitwise(peer, disk)
            assert pstatus.to_dict() == dstatus.to_dict()
            assert stats["bytes_from_peers"] > 0
        finally:
            svc.shutdown(linger=False)

    @pytest.mark.parametrize("tgt_n", [2, 8])
    def test_sharded_peer_restore_reshards_bitwise(self, tmp_path,
                                                   tgt_n):
        """A state saved dp-sharded on 4 devices restores onto a 2- and
        an 8-device mesh identically through peers and disk (the same
        region planner drives both)."""
        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs the 8-virtual-device test mesh")
        src = Mesh(np.array(devs[:4]), ("dp",))
        tgt = Mesh(np.array(devs[:tgt_n]), ("dp",))
        rng = np.random.default_rng(3)
        state = {f"l{i}": jax.device_put(
            rng.normal(size=(16, 6)).astype(np.float32),
            NamedSharding(src, P("dp"))) for i in range(3)}

        store = InMemStore()
        mgr = CheckpointManager(str(tmp_path / "c"), process_index=0,
                                sharded=True)
        svc = make_service(store, mgr)
        try:
            mgr.save(state, TrainStatus(epoch=0, step=7))
            wait_until(lambda: mig.live_donors(store, "mjob"),
                       what="donor advert")

            def target():
                return {k: jax.device_put(
                    np.zeros((16, 6), np.float32),
                    NamedSharding(tgt, P("dp"))) for k in state}

            peer, _, stats = mig.restore_from_peers(store, "mjob",
                                                    target())
            disk, _ = mgr.restore(target())
            assert_trees_bitwise(peer, disk)
            assert_trees_bitwise(peer, state)
            assert stats["bytes_from_peers"] \
                == sum(np.asarray(v).nbytes for v in state.values())
        finally:
            svc.shutdown(linger=False)


class TestExpertReshard:
    """ep elasticity: MoE expert tables (leading dim sharded P('ep') by
    sharding.DEFAULT_RULES) reshard through the SAME planner as every
    other sharded leaf — peer restore onto a shrunk or grown ep mesh is
    bitwise identical to disk, with zero process restarts (everything
    here happens in-process over the tensor wire)."""

    @staticmethod
    def _expert_state(mesh, rng):
        from edl_tpu.parallel.sharding import logical_to_spec
        spec = logical_to_spec(("expert", "embed", "mlp"), mesh=mesh)
        assert spec == P("ep")
        return {f"block{i}.moe_mlp.{name}": jax.device_put(
            rng.normal(size=(8, 4, 6)).astype(np.float32),
            NamedSharding(mesh, spec))
            for i in range(2) for name in ("w_in", "w_out")}

    @pytest.mark.parametrize("tgt_n", [2, 8])
    def test_expert_tables_peer_reshard_bitwise(self, tmp_path, tgt_n):
        """Expert tables saved ep=4 restore onto ep=2 (shrink: each
        chip adopts two experts' rows) and ep=8 (grow: rows split)
        identically through peers and disk."""
        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs the 8-virtual-device test mesh")
        src = Mesh(np.array(devs[:4]), ("ep",))
        tgt = Mesh(np.array(devs[:tgt_n]), ("ep",))
        rng = np.random.default_rng(13)
        state = self._expert_state(src, rng)

        store = InMemStore()
        mgr = CheckpointManager(str(tmp_path / "c"), process_index=0,
                                sharded=True)
        svc = make_service(store, mgr)
        try:
            mgr.save(state, TrainStatus(epoch=0, step=11))
            wait_until(lambda: mig.live_donors(store, "mjob"),
                       what="donor advert")

            def target():
                return {k: jax.device_put(
                    np.zeros((8, 4, 6), np.float32),
                    NamedSharding(tgt, P("ep"))) for k in state}

            peer, _, stats = mig.restore_from_peers(store, "mjob",
                                                    target())
            disk, _ = mgr.restore(target())
            assert_trees_bitwise(peer, disk)
            assert_trees_bitwise(peer, state)
            assert stats["bytes_from_peers"] > 0
            # every restored leaf really lands ep-sharded on the new
            # mesh: one distinct expert row range per chip
            for v in peer.values():
                assert len(v.sharding.device_set) == tgt_n
        finally:
            svc.shutdown(linger=False)

    def test_expert_resize_round_trip_bitwise(self, tmp_path):
        """The full 4 -> 2 -> 4 resize cycle: shrink onto 2 chips,
        re-save from the shrunk world, grow back — tables return to
        the original placement bitwise (no quantization, no reorder:
        the planner moves expert rows, never rewrites them)."""
        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs the 8-virtual-device test mesh")
        m4 = Mesh(np.array(devs[:4]), ("ep",))
        m2 = Mesh(np.array(devs[:2]), ("ep",))
        rng = np.random.default_rng(17)
        state = self._expert_state(m4, rng)

        def target(mesh):
            return {k: jax.device_put(
                np.zeros((8, 4, 6), np.float32),
                NamedSharding(mesh, P("ep"))) for k in state}

        d1 = str(tmp_path / "ep4")
        sc.save_sharded(d1, state)
        shrunk = sc.restore_sharded(d1, target(m2))
        assert_trees_bitwise(shrunk, state)
        d2 = str(tmp_path / "ep2")
        sc.save_sharded(d2, shrunk)
        regrown = sc.restore_sharded(d2, target(m4))
        assert_trees_bitwise(regrown, state)
        for v in regrown.values():
            assert len(v.sharding.device_set) == 4


class _FetchDropsServer(mig.MigrationServer):
    """Donor that dies mid-transfer: serves the manifest, then drops
    the connection on the first chunk fetch."""

    def _handle(self, conn, meta):
        if meta.get("op") == "fetch":
            conn.close()
            raise OSError("donor died mid-transfer")
        super()._handle(conn, meta)


class _ResealsServer(mig.MigrationServer):
    """Donor that seals a NEWER version between manifest and fetch."""

    def _handle(self, conn, meta):
        if meta.get("op") == "fetch":
            newer = dict(self.snapshot())
            newer["version"] = newer["version"] + 1
            self.publish(newer)
        super()._handle(conn, meta)


def publish_donor(store, server, snap, job="mjob", pod="pod0"):
    server.publish(snap)
    store.put(mig.donor_key(job, pod), json.dumps(
        {"pod_id": pod, "addr": "127.0.0.1", "port": server.port,
         "version": snap["version"]}))


def serveable(state, version=0, step=9):
    snap = sc.snapshot_host_tree(state)
    return {"version": version,
            "status": TrainStatus(step=step).to_dict(),
            "process_index": 0, "leaves": snap["leaves"],
            "chunks": dict(snap["chunks"])}


class TestFallbackAndFencing:
    def test_donor_death_mid_transfer_falls_back_to_disk(self, tmp_path):
        """The donor serves its manifest then drops every fetch: peer
        restore must raise (not hang, not return garbage) and the disk
        restore of the SAME version must still produce intact state."""
        store = InMemStore()
        state = rep_state()
        mgr = CheckpointManager(str(tmp_path / "c"), process_index=0)
        mgr.save(state, TrainStatus(step=9))
        server = _FetchDropsServer(host="127.0.0.1")
        try:
            publish_donor(store, server, serveable(state))
            with pytest.raises(mig.PeerRestoreError):
                mig.restore_from_peers(store, "mjob", rep_target())
            disk, status = mgr.restore(rep_target())
            assert_trees_bitwise(disk, state)
            assert status.step == 9
        finally:
            server.stop()

    def test_loop_try_restore_survives_peer_failure(self, tmp_path,
                                                    monkeypatch):
        """TrainLoop.try_restore: a failing migration plane degrades to
        the disk path transparently (restore_source records it)."""
        from edl_tpu.examples import fit_a_line
        from edl_tpu.parallel.mesh import make_mesh
        from edl_tpu.train.loop import LoopConfig, TrainLoop

        cfg = fit_a_line.Config(num_epochs=1, steps_per_epoch=5)
        state, step_fn = fit_a_line.build(cfg)
        loop = TrainLoop(step_fn, state, mesh=make_mesh(),
                         config=LoopConfig(num_epochs=1,
                                           ckpt_dir=str(tmp_path)))
        loop.run(lambda e: fit_a_line.synthetic_batches(e, cfg))

        loop2 = TrainLoop(step_fn, state, mesh=make_mesh(),
                          config=LoopConfig(num_epochs=1,
                                            ckpt_dir=str(tmp_path)))

        class _BrokenMigration:
            def restore_from_peers(self, target, **kw):
                raise mig.PeerRestoreError("no live donors advertised")
        loop2._migration = _BrokenMigration()
        assert loop2.try_restore()
        assert loop2.restore_source == "disk"
        loop2._migration = None

    def test_stale_donors_fenced_by_local_disk_version(self, tmp_path):
        """Epoch fence: donors serving an OLDER version than this pod's
        own sealed disk checkpoint are refused (total-kill recovery must
        not resurrect an old state via a lagging donor)."""
        store = InMemStore()
        state = rep_state()
        mgr = CheckpointManager(str(tmp_path / "c"), process_index=0)
        mgr.save(state, TrainStatus(step=1))   # ckpt-0
        mgr.save(state, TrainStatus(step=2))   # ckpt-1
        server = mig.MigrationServer(host="127.0.0.1")
        try:
            publish_donor(store, server, serveable(state, version=0))
            with pytest.raises(mig.PeerRestoreError, match="stale"):
                mig.restore_from_peers(
                    store, "mjob", rep_target(),
                    local_version=mgr.latest_version())
        finally:
            server.stop()

    def test_donor_resealing_mid_restore_is_fenced(self, tmp_path):
        """A donor that seals a newer version between the manifest and
        a chunk fetch must not hand the restorer a mixed-step state —
        the version fence turns it into a disk fallback."""
        store = InMemStore()
        state = rep_state()
        server = _ResealsServer(host="127.0.0.1")
        try:
            publish_donor(store, server, serveable(state, version=3))
            with pytest.raises(mig.PeerRestoreError,
                               match="mid-restore"):
                mig.restore_from_peers(store, "mjob", rep_target())
        finally:
            server.stop()

    def test_no_donors_raises(self):
        with pytest.raises(mig.PeerRestoreError, match="no live donors"):
            mig.restore_from_peers(InMemStore(), "mjob", rep_target())

    def test_merge_leaf_tables_shape_mismatch_raises(self):
        t1 = [{"key": "w", "shape": [4], "dtype": "float32",
               "chunks": []}]
        t2 = [{"key": "w", "shape": [8], "dtype": "float32",
               "chunks": []}]
        with pytest.raises(ValueError, match="shape mismatch"):
            sc.merge_leaf_tables([t1, t2])


class TestSealedRetention:
    def test_async_saves_retain_newest_sealed_snapshot(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), process_index=0)
        mgr.retain_sealed = True
        state = rep_state()
        mgr.save_async(state, TrainStatus(step=1))
        mgr.close()
        snap = mgr.sealed_snapshot()
        assert snap is not None and snap["version"] == 0
        assert snap["status"]["step"] == 1
        # a previously handed-out snapshot survives the next seal intact
        # (retained payloads are never recycled into the staging pool)
        w0 = snap["chunks"][snap["leaves"][0]["chunks"][0]["file"]]
        w0_copy = np.array(w0)
        state2 = {**rep_state(), "w": np.full((8, 16), 5.0, np.float32)}
        mgr.save_async(state2, TrainStatus(step=2))
        mgr.close()
        assert mgr.sealed_snapshot()["version"] == 1
        assert np.array_equal(w0, w0_copy), \
            "older retained snapshot was overwritten while serveable"

    def test_sync_sharded_save_retains_a_copy(self, tmp_path):
        devs = jax.devices()
        mesh = Mesh(np.array(devs[:2]), ("dp",))
        state = {"w": jax.device_put(
            np.arange(32, dtype=np.float32).reshape(8, 4),
            NamedSharding(mesh, P("dp")))}
        mgr = CheckpointManager(str(tmp_path), process_index=0,
                                sharded=True)
        mgr.retain_sealed = True
        mgr.save(state, TrainStatus(step=3))
        snap = mgr.sealed_snapshot()
        assert snap["version"] == 0
        total = sum(a.nbytes for a in snap["chunks"].values())
        assert total == 32 * 4


class TestResizeEpochPublish:
    def test_resize_publishes_epoch_with_donor_roster(self):
        from edl_tpu.collective.job_server import JobState
        store = InMemStore()
        store.put(mig.donor_key("j", "podA"), json.dumps(
            {"pod_id": "podA", "addr": "127.0.0.1", "port": 1234,
             "version": 5}))
        state = JobState("j", 1, 4, desired=2, store=store)
        state.resize(3)
        doc = json.loads(store.get(mig.epoch_key("j")).value)
        assert doc["epoch"] == 1 and doc["desired"] == 3
        assert doc["from"] == 2
        assert [d["pod_id"] for d in doc["donors"]] == ["podA"]
        # unchanged desired -> no new epoch (fencing stays monotonic)
        state.resize(3)
        assert json.loads(
            store.get(mig.epoch_key("j")).value)["epoch"] == 1
        state.random_resize()
        assert json.loads(
            store.get(mig.epoch_key("j")).value)["epoch"] == 2


def seed_job(store, job="j1", world=2, rate=120.0, now=None):
    """A live job in the store: rank claims + cluster + fresh util."""
    from edl_tpu.collective.cluster import Cluster, Pod
    from edl_tpu.collective.register import cluster_key, rank_key
    from edl_tpu.coord.collector import util_key
    now = time.time() if now is None else now
    pods = []
    for i in range(world):
        pod_id = f"pod{i}"
        store.put(rank_key(job, i),
                  Pod(pod_id=pod_id, addr=f"10.0.0.{i}", n_devices=1,
                      claimed_rank=i, rank=i).to_json(),
                  lease=store.lease_grant(30.0))
        store.put(util_key(job, pod_id),
                  json.dumps({"pod_id": pod_id, "step": 10,
                              "examples_per_sec": rate / world,
                              "world_size": world,
                              "published_unix": now}),
                  lease=store.lease_grant(30.0))
        pods.append(Pod(pod_id=pod_id, addr=f"10.0.0.{i}", rank=i))
    store.put(cluster_key(job),
              Cluster(job_id=job, version=world, pods=pods).to_json())


class TestMeasuredDowntimeFeedback:
    def _controller(self, store, state, clock):
        from edl_tpu.scaler.controller import (ScalerConfig,
                                               ScalerController)
        from edl_tpu.scaler.policy import ThroughputPolicy
        return ScalerController(
            store, [state.job_id],
            ThroughputPolicy(gain_threshold=0.05, cooldown_s=1.0,
                             horizon_s=60.0),
            config=ScalerConfig(cooldown_s=1.0, downtime_s=1.5,
                                staleness_s=3600.0),
            actuate=lambda _job, desired: state.resize(desired),
            elect=False, clock=clock)

    def test_observed_downtime_replaces_configured_constant(self):
        """The amortization charge follows the MEASURED resize price:
        actuation -> first fresh utilization at the new world closes the
        probe, the EWMA lands in subsequent JobViews and the journal,
        and a takeover controller replays it."""
        from edl_tpu.collective.job_server import JobState
        from edl_tpu.scaler.controller import journal_prefix
        store = InMemStore()
        t0 = time.time()
        now = [t0]
        seed_job(store, world=2, now=t0)
        state = JobState("j1", 1, 4, desired=2)
        ctl = self._controller(store, state, clock=lambda: now[0])
        (entry,) = ctl.tick()
        assert entry["action"] == "resize" and entry["applied"] == 3
        # before any observation: the configured fallback is the charge
        assert entry["downtime_s"] == 1.5

        # 0.4s later the re-formed world publishes fresh utilization
        now[0] = t0 + 0.4
        seed_job(store, world=3, rate=150.0, now=now[0])
        view = ctl.observe("j1", now=now[0])
        assert view.downtime_s == pytest.approx(0.4, abs=1e-6)

        # the next tick journals the measurement alongside the charge
        # it actually used
        now[0] = t0 + 1.3  # past cooldown
        (entry,) = ctl.tick()
        assert entry["downtime_s"] == pytest.approx(0.4, abs=0.01)
        assert entry["observed_downtime_s"] == pytest.approx(0.4,
                                                             abs=0.01)
        recs, _ = store.get_prefix(journal_prefix("j1"))
        journaled = [json.loads(r.value).get("observed_downtime_s")
                     for r in recs]
        assert any(m is not None for m in journaled)
        ctl.stop()

    def test_journal_replay_reseeds_measured_downtime(self):
        from edl_tpu.collective.job_server import JobState
        store = InMemStore()
        t0 = time.time()
        now = [t0]
        seed_job(store, world=2, now=t0)
        state = JobState("j1", 1, 4, desired=2)
        ctl = self._controller(store, state, clock=lambda: now[0])
        ctl.tick()                       # resize 2->3, probe armed
        now[0] = t0 + 0.5
        seed_job(store, world=3, rate=150.0, now=now[0])
        ctl.observe("j1", now=now[0])    # probe closes at 0.5s
        now[0] = t0 + 1.6
        ctl.tick()                       # journals the measurement
        ctl.stop()

        takeover = self._controller(store, state, clock=lambda: now[0])
        takeover._restore_from_journal()
        assert takeover._downtime.get("j1") == pytest.approx(0.5,
                                                             abs=0.01)
        takeover.stop()

    def test_artifact_downtime_prefers_p2p_number(self, tmp_path):
        from edl_tpu.scaler.controller import artifact_downtime
        art = tmp_path / "BENCH.json"
        art.write_text(json.dumps({"extras": {
            "elastic_downtime_s": 1.2,
            "elastic_downtime_p2p_s": 0.06}}))
        assert artifact_downtime(str(art)) == pytest.approx(0.06)
        art2 = tmp_path / "B2.json"
        art2.write_text(json.dumps({"extras": {
            "elastic_downtime_s": 1.2}}))
        assert artifact_downtime(str(art2)) == pytest.approx(1.2)
        assert artifact_downtime(str(tmp_path / "missing.json")) is None


@pytest.mark.slow
def test_resize_p2p_demo_end_to_end(tmp_path):
    """The full loop under real processes: store + JobServer + launcher
    pods, scripted shrink (survivor ADOPTS in place) and grow (joiner
    restores FROM PEERS over the wire), self-audited — the demo exits
    non-zero when any resize silently degraded to the disk recipe.
    Covers the SIGKILL-free churn path; donor-death-mid-transfer is
    pinned by the fast in-process tests above."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({"JAX_PLATFORMS": "cpu", "JAX_NUM_CPU_DEVICES": "1"})
    proc = subprocess.run(
        [sys.executable, "-m", "edl_tpu.examples.elastic_demo",
         "--resize-p2p"],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.join(os.path.dirname(__file__), ".."))
    assert proc.returncode == 0, \
        f"p2p demo failed:\n{proc.stdout[-4000:]}\n{proc.stderr[-4000:]}"
    summary = json.loads(
        proc.stdout.split("p2p_summary=", 1)[1].splitlines()[0])
    assert summary["ok"] and summary["adoptions"] >= 1
    assert summary["peer_restores"] >= 1
    assert summary["resize_bytes_from_peers"] > 0
    # the headline: surviving pods' resize gap is far below the ~1.2s
    # stop-resume respawn floor (no respawn, no re-jit, no restore)
    assert summary["elastic_downtime_p2p_s"] < 0.5
