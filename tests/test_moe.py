"""Elastic MoE: router semantics, dispatch-wire parity, ep plumbing.

The load-bearing claims of the expert-parallel plane, each pinned:

- the top-k capacity-factor router (models/transformer.router_topk)
  grants slots choice-major, drops past capacity, and accounts every
  drop (combine/dispatch zero out together; dropped_frac is exact);
- the hierarchical all-to-all (train/comm.moe_all_to_all) is a pure
  permutation: BITWISE identical to the flat single collective when
  uncompressed, on the emulated 2x4 world and through a real training
  step (moe_parity_gate);
- the int8 DCN leg rides the SHARED quantizer (ops/pack.py) — the
  wire decomposes into per-destination pack_int8 exactly, so the
  interpret-mode kernel pin on pack_int8 covers it;
- ep mesh plumbing: MeshSpec.resolve_hybrid lets `ep` carry the DCN
  dimension, ep_comm_groups mirrors dp_comm_groups, and the MoE step
  rejects meshes it does not own;
- the obs surface: `step.moe_dispatch` span + `step_moe_dcn_bytes`
  counter carry the wire accounting.

ep-resize bitwise restore (expert tables through the checkpoint /
migration planner) lives in tests/test_state_migration.py.
"""

import contextlib
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.models import transformer as tfm
from edl_tpu.parallel import mesh as mesh_lib
from edl_tpu.parallel.compat import shard_map
from edl_tpu.train import comm

WORLD = 8


# -- router ------------------------------------------------------------------


def test_moe_capacity_arithmetic():
    # ceil(1.25 * 64 * 2 / 8) = 20
    assert tfm.moe_capacity(64, 8, 2, 1.25) == 20
    assert tfm.moe_capacity(1, 64, 1, 0.1) == 1  # floor at 1
    assert tfm.moe_capacity(16, 4, 1, 1.0) == 4


def test_router_topk_shapes_and_renormalized_gates():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(12, 4)).astype(np.float32))
    # capacity == T: no expert can overflow, whatever the routing
    combine, dispatch, aux = tfm.router_topk(logits, top_k=2,
                                             capacity=12)
    assert combine.shape == (12, 4, 12) and dispatch.shape == (12, 4, 12)
    assert dispatch.dtype == jnp.bool_
    # nothing dropped at this capacity -> each token's kept gates sum to 1
    assert float(aux["dropped_frac"]) == 0.0
    np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)),
                               1.0, rtol=1e-5)


def test_router_capacity_drop_is_exact_and_choice_major():
    """All 8 tokens pick experts (0, 1); capacity 3 keeps the FIRST
    three first-choice assignments per expert and drops the rest —
    10 of 16 assignments, and every dropped assignment vanishes from
    dispatch AND combine."""
    t, e, cap = 8, 4, 3
    logits = np.full((t, e), -10.0, np.float32)
    logits[:, 0] = 2.0   # every token's first choice
    logits[:, 1] = 1.0   # every token's second choice
    combine, dispatch, aux = tfm.router_topk(jnp.asarray(logits),
                                             top_k=2, capacity=cap)
    d = np.asarray(dispatch)
    assert d[:, 0].sum() == cap and d[:, 1].sum() == cap
    assert d[:, 2:].sum() == 0                      # untouched experts
    # choice-major: expert 0's slots go to tokens 0..2, THEN expert 1's
    # to tokens 0..2 (second choices of the earliest tokens)
    assert d[:3, 0].sum() == cap and d[3:, 0].sum() == 0
    assert float(aux["dropped_frac"]) == pytest.approx(10 / 16)
    c = np.asarray(combine)
    assert (c[d] > 0).all() and (c[~d] == 0).all()


def test_router_perfect_balance_scores_one():
    """One token per expert, uniform probs -> Shazeer load_balance == 1
    (its minimum under a fixed top_k) up to softmax float noise."""
    e = 4
    logits = jnp.asarray(np.zeros((8, e), np.float32))
    _, _, aux = tfm.router_topk(logits, top_k=1, capacity=8)
    assert float(aux["load_balance"]) == pytest.approx(1.0, rel=1e-5)


def test_transformer_config_moe_validation():
    common = dict(vocab_size=8, d_model=8, n_heads=1, n_layers=1,
                  d_ff=8, max_len=4)
    with pytest.raises(ValueError, match="n_experts"):
        tfm.TransformerConfig(**common, moe=True, n_experts=1)
    with pytest.raises(ValueError, match="moe_top_k"):
        tfm.TransformerConfig(**common, moe=True, n_experts=4,
                              moe_top_k=5)
    with pytest.raises(ValueError, match="moe_capacity_factor"):
        tfm.TransformerConfig(**common, moe=True, n_experts=4,
                              moe_capacity_factor=0.0)
    # moe=False skips the expert checks entirely
    tfm.TransformerConfig(**common, n_experts=1)


def test_lm_loss_moe_collects_router_aux():
    cfg = tfm.TransformerConfig(vocab_size=16, d_model=16, n_heads=2,
                                n_layers=2, d_ff=32, max_len=8,
                                dtype=jnp.float32, moe=True, n_experts=4)
    model = tfm.Transformer(cfg)
    toks = jnp.asarray(np.arange(32, dtype=np.int32).reshape(4, 8) % 16)
    from flax.core import meta
    variables = meta.unbox(model.init(jax.random.PRNGKey(0), toks,
                                      train=False))
    from edl_tpu.train.state import TrainState
    import optax
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"],
                              tx=optax.sgd(0.1))
    loss, metrics = tfm.lm_loss_moe(state, state.params,
                                    {"tokens": toks})
    assert float(loss) > 0
    assert {"ppl", "moe_balance", "moe_dropped"} <= set(metrics)
    assert float(metrics["moe_balance"]) > 0  # n_layers=2 MoE blocks sown
    assert 0.0 <= float(metrics["moe_dropped"]) <= 1.0


# -- ep mesh plumbing --------------------------------------------------------


def test_dcn_axis_of_prefers_ep():
    assert mesh_lib.dcn_axis_of({"dp": 4}) == "dp"
    assert mesh_lib.dcn_axis_of({"ep": 4}) == "ep"
    assert mesh_lib.dcn_axis_of({"dp": 2, "ep": 4}) == "ep"


def test_resolve_hybrid_ep_carries_dcn():
    spec = mesh_lib.MeshSpec({"ep": -1})
    topo = mesh_lib.SliceTopology(2, 4)
    dcn, ici = spec.resolve_hybrid(topo)
    assert dcn == {"ep": 2} and ici == {"ep": 4}
    with pytest.raises(ValueError, match="not divisible by n_slices"):
        mesh_lib.MeshSpec({"ep": 3}).resolve_hybrid(topo)
    with pytest.raises(ValueError, match="carry the DCN"):
        mesh_lib.MeshSpec({"tp": 8}).resolve_hybrid(topo)


def test_ep_comm_groups_mirror_dp():
    assert mesh_lib.ep_comm_groups(2, 4) == mesh_lib.dp_comm_groups(2, 4)
    intra, cross = mesh_lib.ep_comm_groups(2, 4)
    assert intra == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert cross == [[0, 4], [1, 5], [2, 6], [3, 7]]
    with pytest.raises(ValueError, match="positive factors"):
        mesh_lib.ep_comm_groups(0, 4)


def test_expert_tables_shard_over_ep():
    from edl_tpu.parallel.sharding import logical_to_spec
    mesh = _ep_mesh()
    # ep-only mesh: expert dim shards, embed/mlp (fsdp/tp) drop out
    assert logical_to_spec(("expert", "embed", "mlp"),
                           mesh=mesh) == P("ep")
    # the router stays replicated — every chip routes against all experts
    assert logical_to_spec(("embed", "expert_router"), mesh=mesh) == P()


def test_moe_dispatch_config_validation():
    with pytest.raises(ValueError, match="mode"):
        comm.MoEDispatchConfig(mode="ring")
    with pytest.raises(ValueError, match="compress"):
        comm.MoEDispatchConfig(compress="topk")
    with pytest.raises(ValueError, match="hier"):
        comm.MoEDispatchConfig(mode="flat", compress="int8")


def test_moe_step_rejects_foreign_meshes():
    lf = lambda wire: None  # noqa: E731 — never reached
    dp = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1}))
    with pytest.raises(ValueError, match="needs an ep axis"):
        comm.make_moe_comm_step(lf, mesh=dp)
    mixed = mesh_lib.make_mesh(mesh_lib.MeshSpec({"ep": -1, "tp": 2}))
    with pytest.raises(ValueError, match="ep-only"):
        comm.make_moe_comm_step(lf, mesh=mixed)


# -- the dispatch wire -------------------------------------------------------


def _ep_mesh(topo=None):
    if topo is not None:
        return mesh_lib.make_hybrid_mesh(mesh_lib.MeshSpec({"ep": -1}),
                                         topo)
    return mesh_lib.make_mesh(mesh_lib.MeshSpec({"ep": -1}))


def _run_a2a(x, **kw):
    """Drive moe_all_to_all under shard_map over the full ep axis."""
    mesh = _ep_mesh(kw.pop("topo", None))
    fn = functools.partial(comm.moe_all_to_all, axis="ep", **kw)
    return np.asarray(shard_map(fn, mesh=mesh, in_specs=(P("ep"),),
                                out_specs=P("ep"))(jnp.asarray(x)))


def test_hier_all_to_all_bitwise_with_flat():
    """The tentpole permutation claim, on the emulated 2x4 world: ICI
    leg + DCN leg == one flat collective, bitwise."""
    rng = np.random.default_rng(1)
    x = rng.normal(size=(WORLD * WORLD, 3, 5)).astype(np.float32)
    topo = mesh_lib.SliceTopology(2, 4)
    flat = _run_a2a(x, n_slices=2, chips=4, mode="flat", topo=topo)
    hier = _run_a2a(x, n_slices=2, chips=4, mode="hier", topo=topo)
    np.testing.assert_array_equal(flat, hier)
    # degenerate S=W decomposition (the flat-world compress path) is
    # the same permutation too
    hier_w = _run_a2a(x, n_slices=WORLD, chips=1, mode="hier")
    np.testing.assert_array_equal(flat, hier_w)


def test_hier_all_to_all_int8_bounded_and_per_dest_scaled():
    """int8 only touches the DCN leg, with one scale per (sender,
    destination-slice) chunk: payloads bound for different slices keep
    INDEPENDENT scales, so a slice receiving only small tokens gets a
    small-scale error bound — one global scale would crush it."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(WORLD * WORLD, 4, 4)).astype(np.float32)
    # destination-major rows w*W + d: everything bound for slice 0
    # (d < 4) is 100x the slice-1 payloads
    dest = np.arange(WORLD * WORLD) % WORLD
    x[dest < 4] *= 100.0
    topo = mesh_lib.SliceTopology(2, 4)
    dense = _run_a2a(x, n_slices=2, chips=4, mode="hier", topo=topo)
    q = _run_a2a(x, n_slices=2, chips=4, mode="hier", compress="int8",
                 topo=topo)
    assert q.dtype == np.float32
    err = np.abs(q - dense)
    # received rows w*W + s at chips of slice 1 carry only small
    # payloads: their bound follows the SMALL chunks' amax
    recv_chip = np.arange(WORLD * WORLD) // WORLD
    small = recv_chip >= 4
    small_amax = np.abs(dense[small]).max()
    assert err[small].max() <= small_amax / 254 * 1.05 + 1e-6
    # ...which is far tighter than a global-scale bound would allow
    assert np.abs(dense[~small]).max() / 254 > 10 * err[small].max()


def test_a2a_int8_wire_is_the_shared_quantizer():
    """ops/pack.all_to_all_int8 == per-destination pack_int8 +
    the same permutation, bitwise — so the interpret-mode kernel pin
    on pack_int8 (test_comm_overlap) covers this wire too."""
    from edl_tpu.ops.pack import all_to_all_int8, pack_int8, \
        unpack_int8
    rng = np.random.default_rng(3)
    g = WORLD
    x = rng.normal(size=(g * g, 6)).astype(np.float32)
    mesh = _ep_mesh()

    def wire(v):
        return all_to_all_int8(v, "ep")

    got = np.asarray(shard_map(wire, mesh=mesh, in_specs=(P("ep"),),
                               out_specs=P("ep"))(jnp.asarray(x)))
    # reference: quantize every destination block locally, permute
    # blocks exactly as the flat tiled all_to_all does
    per_chip = x.reshape(g, g, 6)
    rq = np.empty_like(per_chip)
    for s in range(g):
        for d_ in range(g):
            q, sc = pack_int8(jnp.asarray(per_chip[s, d_]))
            rq[s, d_] = np.asarray(unpack_int8(q, sc))
    want = rq.transpose(1, 0, 2).reshape(g * g, 6)
    np.testing.assert_array_equal(got, want)


def test_moe_wire_combine_inverts_dispatch():
    """combine(dispatch(buf)) == buf bitwise: the two transports are
    inverse permutations (all_to_all is an involution on the block
    grid), so a no-op expert returns every token slot untouched."""
    e, cap, d = WORLD * 2, 3, 4
    rng = np.random.default_rng(4)
    x = rng.normal(size=(WORLD * e, cap, d)).astype(np.float32)
    mesh = _ep_mesh()
    wire = comm.MoEWire(axis="ep", n_slices=2, chips=4,
                        config=comm.MoEDispatchConfig(mode="hier"))

    def fn(buf):
        recv = wire.dispatch(buf)
        assert recv.shape == (e // WORLD, WORLD * cap, d)
        return wire.combine(recv)

    out = np.asarray(shard_map(fn, mesh=mesh, in_specs=(P("ep"),),
                               out_specs=P("ep"))(jnp.asarray(x)))
    np.testing.assert_array_equal(out, x)


def test_moe_wire_rejects_indivisible_experts():
    wire = comm.MoEWire(axis="ep", n_slices=2, chips=4,
                        config=comm.MoEDispatchConfig())
    with pytest.raises(ValueError, match="not divisible by ep"):
        wire.dispatch(jnp.zeros((6, 2, 2)))  # 6 experts on 8 chips


def test_moe_leg_bytes_ratio():
    """The bench's acceptance arithmetic: hier+int8 moves ~4x (>= 3x)
    fewer cross-slice bytes than the dense leg, per leg."""
    blk, s, c = 5 * 4, 2, 4  # cap*d elements per destination block
    dense = comm.moe_leg_bytes(blk, 4, s, c, "off")
    int8 = comm.moe_leg_bytes(blk, 4, s, c, "int8")
    assert dense == (s - 1) * c * blk * 4
    assert int8 == (s - 1) * c * blk + (s - 1) * 4
    assert dense / int8 >= 3.0
    assert comm.moe_leg_bytes(blk, 4, 1, 8, "off") == 0  # single slice


# -- the parity gate through a real step -------------------------------------


def _tiny_moe(world: int, n_layers: int = 1):
    """Smallest trainable MoE problem: one block, E=2*world experts."""
    import optax
    from flax.core import meta
    from edl_tpu.train.state import TrainState

    vocab, seq = 16, 8
    rng = np.random.default_rng(5)
    toks = rng.integers(0, vocab, size=(2 * world, seq)).astype(np.int32)
    cfg = tfm.TransformerConfig(vocab_size=vocab, d_model=16, n_heads=2,
                                n_layers=n_layers, d_ff=32, max_len=seq,
                                dtype=jnp.float32, moe=True,
                                n_experts=2 * world, moe_top_k=2)
    model = tfm.Transformer(cfg)
    variables = meta.unbox(model.init(jax.random.PRNGKey(0),
                                      jnp.asarray(toks), train=False))
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"],
                              tx=optax.sgd(0.3, momentum=0.9))

    def loss_factory(wire):
        wired = tfm.Transformer(dataclasses.replace(cfg, moe_wire=wire))
        return functools.partial(tfm.lm_loss_moe,
                                 aux_weight=cfg.moe_aux_weight,
                                 apply_fn=wired.apply)

    return loss_factory, state, {"tokens": toks}


def test_moe_parity_gate_hier_bitwise_and_int8_enveloped():
    """The r21 gate on the dispatch wire: hier/off == flat/off bitwise
    through 2 full training steps on the emulated 2x4 world; the int8
    leg holds the loss envelope."""
    loss_factory, state, batch = _tiny_moe(WORLD)
    topo = mesh_lib.SliceTopology(2, 4)
    mesh = _ep_mesh(topo)
    gate = comm.moe_parity_gate(
        loss_factory, state, batch, mesh=mesh, topology=topo,
        comm_config=comm.CommConfig(bucket_mb=0.25),
        moe_config=comm.MoEDispatchConfig(mode="hier", compress="int8"),
        steps=2, envelope=0.2)
    assert gate["bitwise_hier"] is True
    assert gate["hier_loss_delta"] == 0.0
    assert gate["loss_envelope_ok"], gate
    assert gate["ok"]


def test_moe_step_stats_counter_and_span(monkeypatch):
    """The obs satellite: `step.moe_dispatch` spans every dispatch with
    the wire accounting, `step_moe_dcn_bytes` advances by the static
    per-step bytes, and stats() carries the bench columns."""
    from edl_tpu.obs import metrics as obs_metrics
    from edl_tpu.obs import trace

    calls = []

    @contextlib.contextmanager
    def fake_span(name, parent=None, attrs=None):
        calls.append((name, attrs))
        yield None

    monkeypatch.setattr(trace, "enabled", lambda: True)
    monkeypatch.setattr(trace, "span", fake_span)
    loss_factory, state, batch = _tiny_moe(WORLD)
    topo = mesh_lib.SliceTopology(2, 4)
    mesh = _ep_mesh(topo)
    step = comm.make_moe_comm_step(
        loss_factory, mesh=mesh, topology=topo, donate=False,
        config=comm.CommConfig(bucket_mb=0.25),
        moe_config=comm.MoEDispatchConfig(mode="hier", compress="int8"))
    counter = obs_metrics.registry().counter("step_moe_dcn_bytes")
    before = counter.value
    placed = mesh_lib.shard_batch(mesh, batch, batch_axes=("ep",))
    rep = lambda t: jax.device_put(  # noqa: E731
        t, NamedSharding(mesh, P()))
    s = jax.tree.map(rep, state)
    s, metrics = step(s, placed)
    s, metrics = step(s, placed)
    assert "loss" in metrics and "moe_dropped" in metrics

    stats = step.stats()
    assert stats["moe_dispatch"] == "hier"
    assert stats["moe_compress"] == "int8"
    # one layer = dispatch + combine legs
    assert stats["moe_dispatch_legs"] == 2
    assert stats["moe_dcn_bytes_per_step"] > 0
    assert stats["moe_dispatch_overlap_pct"] == 50.0
    assert counter.value - before \
        == 2 * stats["moe_dcn_bytes_per_step"]

    moe_spans = [(n, a) for n, a in calls if n == "step.moe_dispatch"]
    assert len(moe_spans) == 2
    assert moe_spans[-1][1]["mode"] == "hier"
    assert moe_spans[-1][1]["compress"] == "int8"
    assert moe_spans[-1][1]["moe_dcn_bytes"] \
        == stats["moe_dcn_bytes_per_step"]

    # byte accounting vs the flat baseline: >= 3x fewer DCN bytes
    flat = comm.make_moe_comm_step(
        loss_factory, mesh=mesh, topology=topo, donate=False,
        config=comm.CommConfig(bucket_mb=0.25),
        moe_config=comm.MoEDispatchConfig(mode="flat"))
    s2 = jax.tree.map(rep, state)
    flat(s2, placed)
    assert flat.moe_dcn_bytes_per_step() \
        >= 3 * stats["moe_dcn_bytes_per_step"]
