"""Continuous batching + admission-controlled serving (r23).

Four tiers, mirroring the subsystem's layering:

  * `AdmissionQueue` logic under a fake clock — WFQ ordering, bounded
    per-tenant queues, the per-class delay-budget shed rule, and the
    drain flip (pure stdlib, no sockets);
  * `Batcher` behavior — the stats() schema PIN (the r15 scaler, drain
    poller, and registrar all consume these keys), continuous-mode
    idle latency vs the window batcher, and coalescing under a busy
    pipeline;
  * the wire + pool tier — typed reject-with-retry-after over a real
    socket, the reader's bounded shed-retry ladder, and a graceful
    drain under continuous batching through `TeacherPoolActuator`
    with ZERO hard kills;
  * the control plane — registrar per-class windowed publish,
    collector per-class rollup, the policy's shed-blinded-breach rule,
    the balancer's class-weighted tie-break, and the obs renderer's
    ``_by_class`` label promotion.
"""

import json
import threading
import time

import numpy as np
import pytest

from edl_tpu.coord.collector import Collector
from edl_tpu.coord.registry import ServiceRegistry
from edl_tpu.coord.store import InMemStore
from edl_tpu.distill.admission import (AdmissionConfig, AdmissionQueue,
                                       AdmissionReject, RETRY_AFTER_MAX_MS,
                                       RETRY_AFTER_MIN_MS,
                                       parse_class_weights)
from edl_tpu.distill.balance import ServiceBalance
from edl_tpu.distill.teacher_server import (Batcher, TeacherClient,
                                            TeacherRejected, TeacherServer)
from edl_tpu.scaler.serving import (ServingConfig, ServingPolicy,
                                    ServingView)

ROOT = "edl_distill"


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def echo_predict(feeds):
    rows = next(iter(feeds.values())).shape[0]
    return {"logits": np.zeros((rows, 2), np.float32)}


def feed(rows: int = 4, feat: int = 2) -> dict:
    return {"x": np.zeros((rows, feat), np.float32)}


# -- admission queue (logic tier, fake clock) --------------------------------


class TestAdmissionQueue:
    def make(self, clock=None, **kw):
        return AdmissionQueue(AdmissionConfig(**kw),
                              clock=clock or FakeClock())

    def test_fifo_within_one_flow(self):
        q = self.make()
        for i in range(5):
            q.submit(i, rows=1, tenant="a", priority="normal")
        assert [q.get_nowait() for _ in range(5)] == list(range(5))
        assert q.get_nowait() is None

    def test_wfq_shares_track_class_weights(self):
        """Drain a backlog where every class has equal demand: the pop
        stream interleaves by weight (4:2:1), so between any two low
        pops ~4 high pops land — not strict priority, not FIFO."""
        q = self.make(class_weights="high=4,normal=2,low=1")
        for i in range(12):
            q.submit(("high", i), 1, "t", "high")
            q.submit(("normal", i), 1, "t", "normal")
            q.submit(("low", i), 1, "t", "low")
        first_14 = [q.get_nowait()[0] for _ in range(14)]
        counts = {c: first_14.count(c) for c in ("high", "normal", "low")}
        assert counts["high"] == 8 and counts["normal"] == 4 \
            and counts["low"] == 2, counts
        # the backlog drains completely (work-conserving)
        rest = [q.get_nowait() for _ in range(3 * 12 - 14)]
        assert all(item is not None for item in rest)

    def test_idle_flow_does_not_bank_credit(self):
        """A flow idle while others drained must re-enter at the
        CURRENT virtual time — not replay its stale credit and
        monopolize the scheduler."""
        q = self.make(class_weights="high=1,normal=1,low=1")
        for i in range(50):
            q.submit(("a", i), 1, "a", "normal")
        for _ in range(50):
            q.get_nowait()   # vclock advanced to 50
        q.submit(("b", 0), 1, "b", "normal")   # fresh flow
        q.submit(("a", 50), 1, "a", "normal")  # old flow, same vtime rule
        got = {q.get_nowait()[0], q.get_nowait()[0]}
        assert got == {"a", "b"}

    def test_queue_cap_rejects_with_retry_hint(self):
        q = self.make(queue_cap=2)
        q.submit(1, 1, "a", "low")
        q.submit(2, 1, "a", "low")
        with pytest.raises(AdmissionReject) as exc:
            q.submit(3, 1, "a", "low")
        assert exc.value.reason == "queue-full"
        assert RETRY_AFTER_MIN_MS <= exc.value.retry_after_ms \
            <= RETRY_AFTER_MAX_MS
        # the cap is per (class, tenant) flow: another tenant admits
        q.submit(4, 1, "b", "low")

    def test_overload_sheds_low_before_high(self):
        """Warm the rate estimate, pile rows onto every class, and the
        delay-budget rule (budget scales with class weight) sheds the
        low class while high still admits."""
        clock = FakeClock()
        q = self.make(clock=clock, shed_ms=100.0,
                      class_weights="high=4,normal=2,low=1")
        q.note_served(64)
        clock.advance(1.0)
        q.note_served(64)   # ~64-128 rows/s measured rate
        for cls in ("high", "normal", "low"):
            q.submit((cls, "seed"), 8, "t", cls)
        # low budget = 50 ms; its 8-row backlog against its 1/7 WFQ
        # share of ~100 rows/s is ~550 ms of wait -> shed
        with pytest.raises(AdmissionReject) as exc:
            q.submit(("low", 1), 8, "t", "low")
        assert exc.value.reason == "overload"
        # high budget = 400 ms and a 4/7 share: same backlog admits
        q.submit(("high", 1), 8, "t", "high")

    def test_shed_rule_disarmed_cold_and_by_default(self):
        clock = FakeClock()
        q = self.make(clock=clock, shed_ms=50.0)
        # no served rows yet: rate unknown -> never shed on a guess
        for i in range(20):
            q.submit(i, 8, "t", "low")
        q2 = self.make()   # shed_ms=0 (default): rule off entirely
        q2.note_served(1000)
        for i in range(20):
            q2.submit(i, 8, "t", "low")

    def test_drain_flips_submits_to_typed_reject(self):
        q = self.make()
        q.submit("queued", 1, "t", "normal")
        q.begin_drain()
        with pytest.raises(AdmissionReject) as exc:
            q.submit("late", 1, "t", "normal")
        assert exc.value.reason == "draining"
        # already-admitted work still drains normally
        assert q.get_nowait() == "queued"
        assert q.stats()["draining"] == 1

    def test_unknown_priority_degrades_to_normal(self):
        q = self.make()
        q.submit("x", 1, "t", "platinum")
        q.submit("y", 1, "t", None)
        assert q.stats()["queue_depth_by_class"]["normal"] == 2

    def test_stats_counters(self):
        q = self.make(queue_cap=1)
        q.submit(1, 2, "a", "high")
        q.submit(2, 3, "b", "low")
        for _ in range(2):
            with pytest.raises(AdmissionReject):
                q.submit(3, 1, "b", "low")
        s = q.stats()
        assert s["admitted_total"] == 2 and s["rejected_total"] == 2
        assert s["rejected_by_class"]["low"] == 2
        assert s["rejected_by_reason"] == {"queue-full": 2}
        assert s["queue_depth_by_class"] == {"high": 1, "normal": 0,
                                             "low": 1}
        assert s["queue_depth_by_tenant"] == {"a": 1, "b": 1}

    def test_get_timeout_and_close(self):
        q = AdmissionQueue(AdmissionConfig())   # real clock: get() sleeps
        t0 = time.monotonic()
        assert q.get(timeout=0.05) is None
        assert time.monotonic() - t0 < 2.0
        q.close()
        assert q.get(timeout=10.0) is None   # returns, no hang

    def test_parse_class_weights_tolerates_junk(self):
        w = parse_class_weights("high=3,bogus,low=x,platinum=9,normal=-1")
        assert w == {"high": 3.0, "normal": 1.0, "low": 1.0}

    def test_config_env_registry(self, monkeypatch):
        monkeypatch.setenv("EDL_TPU_SERVE_BATCHING", "window")
        monkeypatch.setenv("EDL_TPU_SERVE_ADMIT_CAP", "7")
        monkeypatch.setenv("EDL_TPU_SERVE_SHED_MS", "33.5")
        cfg = AdmissionConfig.from_env()
        assert cfg.batching == "window" and cfg.queue_cap == 7
        assert cfg.shed_ms == 33.5


# -- batcher: schema pin + continuous vs window ------------------------------


PINNED_STATS_KEYS = {
    # the r6/r15 contract: scaler drain poller + registrar consume these
    "served_rows", "served_requests", "busy_s", "uptime_s",
    "queue_depth", "inflight_groups", "pending_hwm",
    "coalesce_window_ms", "batch_rows_hist", "batch_rows_mean",
    "latency_hist_ms", "latency_ms_p50", "latency_ms_p95",
    # r23 additions (admission + per-class split)
    "batching", "admitted_total", "rejected_total", "rejected_by_class",
    "rejected_by_reason", "queue_depth_by_class", "queue_depth_by_tenant",
    "draining", "latency_hist_ms_by_class", "latency_ms_p95_by_class",
}


class TestBatcher:
    def test_stats_schema_pin(self):
        """The stats() key set is a contract — additions are fine ONLY
        via this pin; removals/renames break the scaler's drain poller,
        the registrar differencing, and the obs gauges silently."""
        b = Batcher(echo_predict, max_batch=8,
                    admission=AdmissionConfig()).start()
        try:
            req = b.submit(feed(4), tenant="a", priority="high")
            assert req.done.wait(timeout=5.0) and req.error is None
            s = b.stats()
        finally:
            b.stop()
        assert set(s) == PINNED_STATS_KEYS, (
            f"missing={PINNED_STATS_KEYS - set(s)} "
            f"extra={set(s) - PINNED_STATS_KEYS}")
        assert s["served_rows"] == 4 and s["served_requests"] == 1
        assert s["batching"] == "continuous"
        assert s["admitted_total"] == 1 and s["rejected_total"] == 0
        # JSON-shaped: one-level dicts with string keys, scalars else
        for key in ("batch_rows_hist", "latency_hist_ms",
                    "queue_depth_by_class", "rejected_by_class"):
            assert all(isinstance(k, str) for k in s[key])

    def test_continuous_idle_latency_beats_window(self):
        """An idle continuous batcher dispatches a lone request
        immediately; the window batcher holds it for max_wait. The
        microscopic version of the bench's p95 acceptance gate."""
        lat = {}
        for mode in ("continuous", "window"):
            b = Batcher(echo_predict, max_batch=8, max_wait=0.08,
                        admission=AdmissionConfig(batching=mode)).start()
            try:
                t0 = time.monotonic()
                req = b.submit(feed(2))
                assert req.done.wait(timeout=5.0) and req.error is None
                lat[mode] = time.monotonic() - t0
            finally:
                b.stop()
        assert lat["continuous"] < 0.04, lat
        assert lat["window"] >= 0.06, lat

    def test_continuous_coalesces_against_busy_pipeline(self):
        """While the pipeline computes, newly-arrived requests join the
        FORMING group — the iteration-level admission that makes
        saturated batches dense instead of degenerate singletons."""
        release = threading.Event()

        def gated(feeds):
            release.wait(timeout=10.0)
            return echo_predict(feeds)

        b = Batcher(gated, max_batch=64, stage_depth=1,
                    max_wait_cap=2.0,
                    admission=AdmissionConfig(batching="continuous")).start()
        try:
            reqs = [b.submit(feed(4))]   # group 1 -> compute (gated)
            time.sleep(0.15)
            reqs.append(b.submit(feed(4)))   # group 2 fills the stage queue
            time.sleep(0.15)
            # pipeline full: these all merge into ONE forming group
            reqs += [b.submit(feed(4)) for _ in range(5)]
            time.sleep(0.15)
            release.set()
            for r in reqs:
                assert r.done.wait(timeout=5.0) and r.error is None
            hist = {int(k): v for k, v in
                    b.stats()["batch_rows_hist"].items()}
        finally:
            release.set()
            b.stop()
        # 7 requests, but only 3 device batches: 4 + 4 + 20 merged
        assert sum(hist.values()) == 3, hist
        assert max(hist) == 20, hist

    def test_unknown_batching_mode_raises(self):
        with pytest.raises(ValueError):
            Batcher(echo_predict,
                    admission=AdmissionConfig(batching="magic"))

    def test_drain_rejects_while_inflight_completes(self):
        release = threading.Event()

        def gated(feeds):
            release.wait(timeout=10.0)
            return echo_predict(feeds)

        b = Batcher(gated, max_batch=8).start()
        try:
            req = b.submit(feed(2))
            time.sleep(0.1)
            b.begin_drain()
            with pytest.raises(AdmissionReject) as exc:
                b.submit(feed(2))
            assert exc.value.reason == "draining"
            release.set()
            assert req.done.wait(timeout=5.0) and req.error is None
            assert b.stats()["draining"] == 1
        finally:
            release.set()
            b.stop()


# -- wire tier: typed rejection + reader retry ladder ------------------------


class TestWireRejection:
    def test_client_raises_typed_reject_with_retry_after(self):
        with TeacherServer(echo_predict, port=0,
                           host="127.0.0.1") as server:
            client = TeacherClient(f"127.0.0.1:{server.port}",
                                   timeout=5.0)
            try:
                out = client.predict(feed(3))
                assert out["logits"].shape == (3, 2)
                assert client.drain() is True   # op: drain over the wire
                with pytest.raises(TeacherRejected) as exc:
                    client.predict(feed(3))
                assert exc.value.reason == "draining"
                assert exc.value.retry_after_ms >= RETRY_AFTER_MIN_MS
                assert exc.value.retry_after_s == pytest.approx(
                    exc.value.retry_after_ms / 1e3)
                # the connection survived the rejection (typed response,
                # not a reset): control ops still answer
                assert client.stats()["draining"] == 1
            finally:
                client.close()

    def test_priority_and_tenant_ride_the_wire(self):
        with TeacherServer(echo_predict, port=0,
                           host="127.0.0.1") as server:
            client = TeacherClient(f"127.0.0.1:{server.port}",
                                   timeout=5.0, tenant="acme",
                                   priority="high")
            try:
                client.predict(feed(2))
                s = client.stats()
            finally:
                client.close()
            assert s["queue_depth_by_class"] == {"high": 0, "normal": 0,
                                                 "low": 0}
            hist = s["latency_hist_ms_by_class"]["high"]
            assert sum(hist.values()) == 1   # counted under its class


class _SheddingClient:
    """Fake teacher that rejects the first ``sheds`` predicts."""

    def __init__(self, endpoint, sheds, log_):
        self.endpoint = endpoint
        self.sheds = sheds
        self.log = log_

    def predict(self, feeds):
        if self.sheds > 0:
            self.sheds -= 1
            self.log.append("shed")
            raise TeacherRejected("busy", retry_after_ms=25.0)
        self.log.append("ok")
        return {"p": np.zeros((feeds["image"].shape[0], 1), np.float32)}

    def close(self):
        pass


class TestReaderShedRetry:
    def make_batches(self, n=3, rows=8):
        return [{"image": np.ones((rows, 4), np.float32) * b}
                for b in range(n)]

    def test_shed_then_recover_within_budget(self):
        from edl_tpu.distill.reader import DistillReader
        calls: list[str] = []
        batches = self.make_batches()
        dr = DistillReader(lambda: iter(batches), feeds=["image"],
                           predicts=["p"], teachers=["t0"],
                           teacher_batch_size=8, shed_retry_budget=8,
                           client_factory=lambda ep: _SheddingClient(
                               ep, sheds=2, log_=calls))
        got = list(dr())
        assert len(got) == len(batches)
        assert calls.count("shed") == 2   # retried, never surfaced

    def test_budget_exhaustion_fails_typed(self):
        from edl_tpu.distill.reader import DistillReader, EdlDistillError
        batches = self.make_batches(n=1)
        dr = DistillReader(lambda: iter(batches), feeds=["image"],
                           predicts=["p"], teachers=["t0"],
                           teacher_batch_size=8, shed_retry_budget=1,
                           client_factory=lambda ep: _SheddingClient(
                               ep, sheds=99, log_=[]))
        with pytest.raises(EdlDistillError, match="shedding"):
            list(dr())


# -- pool tier: graceful drain under continuous batching ---------------------


class TestDrainUnderContinuous:
    def test_actuator_drain_zero_hard_kills(self):
        """Scale-down with continuous batching live: the victim's
        in-flight request completes, NEW submits to it reject typed,
        and the drain log shows graceful completion — no hard kill."""
        from edl_tpu.distill.registrar import TeacherRegistrar
        from edl_tpu.scaler.serving import LocalTeacher, TeacherPoolActuator
        store = InMemStore()
        gate = threading.Event()
        teachers = []

        def spawn(i):
            def predict(feeds):
                if i == 1:
                    gate.wait(timeout=10.0)
                return echo_predict(feeds)
            server = TeacherServer(
                predict, port=0, host="127.0.0.1", max_batch=16,
                admission=AdmissionConfig(batching="continuous")).start()
            registrar = TeacherRegistrar(store, "svc",
                                         f"127.0.0.1:{server.port}",
                                         ttl=5.0, stats_interval=0.1)
            registrar.start()
            t = LocalTeacher(server, registrar)
            teachers.append(t)
            return t

        actuator = TeacherPoolActuator(spawn, max_teachers=4,
                                       drain_deadline_s=10.0,
                                       drain_poll_s=0.02, service="svc")
        try:
            actuator.resize(2)
            victim = teachers[1]   # LIFO retirement
            client = TeacherClient(victim.endpoint, timeout=10.0)
            pending = client.predict_async(feed(4))   # parked on gate
            time.sleep(0.15)
            actuator.resize(1)
            time.sleep(0.2)
            # drain-mode admission: the victim now rejects new work —
            # probed on a SECOND connection (responses are FIFO per
            # connection; the first one's head is parked on the gate)
            probe = TeacherClient(victim.endpoint, timeout=10.0)
            with pytest.raises(TeacherRejected):
                probe.predict(feed(4))
            probe.close()
            gate.set()
            out = pending.result()   # in-flight completed, no reset
            assert out["logits"].shape == (4, 2)
            assert actuator.wait_drains(timeout=10.0)
            (entry,) = actuator.drain_log
            assert entry["drained"] and not entry["hard_killed"], entry
            client.close()
        finally:
            gate.set()
            actuator.close()


# -- control plane: registrar -> rollup -> policy / balance / obs ------------


class TestRegistrarPerClass:
    def test_windowed_per_class_publish(self):
        from edl_tpu.distill.registrar import TeacherRegistrar
        registrar = TeacherRegistrar(InMemStore(), "svc", "h:1")
        prev = {"served_rows": 100, "busy_s": 1.0,
                "latency_hist_ms": {"10.0": 100},
                "latency_hist_ms_by_class": {"high": {"10.0": 100}},
                "rejected_total": 10,
                "rejected_by_class": {"low": 10}}
        cur = {"served_rows": 200, "busy_s": 2.0, "queue_depth": 3,
               "latency_hist_ms": {"10.0": 100, "500.0": 50},
               "latency_hist_ms_by_class": {
                   "high": {"10.0": 100, "500.0": 20},
                   "low": {"500.0": 30}},
               "rejected_total": 60,
               "rejected_by_class": {"low": 45, "normal": 5},
               "queue_depth_by_class": {"high": 1, "low": 2},
               "draining": 1}
        info = json.loads(registrar._utilization_info(cur, prev, dt=5.0))
        # per-class p95 is the WINDOW (high's fast past subtracted out)
        assert info["latency_ms_p95_by_class"] == {"high": 500.0,
                                                   "low": 500.0}
        assert info["shed_per_sec"] == 10.0      # 50 rejects / 5 s
        assert info["shed_by_class"] == {"low": 35, "normal": 5}
        assert info["queue_depth_by_class"] == {"high": 1, "low": 2}
        assert info["draining"] == 1


class TestRollupPerClass:
    def test_rollup_sums_shed_and_merges_per_class(self):
        store = InMemStore()
        registry = ServiceRegistry(store, root=ROOT)
        registry.register_permanent("svc", "h:1", info=json.dumps(
            {"rows_per_sec": 100.0, "util": 0.5, "queue_depth": 2,
             "latency_ms_p95": 40.0, "shed_per_sec": 1.5,
             "queue_depth_by_class": {"high": 1, "low": 1},
             "latency_ms_p95_by_class": {"high": 30.0, "low": 40.0},
             "draining": 0}))
        registry.register_permanent("svc", "h:2", info=json.dumps(
            {"rows_per_sec": 80.0, "util": 0.7, "queue_depth": 4,
             "latency_ms_p95": 90.0, "shed_per_sec": 2.0,
             "queue_depth_by_class": {"high": 2, "normal": 2},
             "latency_ms_p95_by_class": {"high": 80.0},
             "draining": 1}))
        roll = Collector(store, services=("svc",),
                         registry_root=ROOT).service_rollup("svc")
        assert roll["shed_per_sec"] == 3.5            # pool sum
        assert roll["queue_depth_by_class"] == {"high": 3, "low": 1,
                                                "normal": 2}
        # worst reporting teacher per class (same rule as the flat p95)
        assert roll["latency_ms_p95_by_class"] == {"high": 80.0,
                                                   "low": 40.0}
        assert roll["draining"] == 1


class TestPolicyShedBreach:
    def make_view(self, **kw):
        kw.setdefault("service", "svc")
        kw.setdefault("n_teachers", 2)
        kw.setdefault("rows_per_sec", 100.0)
        kw.setdefault("latency_ms_p95", 50.0)   # healthy latency
        kw.setdefault("slo_p95_ms", 250.0)
        return ServingView(**kw)

    def test_healthy_p95_but_shedding_is_a_breach(self):
        """The anti-blindness rule: an admission-controlled pool holds
        p95 in-SLO by REJECTING — sustained shed is overload."""
        policy = ServingPolicy(ServingConfig(breach_ticks=2,
                                             cooldown_s=0.0))
        view = self.make_view(shed_per_sec=5.0)
        (p1,) = policy.decide([view], now=1.0)
        assert p1.reason == "in-band"   # one breach tick: no action yet
        (p2,) = policy.decide([view], now=2.0)
        assert p2.reason == "slo-breach-grow"
        # grow factor covers OFFERED load: (100 + 5) / 100 ~ 1.05 ->
        # still at least +1 teacher
        assert p2.desired >= 3

    def test_shed_below_threshold_stays_in_band(self):
        policy = ServingPolicy(ServingConfig(breach_ticks=1,
                                             cooldown_s=0.0))
        view = self.make_view(shed_per_sec=0.2, util=0.6)
        (p,) = policy.decide([view], now=1.0)
        assert p.reason == "in-band"

    def test_shed_grow_scales_with_offered_over_served(self):
        policy = ServingPolicy(ServingConfig(breach_ticks=1,
                                             cooldown_s=0.0))
        # shedding as much as it serves -> offered/served = 2x
        view = self.make_view(shed_per_sec=100.0)
        (p,) = policy.decide([view], now=1.0)
        assert p.desired == 4   # 2 teachers * 2.0 factor


class TestBalanceClassTieBreak:
    def test_queued_high_outweighs_queued_low(self):
        """Equal flat depth, different class mix: the teacher with the
        queued HIGH work is the busier tie-break candidate."""
        bal = ServiceBalance("svc")
        bal.set_utilization({"a:1": 0.5, "b:1": 0.5},
                            queue_depth={"a:1": 4, "b:1": 4},
                            queue_depth_by_class={
                                "a:1": {"high": 4},
                                "b:1": {"low": 4}})
        assert bal._busy("a:1") > bal._busy("b:1")
        # class-split replaces the flat term; unknown class falls back
        bal2 = ServiceBalance("svc")
        bal2.set_utilization({"c:1": 0.0},
                             queue_depth_by_class={"c:1": {"gold": 2}})
        assert bal2._busy("c:1") == pytest.approx(
            0.0 + ServiceBalance.QUEUE_WEIGHT * 2)


class TestObsByClassLabels:
    def test_render_promotes_by_class_suffix_to_label(self):
        from edl_tpu.obs.metrics import Registry
        reg = Registry(namespace="edl")
        reg.register_stats("teacher", lambda: {
            "queue_depth": 3,
            "queue_depth_by_class": {"high": 1, "low": 2},
            "rejected_by_tenant": {"acme": 7}})
        text = reg.render()
        assert 'edl_teacher_queue_depth{iid="0"} 3' in text
        assert ('edl_teacher_queue_depth_by_class{iid="0",class="high"} 1'
                in text)
        assert ('edl_teacher_rejected_by_tenant{iid="0",tenant="acme"} 7'
                in text)
