"""The C++ edl-store daemon must be protocol- and semantics-identical to
the Python server: the same StoreClient + registry + barrier flows run
against it, plus what only it provides — WAL/snapshot durability across a
SIGKILL.

(The SURVEY §2.2 native contract: the Go master's etcd state store role,
pkg/master/etcd_client.go:49-176, filled by a C++ daemon.)
"""

import json
import os
import signal
import subprocess
import time

import pytest

from edl_tpu.collective import barrier as bar
from edl_tpu.collective import register as reg
from edl_tpu.collective.cluster import Pod
from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.registry import ServiceRegistry
from edl_tpu.utils import net
from edl_tpu.utils.exceptions import EdlLeaseExpired

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native", "store")
BINARY = os.path.join(NATIVE_DIR, "edl-store")


@pytest.fixture(scope="session")
def binary():
    build = subprocess.run(["make", "-C", NATIVE_DIR], capture_output=True,
                           text=True)
    assert build.returncode == 0, f"native build failed:\n{build.stderr}"
    return BINARY


def start_daemon(binary, tmp_path, *, data_dir=None, port=None,
                 extra=()):
    port = port or net.free_port()
    cmd = [binary, "--host", "127.0.0.1", "--port", str(port),
           "--sweep-interval", "0.05", *extra]
    if data_dir is not None:
        cmd += ["--data-dir", str(data_dir)]
    proc = subprocess.Popen(cmd, stdout=open(tmp_path / "native.log", "ab"),
                            stderr=subprocess.STDOUT)
    client = StoreClient(f"127.0.0.1:{port}", timeout=5.0)
    deadline = time.time() + 10
    while time.time() < deadline:
        if client.ping():
            return proc, client, port
        time.sleep(0.1)
    proc.kill()
    pytest.fail("edl-store never came up")


@pytest.fixture
def daemon(binary, tmp_path):
    proc, client, port = start_daemon(binary, tmp_path)
    yield client
    client.close()
    proc.terminate()
    proc.wait(timeout=5)


def test_roundtrip_and_prefix(daemon):
    r1 = daemon.put("/a/x", "1")
    r2 = daemon.put("/a/y", "2")
    daemon.put("/b/z", "3")
    assert r2 == r1 + 1
    assert daemon.get("/a/x").value == "1"
    recs, rev = daemon.get_prefix("/a/")
    assert [(r.key, r.value) for r in recs] == [("/a/x", "1"), ("/a/y", "2")]
    assert rev >= r2
    assert daemon.delete("/a/x")
    assert not daemon.delete("/a/x")
    assert daemon.delete_prefix("/a/") == 1
    assert daemon.get_prefix("/a/")[0] == []


def test_unicode_and_json_values(daemon):
    # Pod records are JSON-in-JSON with quotes/escapes; registry info may
    # carry non-ASCII.
    value = json.dumps({"pod": 'quote"backslash\\', "emoji": "é中"})
    daemon.put("/u", value)
    assert daemon.get("/u").value == value
    daemon.put("/u2", "line\nbreak\ttab\x01ctl")
    assert daemon.get("/u2").value == "line\nbreak\ttab\x01ctl"


def test_cas_and_put_if_absent(daemon):
    assert daemon.put_if_absent("/k", "a")
    assert not daemon.put_if_absent("/k", "b")
    assert daemon.get("/k").value == "a"
    assert not daemon.compare_and_swap("/k", "wrong", "c")
    assert daemon.compare_and_swap("/k", "a", "c")
    assert daemon.get("/k").value == "c"
    # expect=None means "must be absent".
    assert not daemon.compare_and_swap("/k", None, "d")
    assert daemon.compare_and_swap("/new", None, "d")


def test_lease_expiry_emits_delete_events(daemon):
    lease = daemon.lease_grant(0.3)
    daemon.put("/leased", "v", lease=lease)
    _, rev, _ = daemon.events_since(0)
    time.sleep(0.8)   # sweeper interval 0.05 + ttl
    assert daemon.get("/leased") is None
    events, _, compacted = daemon.events_since(rev - 1)
    assert not compacted
    assert any(e.type == "DELETE" and e.key == "/leased" for e in events)


def test_lease_keepalive_extends(daemon):
    lease = daemon.lease_grant(0.4)
    daemon.put("/ka", "v", lease=lease)
    for _ in range(5):
        time.sleep(0.2)
        assert daemon.lease_keepalive(lease)
    assert daemon.get("/ka").value == "v"
    assert daemon.lease_revoke(lease)
    assert daemon.get("/ka") is None


def test_typed_lease_error_over_wire(daemon):
    lease = daemon.lease_grant(5.0)
    daemon.lease_revoke(lease)
    with pytest.raises(EdlLeaseExpired):
        daemon.put("/dead", "1", lease=lease)


def test_registry_and_barrier_flows(daemon):
    # The launcher-critical paths: service registration + rank claim +
    # leader-published cluster barrier, all through the native daemon.
    registry = ServiceRegistry(daemon, root="edl_distill")
    registration = registry.register("svc", "127.0.0.1:9000", ttl=2.0)
    assert [m.server for m in registry.get_service("svc")] \
        == ["127.0.0.1:9000"]

    regs = []
    for i in range(2):
        pod = Pod(pod_id=f"pod{i}", addr="127.0.0.1", port=21000 + i)
        r = reg.PodRegister(daemon, "njob", pod, ttl=2.0)
        r.claim()
        regs.append(r)
    cluster = bar.cluster_barrier(daemon, "njob", "pod0", stable_secs=0.2,
                                  timeout=15.0)
    assert cluster.world_size == 2 and cluster.version == 1
    regs[1].release()
    c2 = bar.cluster_barrier(daemon, "njob", "pod0", after_version=1,
                             stable_secs=0.2, timeout=15.0)
    assert c2.version == 2 and c2.pod_ids() == {"pod0"}
    regs[0].release()
    registration.stop()


def test_durability_across_sigkill(binary, tmp_path):
    data_dir = tmp_path / "store-data"
    proc, client, port = start_daemon(binary, tmp_path, data_dir=data_dir)
    try:
        client.put("/persist/a", "1")
        client.put("/persist/b", "2")
        lease = client.lease_grant(1.0)
        client.put("/ephemeral", "x", lease=lease)
        rev_before = client.get("/persist/b").revision
    finally:
        os.kill(proc.pid, signal.SIGKILL)   # no graceful flush
        proc.wait(timeout=5)
        client.close()

    proc2, client2, _ = start_daemon(binary, tmp_path, data_dir=data_dir,
                                     port=port)
    try:
        assert client2.get("/persist/a").value == "1"
        assert client2.get("/persist/b").value == "2"
        assert client2.get("/persist/b").revision == rev_before
        # Leased key comes back under a grace TTL, then expires (its owner
        # died with the old process and nobody keeps it alive).
        time.sleep(2.0)
        assert client2.get("/ephemeral") is None
        # New mutations take revisions after the replayed history.
        assert client2.put("/persist/c", "3") > rev_before
    finally:
        proc2.terminate()
        proc2.wait(timeout=5)
        client2.close()


def test_snapshot_compaction_and_restart(binary, tmp_path):
    data_dir = tmp_path / "snap-data"
    proc, client, port = start_daemon(
        binary, tmp_path, data_dir=data_dir,
        extra=("--snapshot-every", "50", "--no-fsync"))
    try:
        for i in range(120):   # crosses 2 snapshot thresholds
            client.put(f"/k/{i:04d}", str(i))
        client.delete_prefix("/k/000")   # deletes 0000..0009
    finally:
        proc.terminate()
        proc.wait(timeout=5)
        client.close()
    assert (data_dir / "snapshot.json").exists()

    proc2, client2, _ = start_daemon(binary, tmp_path, data_dir=data_dir,
                                     port=port)
    try:
        recs, _ = client2.get_prefix("/k/")
        assert len(recs) == 110
        assert client2.get("/k/0119").value == "119"
        assert client2.get("/k/0005") is None
    finally:
        proc2.terminate()
        proc2.wait(timeout=5)
        client2.close()


def test_crash_between_snapshot_rename_and_wal_truncate(binary, tmp_path):
    """The snapshot-then-truncate window: a crash after the snapshot rename
    but before the WAL truncation leaves the whole pre-snapshot WAL on
    disk. Replay must skip ops the snapshot already contains (seq stamps)
    or revisions re-bump and diverge from pre-crash values."""
    data_dir = tmp_path / "window-data"
    proc, client, port = start_daemon(
        binary, tmp_path, data_dir=data_dir,
        extra=("--snapshot-every", "5", "--no-fsync"))
    try:
        for i in range(4):                      # WAL: seq 1..4, no snapshot
            client.put(f"/w/{i}", str(i))
        pre_snapshot_wal = (data_dir / "wal.log").read_bytes()
        client.put("/w/4", "4")                 # 5th append -> snapshot, WAL truncated
        assert (data_dir / "snapshot.json").exists()
        client.put("/w/5", "5")                 # post-snapshot WAL: seq 6
        revs = {k: client.get(k).revision for k in
                ("/w/0", "/w/3", "/w/5")}
        last_rev = client.get("/w/5").revision
    finally:
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=5)
        client.close()

    # Reconstruct the crash window: the old WAL lines sit in front of the
    # post-snapshot ones, exactly as if truncation never happened.
    post_wal = (data_dir / "wal.log").read_bytes()
    (data_dir / "wal.log").write_bytes(pre_snapshot_wal + post_wal)

    proc2, client2, _ = start_daemon(binary, tmp_path, data_dir=data_dir,
                                     port=port)
    try:
        for key, rev in revs.items():
            rec = client2.get(key)
            assert rec.value == key[-1]
            assert rec.revision == rev, \
                f"{key} revision re-bumped by duplicate WAL replay"
        # The global counter also survives un-bumped.
        assert client2.put("/w/new", "n") == last_rev + 1
    finally:
        proc2.terminate()
        proc2.wait(timeout=5)
        client2.close()


@pytest.fixture(scope="session")
def tsan_binary():
    build = subprocess.run(["make", "-C", NATIVE_DIR, "tsan"],
                           capture_output=True, text=True)
    if build.returncode != 0:
        pytest.skip(f"tsan build unavailable:\n{build.stderr[-500:]}")
    return os.path.join(NATIVE_DIR, "edl-store-tsan")


def test_concurrent_clients_under_tsan(tsan_binary, tmp_path):
    """SURVEY §5 sanitizers: hammer the mutex-per-op store + sweeper thread
    + thread-per-connection server from concurrent clients under
    ThreadSanitizer; any data race aborts the daemon (halt_on_error)."""
    import threading

    port = net.free_port()
    log_path = tmp_path / "tsan.log"
    env = dict(os.environ,
               TSAN_OPTIONS="halt_on_error=1 exitcode=66 abort_on_error=0")
    proc = subprocess.Popen(
        [tsan_binary, "--host", "127.0.0.1", "--port", str(port),
         "--sweep-interval", "0.01", "--data-dir", str(tmp_path / "td"),
         "--no-fsync", "--snapshot-every", "40"],
        stdout=open(log_path, "ab"), stderr=subprocess.STDOUT, env=env)
    client = StoreClient(f"127.0.0.1:{port}", timeout=10.0)
    deadline = time.time() + 20
    while time.time() < deadline and not client.ping():
        time.sleep(0.1)
    assert client.ping(), "tsan daemon never came up"
    client.close()

    errors = []

    def worker(wid: int):
        try:
            c = StoreClient(f"127.0.0.1:{port}", timeout=10.0)
            for i in range(60):
                c.put(f"/stress/{wid}/{i % 7}", str(i))
                c.get(f"/stress/{(wid + 1) % 6}/{i % 7}")
                if i % 5 == 0:
                    lease = c.lease_grant(0.05)   # sweeper races on purpose
                    try:
                        c.put(f"/stress/lease/{wid}", "x", lease=lease)
                    except EdlLeaseExpired:
                        pass   # sweeper won the race — the point is the race
                    c.lease_keepalive(lease)
                if i % 9 == 0:
                    c.compare_and_swap(f"/stress/cas/{wid}", None, "v")
                    c.events_since(0)
                    c.delete_prefix(f"/stress/{wid}/")
            c.close()
        except Exception as exc:   # noqa: BLE001 — collected for assert
            errors.append((wid, exc))

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    try:
        assert not errors, f"client errors (daemon died mid-run?): {errors}"
        assert proc.poll() is None, \
            f"daemon exited {proc.returncode} — TSAN report:\n" \
            f"{log_path.read_bytes().decode(errors='replace')[-3000:]}"
    finally:
        proc.terminate()
        proc.wait(timeout=10)
    report = log_path.read_bytes().decode(errors="replace")
    assert "WARNING: ThreadSanitizer" not in report, report[-3000:]


def test_garbage_bytes_close_connection_not_daemon(daemon):
    import socket
    host, port = daemon._endpoint.split(":")
    s = socket.create_connection((host, int(port)), timeout=3)
    s.sendall(b"NOT-A-FRAME" * 100)
    s.close()
    assert daemon.ping()
