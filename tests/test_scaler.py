"""Elastic autoscaler plane: policies, simulator, controller, handoff.

The decision half of the reference's TrainingJob-controller pillar
(SURVEY §1): utilization-driven resize decisions. Policy behavior is
pinned against the deterministic `SimCluster` (virtual time, seeded
noise, oracle allocations from the true curves); the controller tier
runs over InMemStore + a real JobServer on a loopback port.
"""

import json
import time

import pytest

from edl_tpu.coord.store import InMemStore
from edl_tpu.scaler.controller import (DecisionJournal, ScalerConfig,
                                       ScalerController, journal_prefix)
from edl_tpu.scaler.policy import (FairSharePolicy, JobView,
                                   ThroughputPolicy)
from edl_tpu.scaler.simulator import (SimCluster, SimJob, concave, flat,
                                      knee, linear, run_policy)


def make_policy(**kw):
    kw.setdefault("gain_threshold", 0.05)
    kw.setdefault("cooldown_s", 15.0)
    kw.setdefault("horizon_s", 60.0)
    return ThroughputPolicy(**kw)


class TestThroughputPolicy:
    @pytest.mark.parametrize("name,curve,start", [
        ("concave-steep", concave(100, 0.3), 1),
        ("concave-gentle", concave(100, 0.6), 2),
        ("flat-from-above", flat(100), 3),
        ("knee-from-above", knee(100, 4), 7),
        ("knee-from-below", knee(100, 4), 1),
        ("linear", linear(100), 1),
    ])
    def test_converges_to_oracle_without_oscillating(self, name, curve,
                                                     start):
        """The acceptance bar: within 1 node of the oracle allocation on
        concave, flat, and knee curves, with ZERO post-convergence
        resizes over the trailing 50 ticks."""
        sim = SimCluster([SimJob("j", curve, 1, 8, nodes=start,
                                 noise=0.01)],
                         tick_s=5.0, downtime_s=1.2, seed=0)
        out = run_policy(sim, make_policy(), ticks=150, settle_ticks=50)
        job = out["jobs"]["j"]
        assert job["gap_nodes"] <= 1, (name, job)
        assert job["post_convergence_resizes"] == 0, (name, job)

    def test_no_oscillation_on_noisy_flat_curve(self):
        """Hysteresis: 2% multiplicative noise on a flat curve must not
        produce grow/shrink flapping — across seeds, the policy walks
        down to min once and then never resizes again."""
        for seed in range(6):
            sim = SimCluster([SimJob("j", flat(100), 1, 8, nodes=4,
                                     noise=0.02)],
                             tick_s=5.0, downtime_s=1.2, seed=seed)
            out = run_policy(sim, make_policy(), ticks=250,
                             settle_ticks=100)
            job = out["jobs"]["j"]
            assert job["final_nodes"] == 1, (seed, job)
            assert job["post_convergence_resizes"] == 0, (seed, job)
            # exploration is bounded: 4 -> 5 probe, then down to 1
            assert job["resizes"] <= 5, (seed, job)

    def test_cooldown_spaces_resizes(self):
        """No two actuated resizes for one job closer than cooldown."""
        cooldown = 20.0
        sim = SimCluster([SimJob("j", concave(100, 0.5), 1, 8, nodes=1,
                                 noise=0.0)],
                         tick_s=5.0, downtime_s=1.0, seed=0)
        run_policy(sim, make_policy(cooldown_s=cooldown, horizon_s=60.0),
                   ticks=100)
        ticks = sim.jobs["j"].resize_ticks
        assert len(ticks) >= 2
        gaps = [(b - a) * sim.tick_s for a, b in zip(ticks, ticks[1:])]
        assert min(gaps) >= cooldown, gaps

    def test_amortization_blocks_unpayable_resize(self):
        """A downtime larger than the decision horizon can never pay for
        itself — the policy must hold forever, not resize."""
        sim = SimCluster([SimJob("j", linear(100), 1, 8, nodes=2,
                                 noise=0.0)],
                         tick_s=5.0, downtime_s=100.0, seed=0)
        out = run_policy(sim, make_policy(cooldown_s=15.0,
                                          horizon_s=60.0), ticks=60)
        assert sim.jobs["j"].resizes == 0
        assert out["downtime_paid_s"] == 0.0

    def test_restore_resumes_cooldown_and_curve(self):
        """Journal replay: a restored policy knows the curve and does
        not re-resize inside the predecessor's cooldown window."""
        src = make_policy()
        now = 1000.0
        view = JobView("j", 2, 200.0, 1, 8, downtime_s=1.0)
        entries = [
            {"job_id": "j", "world_size": 1, "throughput": 100.0,
             "fresh": True, "action": "hold", "ts": now - 40},
            {"job_id": "j", "world_size": 2, "throughput": 200.0,
             "fresh": True, "action": "resize", "ts": now - 5},
        ]
        src.restore(entries)
        assert src.model("j").observed(1) == 100.0
        (prop,) = src.decide([view], now)
        assert not prop.is_resize and prop.reason == "cooldown"
        # past the cooldown the restored curve drives a real decision
        (prop,) = src.decide([view], now + 60.0)
        assert prop.is_resize and prop.reason == "probe-up"


class TestFairSharePolicy:
    def test_budget_conservation_and_minmax(self):
        """Planned allocations always sum to min(budget, sum(max)) when
        the budget covers the mins, and honor every job's min/max."""
        for budget in (4, 6, 9, 12, 24):
            pol = FairSharePolicy(budget, cooldown_s=15.0,
                                  horizon_s=60.0)
            views = [JobView("a", 2, 100.0, 1, 8),
                     JobView("b", 2, 50.0, 2, 4),
                     JobView("c", 1, 10.0, 1, 6)]
            for v in views:  # teach each model one point
                pol.model(v.job_id).observe(v.world_size, v.throughput)
            alloc = pol.plan(views)
            cap = sum(v.max_nodes for v in views)
            assert sum(alloc.values()) == min(budget, cap), alloc
            for v in views:
                assert v.min_nodes <= alloc[v.job_id] <= v.max_nodes, \
                    (budget, alloc)

    def test_budget_never_exceeded_mid_flight(self):
        """Shrink-before-grow: across a whole simulated run the live
        node total never transiently exceeds the budget."""
        budget = 8
        jobs = [SimJob("lin", linear(50), 1, 8, nodes=4, noise=0.01),
                SimJob("fl", flat(100), 1, 8, nodes=4, noise=0.01)]
        sim = SimCluster(jobs, tick_s=5.0, downtime_s=1.2, seed=0)
        pol = FairSharePolicy(budget, cooldown_s=15.0, horizon_s=60.0)
        for _ in range(100):
            views = sim.tick()
            for prop in pol.decide(views, sim.now):
                if prop.is_resize:
                    actual = sim.resize(prop.job_id, prop.desired)
                    pol.notify_resized(prop.job_id, actual, sim.now)
            assert sum(j.nodes for j in sim.jobs.values()) <= budget

    def test_unexplored_job_attracts_probe_nodes(self):
        """Scale-aware exploration: with an explored job measured in the
        ~100 examples/sec range (absolute marginal gains of several
        ex/s), a job with NO observations must still win probe nodes —
        the old constant 1.0 bonus starved it until every explored
        marginal dropped below 1.0 ex/s."""
        pol = FairSharePolicy(8, cooldown_s=15.0, horizon_s=60.0)
        # diminishing curve, but absolute marginals still > 1.0 ex/s
        pol.model("old").observe(2, 100.0)
        pol.model("old").observe(4, 110.0)
        views = [JobView("old", 4, 110.0, 1, 8),
                 JobView("new", 1, 0.0, 1, 8, fresh=False)]
        alloc = pol.plan(views)
        assert alloc["new"] > 1, alloc
        assert sum(alloc.values()) == 8, alloc

    def test_prefers_higher_marginal_job(self):
        """A linear-scaling job outbids a flat one for the headroom and
        the split matches the true-curve oracle."""
        jobs = [SimJob("lin", linear(50), 1, 8, nodes=2, noise=0.01),
                SimJob("fl", flat(100), 1, 8, nodes=2, noise=0.01)]
        sim = SimCluster(jobs, tick_s=5.0, downtime_s=1.2, seed=0)
        pol = FairSharePolicy(8, cooldown_s=15.0, horizon_s=60.0)
        out = run_policy(sim, pol, ticks=200, settle_ticks=50)
        oracle = sim.oracle_fair_share(8)
        for job_id, target in oracle.items():
            assert abs(out["jobs"][job_id]["final_nodes"] - target) <= 1
        assert out["post_convergence_resizes"] == 0


# -- controller tier -------------------------------------------------------


def seed_job(store, job="j1", world=2, rate=120.0, now=None):
    """A live job in the store: rank claims + cluster + fresh util."""
    from edl_tpu.collective.cluster import Cluster, Pod
    from edl_tpu.collective.register import cluster_key, rank_key
    from edl_tpu.coord.collector import util_key
    now = time.time() if now is None else now
    pods = []
    for i in range(world):
        pod_id = f"pod{i}"
        store.put(rank_key(job, i),
                  Pod(pod_id=pod_id, addr=f"10.0.0.{i}", n_devices=1,
                      claimed_rank=i, rank=i).to_json(),
                  lease=store.lease_grant(30.0))
        store.put(util_key(job, pod_id),
                  json.dumps({"pod_id": pod_id, "step": 10,
                              "examples_per_sec": rate / world,
                              "world_size": world,
                              "published_unix": now}),
                  lease=store.lease_grant(30.0))
        pods.append(Pod(pod_id=pod_id, addr=f"10.0.0.{i}", rank=i))
    store.put(cluster_key(job),
              Cluster(job_id=job, version=world, pods=pods).to_json())


def make_controller(store, state, **kw):
    """Controller actuating straight into a JobState (no HTTP)."""
    kw.setdefault("config", ScalerConfig(interval=0.1, cooldown_s=30.0,
                                         downtime_s=1.0,
                                         staleness_s=30.0,
                                         min_nodes=state.min_nodes,
                                         max_nodes=state.max_nodes,
                                         leader_ttl=0.5))
    kw.setdefault("actuate",
                  lambda _job, desired: state.resize(desired))
    policy = kw.pop("policy", None) or make_policy(cooldown_s=30.0)
    return ScalerController(store, [state.job_id], policy, **kw)


class TestControllerIntegration:
    def test_collector_to_jobserver_tick(self, tmp_path):
        """One store-backed tick end to end: Collector snapshot ->
        ThroughputPolicy -> HTTP /resize on a real JobServer, with the
        decision journaled to the store AND the JSON-lines file."""
        from edl_tpu.collective.job_server import (JobServer, JobState,
                                                   get_job)
        store = InMemStore()
        seed_job(store, world=2)
        state = JobState("j1", 1, 4, desired=2)
        server = JobServer(state, port=0).start()
        journal_file = tmp_path / "journal.jsonl"
        try:
            ctl = ScalerController(
                store, ["j1"], make_policy(),
                config=ScalerConfig(cooldown_s=30.0, downtime_s=1.0,
                                    staleness_s=30.0),
                job_server=f"127.0.0.1:{server.port}",
                journal_path=str(journal_file), elect=False)
            entries = ctl.tick()
            assert len(entries) == 1
            (entry,) = entries
            # one fresh size known -> the policy probes one node up
            assert entry["action"] == "resize"
            assert entry["reason"] == "probe-up"
            assert entry["current"] == 2 and entry["desired"] == 3
            assert entry["throughput"] == pytest.approx(120.0)
            assert get_job(f"127.0.0.1:{server.port}")[
                "desired_nodes"] == 3
            # journaled in the store (successor's replay medium)...
            recs, _ = store.get_prefix(journal_prefix("j1"))
            assert [json.loads(r.value)["action"] for r in recs] \
                == ["resize"]
            # ...and as a JSON line for the operator
            lines = journal_file.read_text().strip().splitlines()
            assert json.loads(lines[-1])["desired"] == 3
            # the very next tick honors the cooldown it just started
            (entry2,) = ctl.tick()
            assert entry2["action"] == "hold"
            assert entry2["reason"] in ("cooldown",
                                        "settling-after-resize",
                                        "resize-in-flight")
            ctl.stop()
        finally:
            server.stop()

    def test_dry_run_never_actuates(self):
        from edl_tpu.collective.job_server import JobState
        store = InMemStore()
        seed_job(store, world=2)
        state = JobState("j1", 1, 4, desired=2)
        calls = []
        ctl = make_controller(
            store, state, dry_run=True, elect=False,
            actuate=lambda job, desired: calls.append(desired))
        (entry,) = ctl.tick()
        assert entry["action"] == "dry-run"
        assert entry["desired"] == 3
        assert not calls and state.desired == 2
        ctl.stop()

    def test_stale_and_mismatched_utilization_is_ignored(self):
        """Records older than staleness_s or published under a different
        world_size must not feed the model."""
        from edl_tpu.coord.collector import util_key
        store = InMemStore()
        now = time.time()
        seed_job(store, world=2, rate=100.0, now=now)
        # pod0's record goes stale; pod1's is from the pre-resize world
        store.put(util_key("j1", "pod0"),
                  json.dumps({"examples_per_sec": 50.0, "world_size": 2,
                              "published_unix": now - 3600}))
        store.put(util_key("j1", "pod1"),
                  json.dumps({"examples_per_sec": 50.0, "world_size": 9,
                              "published_unix": now}))
        ctl = ScalerController(store, ["j1"], make_policy(),
                               config=ScalerConfig(staleness_s=30.0),
                               elect=False)
        view = ctl.observe("j1")
        assert not view.fresh and view.throughput == 0.0
        ctl.stop()

    def test_publisher_world_unit_matches_cluster(self):
        """Regression (r11 review): the publisher's doc carries the
        ELASTIC world (pod count) — observe() compares it against
        Cluster.world_size, so publishing the device world would drop
        every fresh record as 'pre-resize' whenever devices-per-pod
        != 1 and the live loop would silently do nothing."""
        from edl_tpu.coord.collector import UtilizationPublisher

        class _Loop:
            class status:
                samples_seen = 0
                world_size = 8   # device world: 2 pods x 4 devices

        store = InMemStore()
        seed_job(store, world=2)   # Cluster.world_size = 2 pods
        pubs = []
        for pod in ("pod0", "pod1"):
            pub = UtilizationPublisher(store, "j1", pod,
                                       min_interval=0.0, world_size=2)
            pub(_Loop(), 0, 1, {})
            assert pub.flush()
            pubs.append(pub)
        ctl = ScalerController(store, ["j1"], make_policy(),
                               config=ScalerConfig(staleness_s=30.0),
                               elect=False)
        view = ctl.observe("j1")
        assert view.fresh and view.world_size == 2
        ctl.stop()
        for pub in pubs:
            pub.stop()

    def test_cli_rejects_server_with_multiple_jobs(self, capsys):
        """One JobServer holds one job's state: --server plus several
        --job would alias every job onto the same JobState, so the CLI
        refuses the combination up front."""
        from edl_tpu.scaler.__main__ import main
        with pytest.raises(SystemExit) as exc:
            main(["--store", "127.0.0.1:1", "--job", "a", "--job", "b",
                  "--server", "127.0.0.1:2"])
        assert exc.value.code == 2
        assert "single job" in capsys.readouterr().err

    def test_leader_election_handoff_resumes_from_journal(self):
        """Exactly-one-scaler + takeover: controller A (leader) makes a
        resize and dies WITHOUT resigning; B takes over after lease
        expiry, replays A's journal, and honors A's cooldown instead of
        double-resizing."""
        from edl_tpu.collective.job_server import JobState
        store = InMemStore()
        seed_job(store, world=2)
        state = JobState("j1", 1, 4, desired=2)
        a = make_controller(store, state, owner="A")
        b = make_controller(store, state, owner="B")
        try:
            assert a.election.campaign(timeout=5.0)
            entries = a.tick()
            assert entries and entries[0]["action"] == "resize"
            assert state.desired == 3
            # B cannot act while A holds the lease
            assert b.tick() == []
            # A dies: keepalive stops, lease expires (never resigned)
            hold = a.election.lock._hold
            hold.stop.set()
            assert b.election.campaign(timeout=10.0)
            assert b.is_leader()
            # B's first decision replays A's journal: inside A's
            # cooldown it must hold, not resize again
            seed_job(store, world=3)  # world caught up with desired
            (entry,) = b.tick()
            assert entry["action"] == "hold"
            assert entry["reason"] in ("cooldown",
                                       "settling-after-resize")
            assert state.desired == 3
            assert entry["leader"] == "B"
            # seq continues where A left off (one shared journal)
            recs, _ = store.get_prefix(journal_prefix("j1"))
            seqs = [json.loads(r.value)["seq"] for r in recs]
            assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        finally:
            a.stop()
            b.stop()


class TestDecisionJournal:
    def test_retention_keeps_newest(self):
        store = InMemStore()
        journal = DecisionJournal(store, "jx", keep=5)
        for i in range(12):
            journal.append({"job_id": "jx", "action": "hold", "i": i})
        tail = journal.tail()
        assert len(tail) <= 6  # keep + the in-flight append window
        assert tail[-1]["i"] == 11
        # a new journal instance continues the sequence
        journal2 = DecisionJournal(store, "jx", keep=5)
        entry = journal2.append({"job_id": "jx", "action": "hold"})
        assert entry["seq"] == 12
