"""Remote-FS abstraction + checkpoint mirroring (utils/fs.py).

Covers the reference's remote-store contract (doc/fault_tolerance.md:
30-45 rank-0 uploads / everyone downloads; distill/utils.py:18 fetch of
teacher files) without any cloud: LocalFS directly, and CommandFS through
a cp/ls-backed command table — the same injection a gs:// deployment uses
with gsutil.
"""

import json
import os

import numpy as np
import optax
import pytest

import jax

from edl_tpu.train.checkpoint import CheckpointManager
from edl_tpu.train.state import TrainStatus
from edl_tpu.utils import fs as fslib


def _cp_command_fs():
    """CommandFS over plain POSIX tools — the gsutil stand-in."""
    return fslib.CommandFS(
        exists_cmd=["test", "-e", "{uri}"],
        list_cmd=["ls", "{uri}"],
        upload_cmd=["cp", "-r", "{src}", "{dst}"],
        download_cmd=["cp", "-r", "{src}", "{dst}"],
        delete_cmd=["rm", "-rf", "{uri}"])


@pytest.fixture(params=["local", "command"])
def anyfs(request):
    return fslib.LocalFS() if request.param == "local" else _cp_command_fs()


class TestFileSystems:
    def test_roundtrip_file(self, anyfs, tmp_path):
        src = tmp_path / "a.txt"
        src.write_text("hello")
        remote = tmp_path / "remote" / "a.txt"
        os.makedirs(remote.parent)
        anyfs.upload(str(src), str(remote))
        assert anyfs.exists(str(remote))
        dst = tmp_path / "back.txt"
        anyfs.download(str(remote), str(dst))
        assert dst.read_text() == "hello"

    def test_roundtrip_dir(self, anyfs, tmp_path):
        src = tmp_path / "d"
        (src / "sub").mkdir(parents=True)
        (src / "x").write_text("1")
        (src / "sub" / "y").write_text("2")
        remote = tmp_path / "r" / "d"
        os.makedirs(remote.parent)
        anyfs.upload(str(src), str(remote))
        dst = tmp_path / "d2"
        anyfs.download(str(remote), str(dst))
        assert (dst / "x").read_text() == "1"
        assert (dst / "sub" / "y").read_text() == "2"

    def test_listdir_and_delete(self, anyfs, tmp_path):
        d = tmp_path / "dir"
        d.mkdir()
        (d / "b").write_text("")
        (d / "a").write_text("")
        assert anyfs.listdir(str(d)) == ["a", "b"]
        assert anyfs.listdir(str(tmp_path / "absent")) == []
        anyfs.delete(str(d / "a"))
        assert anyfs.listdir(str(d)) == ["b"]
        anyfs.delete(str(d / "a"))  # idempotent

    def test_text_helpers(self, anyfs, tmp_path):
        uri = str(tmp_path / "marker")
        anyfs.write_text(uri, "7")
        assert anyfs.read_text(uri) == "7"

    def test_exists_false(self, anyfs, tmp_path):
        assert not anyfs.exists(str(tmp_path / "nope"))


class TestUriPlumbing:
    def test_split_scheme(self):
        assert fslib.split_scheme("gs://b/p") == ("gs", "b/p")
        assert fslib.split_scheme("/a/b") == ("", "/a/b")
        assert fslib.split_scheme("file:///a") == ("file", "/a")

    def test_resolve_local_and_file(self):
        assert isinstance(fslib.resolve("/tmp/x"), fslib.LocalFS)
        assert isinstance(fslib.resolve("file:///tmp/x"), fslib.LocalFS)

    def test_resolve_unknown_scheme(self):
        with pytest.raises(fslib.EdlFsError):
            fslib.resolve("s3://bucket/x")

    def test_register_scheme(self, tmp_path):
        fslib.register_scheme("fake", _cp_command_fs)
        try:
            assert isinstance(fslib.resolve("fake://x"), fslib.CommandFS)
        finally:
            fslib._SCHEMES.pop("fake")

    def test_local_fs_rejects_remote_uri(self):
        with pytest.raises(fslib.EdlFsError):
            fslib.LocalFS().exists("gs://b/x")

    def test_join_uri(self):
        assert fslib.join_uri("gs://b/", "c", "d") == "gs://b/c/d"

    def test_fetch_file_local_passthrough(self, tmp_path):
        p = tmp_path / "f"
        p.write_text("x")
        assert fslib.fetch_file(str(p)) == str(p)
        assert fslib.fetch_file(f"file://{p}") == str(p)

    def test_fetch_file_remote_caches(self, tmp_path):
        fslib.register_scheme("fake", fslib.LocalFS)
        try:
            src = tmp_path / "params.bin"
            src.write_text("weights")
            # LocalFS treats fake:// as... it rejects. Use a tiny shim.
            class Shim(fslib.LocalFS):
                @staticmethod
                def _path(uri):
                    return uri.split("://", 1)[1] if "://" in uri else uri
            fslib.register_scheme("fake", Shim)
            cache = tmp_path / "cache"
            out = fslib.fetch_file(f"fake://{src}", str(cache))
            assert open(out).read() == "weights"
            # second fetch hits the cache (delete the source to prove it)
            src.unlink()
            assert fslib.fetch_file(f"fake://{src}", str(cache)) == out
        finally:
            fslib._SCHEMES.pop("fake")

    def test_fetch_file_partial_download_not_cached(self, tmp_path):
        """A download killed mid-transfer must not leave a partial file
        the existence-cache serves forever (the CommandFS failure mode:
        gsutil creates dst, then dies)."""
        src = tmp_path / "params.bin"
        src.write_text("all-the-weights")
        calls = {"n": 0}

        class FlakyFS(fslib.LocalFS):
            @staticmethod
            def _path(uri):
                return uri.split("://", 1)[1] if "://" in uri else uri

            def download(self, uri, local):
                calls["n"] += 1
                if calls["n"] == 1:
                    with open(local, "w") as f:
                        f.write("all-th")  # truncated
                    raise fslib.EdlFsError("killed mid-transfer")
                super().download(uri, local)

        fslib.register_scheme("flaky", FlakyFS)
        try:
            cache = tmp_path / "cache"
            with pytest.raises(fslib.EdlFsError):
                fslib.fetch_file(f"flaky://{src}", str(cache))
            # retry must re-download (no partial file poisoning the cache)
            out = fslib.fetch_file(f"flaky://{src}", str(cache))
            assert open(out).read() == "all-the-weights"
            assert calls["n"] == 2
        finally:
            fslib._SCHEMES.pop("flaky")


class TestCheckpointMirror:
    def _state(self, value):
        return {"w": np.full((4,), value, np.float32)}

    def test_mirror_marker_last_and_fetch(self, tmp_path):
        local, remote = str(tmp_path / "l"), str(tmp_path / "r")
        os.makedirs(os.path.join(local, "ckpt-0"))
        with open(os.path.join(local, "ckpt-0", "meta.json"), "w") as f:
            json.dump({"version": 0}, f)
        fslib.mirror_checkpoint(local, 0, remote)
        assert fslib.remote_versions(remote) == [0]
        dst = str(tmp_path / "cold")
        assert fslib.fetch_latest_checkpoint(remote, dst) == 0
        assert os.path.isfile(os.path.join(dst, "ckpt-0", "meta.json"))

    def test_fetch_no_marker(self, tmp_path):
        remote = str(tmp_path / "empty")
        os.makedirs(remote)
        assert fslib.fetch_latest_checkpoint(remote, str(tmp_path / "d")) is None

    def test_mirror_keep_prunes_old(self, tmp_path):
        local, remote = str(tmp_path / "l"), str(tmp_path / "r")
        for v in range(3):
            os.makedirs(os.path.join(local, f"ckpt-{v}"))
            with open(os.path.join(local, f"ckpt-{v}", "meta.json"),
                      "w") as f:
                json.dump({"version": v}, f)
            fslib.mirror_checkpoint(local, v, remote, keep=2)
        assert fslib.remote_versions(remote) == [1, 2]

    def test_manager_save_mirrors_and_cold_restore(self, tmp_path):
        remote = str(tmp_path / "remote")
        mgr = CheckpointManager(str(tmp_path / "podA"), process_index=0,
                                remote=remote)
        state = self._state(3.0)
        mgr.save(state, TrainStatus(epoch=2, step=7, world_size=1))
        mgr.save(self._state(5.0), TrainStatus(epoch=3, step=9, world_size=1))
        assert fslib.remote_versions(remote) == [0, 1]
        # a brand-new pod with an empty local dir restores from the mirror
        cold = CheckpointManager(str(tmp_path / "podB"), process_index=0,
                                 remote=remote)
        out = cold.restore(self._state(0.0))
        assert out is not None
        restored, status = out
        np.testing.assert_array_equal(restored["w"], self._state(5.0)["w"])
        assert (status.epoch, status.step) == (3, 9)

    def test_manager_restore_specific_version_from_mirror(self, tmp_path):
        remote = str(tmp_path / "remote")
        mgr = CheckpointManager(str(tmp_path / "podA"), process_index=0,
                                remote=remote)
        mgr.save(self._state(1.0), TrainStatus(epoch=0, step=1, world_size=1))
        mgr.save(self._state(2.0), TrainStatus(epoch=1, step=2, world_size=1))
        cold = CheckpointManager(str(tmp_path / "podB"), process_index=0,
                                 remote=remote)
        out = cold.restore(self._state(0.0), version=0)
        assert out is not None
        np.testing.assert_array_equal(out[0]["w"], self._state(1.0)["w"])

    def test_restore_prefers_newer_remote_over_stale_local(self, tmp_path):
        # a pod whose container restarted in place holds ckpt-0 locally
        # while rank 0 mirrored ckpt-1 — restore must take the mirror's.
        remote = str(tmp_path / "remote")
        writer = CheckpointManager(str(tmp_path / "w"), process_index=0,
                                   remote=remote)
        writer.save(self._state(1.0), TrainStatus(epoch=0, step=1,
                                                  world_size=1))
        stale = CheckpointManager(str(tmp_path / "s"), process_index=0,
                                  remote=remote)
        assert stale.restore(self._state(0.0)) is not None  # pulls ckpt-0
        writer.save(self._state(9.0), TrainStatus(epoch=1, step=2,
                                                  world_size=1))
        out = stale.restore(self._state(0.0))
        assert out is not None
        np.testing.assert_array_equal(out[0]["w"], self._state(9.0)["w"])
        assert out[1].epoch == 1

    def test_mirror_failure_is_not_fatal(self, tmp_path, monkeypatch):
        mgr = CheckpointManager(str(tmp_path / "l"), process_index=0,
                                remote=str(tmp_path / "r"))
        monkeypatch.setattr(fslib, "mirror_checkpoint",
                            lambda *a, **k: (_ for _ in ()).throw(
                                fslib.EdlFsError("503")))
        v = mgr.save(self._state(1.0), TrainStatus(epoch=0, step=0,
                                                   world_size=1))
        assert v == 0  # local save sealed despite the mirror failure
        assert mgr.restore(self._state(0.0)) is not None

    def test_cold_rank0_replicated_save_continues_remote_numbering(
            self, tmp_path):
        """A cold-restarted rank 0 (empty local dir) saving BEFORE any
        restore must number above the mirror's LATEST, not recompute
        version 0 and overwrite the published remote ckpt-0."""
        remote = str(tmp_path / "remote")
        warm = CheckpointManager(str(tmp_path / "warm"), process_index=0,
                                 remote=remote)
        warm.save(self._state(1.0), TrainStatus(epoch=0, step=1,
                                                world_size=1))
        warm.save(self._state(2.0), TrainStatus(epoch=1, step=2,
                                                world_size=1))
        cold = CheckpointManager(str(tmp_path / "cold"), process_index=0,
                                 remote=remote)
        v = cold.save(self._state(9.0), TrainStatus(epoch=2, step=3,
                                                    world_size=1))
        assert v == 2  # continues above the remote's LATEST of 1
        assert fslib.remote_latest_version(remote) == 2
        # the published ckpt-0 payload is untouched
        reader = CheckpointManager(str(tmp_path / "r"), process_index=0,
                                   remote=remote)
        out = reader.restore(self._state(0.0), version=0)
        np.testing.assert_array_equal(out[0]["w"], self._state(1.0)["w"])

    def test_cold_rank0_with_unreadable_remote_skips_mirror(
            self, tmp_path, monkeypatch):
        """If the remote LATEST cannot be read, the replicated save must
        seal locally but NOT mirror (it could be reusing a published
        version number)."""
        remote = str(tmp_path / "remote")
        warm = CheckpointManager(str(tmp_path / "warm"), process_index=0,
                                 remote=remote)
        warm.save(self._state(1.0), TrainStatus(epoch=0, step=1,
                                                world_size=1))
        monkeypatch.setattr(
            fslib, "remote_latest_version",
            lambda *a, **k: (_ for _ in ()).throw(fslib.EdlFsError("503")))
        cold = CheckpointManager(str(tmp_path / "cold"), process_index=0,
                                 remote=remote)
        v = cold.save(self._state(9.0), TrainStatus(epoch=2, step=3,
                                                    world_size=1))
        assert v == 0  # local numbering only (remote view unknown)
        monkeypatch.undo()
        assert fslib.remote_latest_version(remote) == 0  # not overwritten
        out = CheckpointManager(str(tmp_path / "r"), process_index=0,
                                remote=remote).restore(self._state(0.0))
        np.testing.assert_array_equal(out[0]["w"], self._state(1.0)["w"])

    def test_failed_write_then_retry_still_folds_remote(
            self, tmp_path, monkeypatch):
        """A save whose local write FAILS after the remote fold must not
        mark the fold done — the retry would skip it, recompute version
        0, and overwrite the published remote ckpt-0."""
        from flax import serialization as ser
        remote = str(tmp_path / "remote")
        warm = CheckpointManager(str(tmp_path / "warm"), process_index=0,
                                 remote=remote)
        warm.save(self._state(1.0), TrainStatus(epoch=0, step=1,
                                                world_size=1))
        warm.save(self._state(2.0), TrainStatus(epoch=1, step=2,
                                                world_size=1))
        cold = CheckpointManager(str(tmp_path / "cold"), process_index=0,
                                 remote=remote)
        monkeypatch.setattr(ser, "to_bytes",
                            lambda *a: (_ for _ in ()).throw(
                                OSError("disk full")))
        with pytest.raises(OSError):
            cold.save(self._state(9.0), TrainStatus(epoch=2, step=3,
                                                    world_size=1))
        monkeypatch.undo()
        v = cold.save(self._state(9.0), TrainStatus(epoch=2, step=3,
                                                    world_size=1))
        assert v == 2  # retry re-folded, did not renumber from 0
        assert fslib.remote_latest_version(remote) == 2
        out = CheckpointManager(str(tmp_path / "r"), process_index=0,
                                remote=remote).restore(self._state(0.0),
                                                       version=0)
        np.testing.assert_array_equal(out[0]["w"], self._state(1.0)["w"])

    def test_nonzero_rank_prunes_fetched_sealed_versions(self, tmp_path):
        """Restore-time mirror fetches accumulate sealed ckpt-N dirs on
        non-zero pods' local dirs; a sharded save must prune them down to
        max_to_keep even though only rank 0 runs the full _gc."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from edl_tpu.parallel.mesh import MeshSpec, make_mesh
        local = tmp_path / "pod1"
        for v in range(4):  # fetched copies of old versions
            d = local / f"ckpt-{v}"
            d.mkdir(parents=True)
            (d / "meta.json").write_text(json.dumps({"version": v}))
        mgr = CheckpointManager(str(local), process_index=1, sharded=True,
                                max_to_keep=2)
        mesh = make_mesh(MeshSpec({"dp": -1}))
        arr = jax.device_put(np.arange(8, dtype=np.float32),
                             NamedSharding(mesh, P()))
        assert mgr.save({"w": arr}, TrainStatus(epoch=0, step=9,
                                                world_size=1)) is None
        assert mgr.versions() == [2, 3]
        # the pending dir this rank just wrote must survive (rank 0 owns
        # sealing it on shared dirs)
        assert (local / ".tmp-ckpt-4").is_dir()

    def test_manager_without_remote_unchanged(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "only"), process_index=0)
        mgr.save(self._state(1.0), TrainStatus(epoch=0, step=0, world_size=1))
        assert mgr.restore(self._state(0.0)) is not None

    def test_sharded_mirror_incomplete_does_not_flip_latest(
            self, tmp_path, monkeypatch):
        """If a rank's chunk/index upload fails, rank 0's completeness
        gate must NOT flip LATEST to the holey version (a cold pod would
        reassemble from a missing index)."""
        from edl_tpu.parallel.mesh import MeshSpec, make_mesh
        from jax.sharding import NamedSharding, PartitionSpec as P
        remote = str(tmp_path / "remote")
        mesh = make_mesh(MeshSpec({"dp": -1}))
        sharding = NamedSharding(mesh, P())
        arr = jax.device_put(np.arange(8, dtype=np.float32), sharding)
        mgr = CheckpointManager(str(tmp_path / "l"), sharded=True,
                                remote=remote)
        real = fslib.mirror_checkpoint_files
        calls = {"n": 0}

        def flaky(version_dir, version, remote_root, files):
            calls["n"] += 1
            if calls["n"] == 1:  # the per-rank chunks+index upload
                raise OSError("disk full mid-upload")  # raw, not EdlFsError
            real(version_dir, version, remote_root, files)

        monkeypatch.setattr(fslib, "mirror_checkpoint_files", flaky)
        v = mgr.save({"w": arr}, TrainStatus(epoch=0, step=0, world_size=1))
        assert v == 0  # local save sealed
        assert fslib.remote_latest_version(remote) is None  # no flip
        # next save (uploads fine) flips LATEST and the remote dir was
        # cleaned of the stale partial before re-upload
        mgr.save({"w": arr}, TrainStatus(epoch=0, step=1, world_size=1))
        assert fslib.remote_latest_version(remote) == 1
        cold = CheckpointManager(str(tmp_path / "cold"), remote=remote)
        target = jax.device_put(np.zeros(8, np.float32), sharding)
        out = cold.restore({"w": target})
        assert out is not None and out[1].step == 1

    def test_gc_ignores_partial_versions(self, tmp_path):
        """A partial remote dir (failed mirror, no meta.json) must not
        occupy a retention slot — and gets deleted once it falls below
        the newest-complete cutoff."""
        remote = str(tmp_path / "r")
        for v in (0, 2):  # complete: sealed by an earlier finalize
            os.makedirs(os.path.join(remote, f"ckpt-{v}"))
            with open(os.path.join(remote, f"ckpt-{v}", "COMPLETE"),
                      "w") as f:
                f.write(str(v))
        for v in (1, 3):  # 1 partial (no marker); 3 being finalized now
            os.makedirs(os.path.join(remote, f"ckpt-{v}"))
        with open(os.path.join(remote, "ckpt-1", "index.0.json"), "w") as f:
            f.write("{}")
        fslib.finalize_mirror(remote, 3, keep=2)
        # complete 2,3 kept; complete 0 GC'd; partial 1 GC'd as garbage
        assert fslib.remote_versions(remote) == [2, 3]

    def test_fetch_explicit_partial_version_refused(self, tmp_path):
        remote = str(tmp_path / "r")
        os.makedirs(os.path.join(remote, "ckpt-0"))  # no meta.json
        assert fslib.fetch_latest_checkpoint(
            remote, str(tmp_path / "d"), version=0) is None

    def test_restore_refetches_incomplete_local_sharded(self, tmp_path):
        """An in-place-restarted pod's local sharded ckpt holds only its
        OWN chunks/index; restore must refetch the complete mirror copy
        instead of reassembling a holey state."""
        from edl_tpu.parallel.mesh import MeshSpec, make_mesh
        from jax.sharding import NamedSharding, PartitionSpec as P
        remote = str(tmp_path / "remote")
        mesh = make_mesh(MeshSpec({"dp": -1}))
        sharding = NamedSharding(mesh, P())
        arr = jax.device_put(np.full(8, 7.0, np.float32), sharding)
        writer = CheckpointManager(str(tmp_path / "w"), sharded=True,
                                   remote=remote)
        writer.save({"w": arr}, TrainStatus(epoch=1, step=5, world_size=2))
        # simulate rank 0's pod-local view of a 2-process world: its
        # sealed dir claims world.process_count=2 but only has index.0
        local = str(tmp_path / "pod0")
        ck = os.path.join(local, "ckpt-0")
        os.makedirs(ck)
        with open(os.path.join(ck, "meta.json"), "w") as f:
            json.dump({"version": 0, "format": "sharded",
                       "status": {"epoch": 0, "step": 0, "world_size": 2},
                       "world": {"process_count": 2, "device_count": 2}},
                      f)
        with open(os.path.join(ck, "index.0.json"), "w") as f:
            json.dump({"leaves": []}, f)
        mgr = CheckpointManager(local, remote=remote)
        target = jax.device_put(np.zeros(8, np.float32), sharding)
        out = mgr.restore({"w": target})
        assert out is not None
        np.testing.assert_array_equal(np.asarray(out[0]["w"]),
                                      np.full(8, 7.0, np.float32))
        assert out[1].step == 5  # the mirror's status, not the stub's

    def test_remote_clean_failure_skips_finalize(self, tmp_path,
                                                 monkeypatch):
        """If rank 0 cannot clear a stale remote version dir, nothing is
        uploaded and LATEST must not flip (stale same-name indexes could
        otherwise pass the exact-set gate)."""
        from edl_tpu.parallel.mesh import MeshSpec, make_mesh
        from jax.sharding import NamedSharding, PartitionSpec as P
        remote = str(tmp_path / "remote")
        # plant a stale complete-LOOKING remote ckpt-0 from a "crashed
        # earlier attempt" (index present, old data)
        os.makedirs(os.path.join(remote, "ckpt-0"))
        with open(os.path.join(remote, "ckpt-0", "index.0.json"),
                  "w") as f:
            json.dump({"leaves": []}, f)
        mesh = make_mesh(MeshSpec({"dp": -1}))
        sharding = NamedSharding(mesh, P())
        arr = jax.device_put(np.arange(8, dtype=np.float32), sharding)
        mgr = CheckpointManager(str(tmp_path / "l"), sharded=True,
                                remote=remote)

        def no_delete(self, uri):
            raise OSError("permission denied")

        monkeypatch.setattr(fslib.LocalFS, "delete", no_delete)
        v = mgr.save({"w": arr}, TrainStatus(epoch=0, step=0, world_size=1))
        assert v == 0  # local save sealed regardless
        assert fslib.remote_latest_version(remote) is None  # no flip

    def test_sharded_save_mirrors(self, tmp_path):
        # single-process sharded save still goes through _mirror
        from edl_tpu.parallel.mesh import MeshSpec, make_mesh
        from jax.sharding import NamedSharding, PartitionSpec as P
        remote = str(tmp_path / "remote")
        mesh = make_mesh(MeshSpec({"dp": -1}))
        sharding = NamedSharding(mesh, P())
        arr = jax.device_put(np.arange(8, dtype=np.float32), sharding)
        mgr = CheckpointManager(str(tmp_path / "l"), sharded=True,
                                remote=remote)
        mgr.save({"w": arr}, TrainStatus(epoch=0, step=0, world_size=1))
        assert fslib.remote_versions(remote) == [0]
        cold = CheckpointManager(str(tmp_path / "cold"), remote=remote)
        target = jax.device_put(np.zeros(8, np.float32), sharding)
        out = cold.restore({"w": target})
        assert out is not None
        np.testing.assert_array_equal(np.asarray(out[0]["w"]),
                                      np.arange(8, dtype=np.float32))
