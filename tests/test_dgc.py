"""DGC gradient-compression transform (train/dgc.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.train.dgc import DGCState, compression_ratio, dgc


def _grads(seed=0, n=256):
    k = jax.random.PRNGKey(seed)
    return {"w": jax.random.normal(k, (n,)),
            "b": jax.random.normal(jax.random.fold_in(k, 1), (4,))}


class TestDgc:
    def test_sparsifies_to_budget(self):
        tx = dgc(sparsity=0.9)
        g = _grads()
        state = tx.init(g)
        out, state = tx.update(g, state)
        nz = int(jnp.sum(out["w"] != 0))
        assert nz == pytest.approx(26, abs=2)  # ~10% of 256
        # tiny leaves stay dense
        assert int(jnp.sum(out["b"] != 0)) == 4

    def test_residual_carries_masked_mass(self):
        """Nothing is lost: sent + residual == momentum-corrected grad."""
        tx = dgc(sparsity=0.9, momentum=0.0)
        g = _grads()
        state = tx.init(g)
        out, state = tx.update(g, state)
        np.testing.assert_allclose(np.asarray(out["w"] + state.residual["w"]),
                                   np.asarray(g["w"]), atol=1e-6)

    def test_residual_eventually_sent(self):
        """A persistent small gradient accumulates and crosses the
        threshold — every coordinate eventually trains. (Send frequency
        is proportional to the gradient rate: with k sends/step the
        slowest coordinate turns over in ~sum(rates)/(k*rate) steps.)"""
        tx = dgc(sparsity=0.9, momentum=0.0)
        g = {"w": jnp.ones((128,)) * jnp.linspace(0.5, 1.0, 128)}
        state = tx.init(g)
        sent_any = jnp.zeros((128,), bool)
        for _ in range(60):
            out, state = tx.update(g, state)
            sent_any = sent_any | (out["w"] != 0)
        assert bool(jnp.all(sent_any))

    def test_rampup_passes_through_dense(self):
        tx = dgc(sparsity=0.99, rampup_steps=3)
        g = _grads()
        state = tx.init(g)
        for step in range(5):
            out, state = tx.update(g, state)
            ratio = compression_ratio(out)
            if step < 3:
                assert ratio == 1.0, (step, ratio)
            else:
                assert ratio < 0.2, (step, ratio)

    def test_chained_training_still_converges(self):
        """Linear regression under 90% compression reaches the optimum."""
        rng = np.random.default_rng(0)
        x = rng.normal(size=(256, 8)).astype(np.float32)
        w_true = rng.normal(size=(8,)).astype(np.float32)
        y = x @ w_true
        tx = optax.chain(dgc(sparsity=0.9, momentum=0.9),
                         optax.sgd(0.05))
        params = {"w": jnp.zeros((8,))}
        state = tx.init(params)

        @jax.jit
        def step(params, state):
            def loss(p):
                return jnp.mean((x @ p["w"] - y) ** 2)
            g = jax.grad(loss)(params)
            updates, state = tx.update(g, state)
            return optax.apply_updates(params, updates), state

        for _ in range(400):
            params, state = step(params, state)
        np.testing.assert_allclose(np.asarray(params["w"]), w_true,
                                   atol=0.05)

    def test_jit_and_static_shapes(self):
        tx = dgc(sparsity=0.5)
        g = _grads()
        state = tx.init(g)
        fast = jax.jit(tx.update)
        out, state = fast(g, state)
        out2, _ = fast(_grads(seed=1), state)
        assert out["w"].shape == out2["w"].shape == (256,)

    def test_invalid_sparsity(self):
        with pytest.raises(ValueError):
            dgc(sparsity=1.0)

    def test_sampled_threshold_hits_budget(self):
        """Leaves above the sample cap estimate the threshold from a
        random sample — the kept fraction must stay near the budget."""
        from edl_tpu.train.dgc import _SAMPLE_CAP
        n = _SAMPLE_CAP * 8
        tx = dgc(sparsity=0.99)
        g = {"w": jax.random.normal(jax.random.PRNGKey(0), (n,))}
        out, _ = tx.update(g, tx.init(g))
        kept = int(jnp.sum(out["w"] != 0)) / n
        assert 0.003 < kept < 0.03, kept  # ~1% within sampling noise

    def test_sampling_unbiased_under_structure(self):
        """Regression: a strided sample aliases with the inner dims of
        structured tensors (per-channel scales) and skews the threshold
        by orders of magnitude; random sampling must hold the budget."""
        from edl_tpu.train.dgc import _SAMPLE_CAP
        tx = dgc(sparsity=0.99)
        # (R, C) kernel where a few columns are 100x larger
        r, c = 64, 1024  # n = 65536 > cap
        w = jax.random.normal(jax.random.PRNGKey(1), (r, c))
        w = w.at[:, ::256].multiply(100.0)
        out, _ = tx.update({"w": w}, tx.init({"w": w}))
        kept = int(jnp.sum(out["w"] != 0)) / w.size
        assert 0.002 < kept < 0.05, kept
        # prefix-structured leaf just above the cap (old stride=1 bug
        # sampled only the large-magnitude prefix)
        n = _SAMPLE_CAP + 4000
        v = jnp.concatenate([
            100.0 * jax.random.normal(jax.random.PRNGKey(2),
                                      (_SAMPLE_CAP,)),
            jax.random.normal(jax.random.PRNGKey(3), (4000,))])
        out2, _ = tx.update({"w": v}, tx.init({"w": v}))
        kept2 = int(jnp.sum(out2["w"] != 0)) / n
        assert 0.002 < kept2 < 0.05, kept2

    def test_sample_rotates_across_steps(self):
        """The threshold sample must use different indices each step —
        a frozen sample never lets out-of-sample entries influence the
        estimate (ADVICE r3 / DGC paper's per-step resampling)."""
        from edl_tpu.train.dgc import _SAMPLE_CAP, _topk_threshold
        flat = jax.random.normal(jax.random.PRNGKey(0), (_SAMPLE_CAP * 8,))
        t1 = _topk_threshold(flat, 0.01, jnp.int32(1))
        t2 = _topk_threshold(flat, 0.01, jnp.int32(2))
        t1b = _topk_threshold(flat, 0.01, jnp.int32(1))
        assert float(t1) == float(t1b)        # deterministic per step
        assert float(t1) != float(t2)         # but rotates across steps

    def test_rotating_sample_tracks_true_quantile(self):
        """On a structured tensor where any single sample is biased, the
        LONG-RUN mean threshold must track the exact 99th percentile."""
        from edl_tpu.train.dgc import _SAMPLE_CAP, _topk_threshold
        n = _SAMPLE_CAP * 16
        # heavy-tailed + structured: planted large entries in one block
        flat = jax.random.normal(jax.random.PRNGKey(3), (n,))
        flat = flat.at[:n // 64].multiply(10.0)
        exact = float(jnp.sort(jnp.abs(flat))[int(n * 0.99)])
        ts = [float(_topk_threshold(flat, 0.01, jnp.int32(s)))
              for s in range(32)]
        mean_t = float(np.mean(ts))
        assert abs(mean_t - exact) / exact < 0.15, (mean_t, exact)
        assert np.std(ts) > 0  # genuinely resampling

    def test_rampup_is_momentum_corrected(self):
        """Ramp-up must emit heavyball-momentum updates (buffers carry),
        not raw gradients — matching the reference's DGCMomentum."""
        tx = dgc(sparsity=0.99, momentum=0.9, rampup_steps=10)
        g = {"w": jnp.ones((128,))}
        state = tx.init(g)
        out1, state = tx.update(g, state)
        out2, state = tx.update(g, state)
        np.testing.assert_allclose(np.asarray(out1["w"]), 1.0)
        np.testing.assert_allclose(np.asarray(out2["w"]), 1.9)  # 0.9*1+1


class TestSparsePsum:
    def _run(self, keep_frac, worlds=8, n=512):
        from jax.sharding import PartitionSpec as P
        from edl_tpu.parallel.mesh import MeshSpec, make_mesh
        from edl_tpu.train.dgc import sparse_psum

        mesh = make_mesh(MeshSpec({"dp": worlds}))
        g = jax.random.normal(jax.random.PRNGKey(3), (worlds, n))

        def body(local):
            summed = sparse_psum({"w": local[0]}, "dp",
                                 keep_frac=keep_frac)["w"]
            return summed[None]  # (1, n) slab per worker -> (8, n) global

        from edl_tpu.parallel.compat import shard_map
        out = jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False))(g)
        return g, out

    def test_keep_all_matches_dense_sum(self):
        g, out = self._run(keep_frac=1.0)
        # every worker's slice holds the same dense sum
        want = jnp.sum(g, axis=0)
        for w in range(8):
            # 2e-5: psum reduction order differs across jax versions
            np.testing.assert_allclose(out[w], want, rtol=2e-5)

    def test_topk_contributions_only(self):
        """Each worker contributes exactly its k largest-|.| entries."""
        g, out = self._run(keep_frac=0.25, n=64)  # n<64 fallback guard: use 64
        k = 16
        want = np.zeros(64, np.float32)
        for w in range(8):
            idx = np.argsort(-np.abs(np.asarray(g[w])))[:k]
            want[idx] += np.asarray(g[w])[idx]
        for w in range(8):
            np.testing.assert_allclose(np.asarray(out[w]), want, rtol=1e-5)

    def test_small_leaf_dense_fallback(self):
        g, out = self._run(keep_frac=0.25, n=32)
        want = jnp.sum(g, axis=0)
        np.testing.assert_allclose(out[0], want, rtol=1e-5)
