"""Pallas flash attention vs the dense oracle.

Off-TPU the public API dispatches to compiled XLA blockwise paths, so
every dense-parity test here runs under BOTH dispatch modes via the
`attn_path` fixture: the XLA fallback, and the Pallas kernels forced
through the same custom_vjp path in interpret mode (interpret=True
executes the same kernel body) — block logic, causal skip,
online-softmax accumulation, and both backwards stay covered on CPU.
"""

import contextlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.flash_attention import (flash_attention,
                                         force_interpret_kernels)
from edl_tpu.parallel.ring_attention import dense_attention


def _qkv(b=2, s=256, h=4, d=64, dtype=jnp.float32, seed=0):
    key = jax.random.PRNGKey(seed)
    return tuple(jax.random.normal(jax.random.fold_in(key, i),
                                   (b, s, h, d), dtype) for i in range(3))


@pytest.fixture(params=["xla_fallback", "pallas_kernels"])
def attn_path(request):
    """Run a test body under each off-TPU dispatch mode."""
    ctx = (force_interpret_kernels() if request.param == "pallas_kernels"
           else contextlib.nullcontext())
    with ctx:
        yield request.param


class TestForward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal, attn_path):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal=causal,
                              block_q=128, block_k=128)
        want = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, want, atol=2e-5)

    def test_uneven_blocks(self, attn_path):
        q, k, v = _qkv(s=512)
        out = flash_attention(q, k, v, block_q=128, block_k=256)
        want = dense_attention(q, k, v)
        np.testing.assert_allclose(out, want, atol=2e-5)

    def test_single_block(self, attn_path):
        q, k, v = _qkv(s=128)
        out = flash_attention(q, k, v)  # blocks clamp to S
        np.testing.assert_allclose(out, dense_attention(q, k, v),
                                   atol=2e-5)

    def test_custom_scale(self, attn_path):
        q, k, v = _qkv(s=128)
        out = flash_attention(q, k, v, scale=0.05)
        want = dense_attention(q, k, v, scale=0.05)
        np.testing.assert_allclose(out, want, atol=2e-5)

    def test_bf16_io(self, attn_path):
        q, k, v = _qkv(s=128, dtype=jnp.bfloat16)
        out = flash_attention(q, k, v)
        assert out.dtype == jnp.bfloat16
        want = dense_attention(q, k, v)
        np.testing.assert_allclose(out.astype(np.float32),
                                   want.astype(np.float32), atol=3e-2)

    def test_shape_validation(self):
        q, k, v = _qkv(s=128)
        with pytest.raises(ValueError, match="mismatch"):
            flash_attention(q, k[:, :64], v)
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, k, v, block_q=96)

    def test_awkward_seq_len_auto_blocks(self, attn_path):
        """640 = 5x128: defaults must fall back to a block that divides
        S instead of raising (regression: auto mode crashed on any
        128-multiple that wasn't a 512-multiple)."""
        q, k, v = _qkv(s=640)
        out = flash_attention(q, k, v)  # default block 512 -> fits to 128
        np.testing.assert_allclose(out, dense_attention(q, k, v),
                                   atol=2e-5)

    def test_unknown_attention_config_rejected(self):
        from edl_tpu.models.transformer import TransformerConfig
        with pytest.raises(ValueError, match="unknown attention"):
            TransformerConfig(attention="Flash").use_flash(128)


class TestBackward:
    def test_grads_match_dense(self, attn_path):
        q, k, v = _qkv(s=256)

        def f_flash(q, k, v):
            return jnp.sum(jnp.sin(flash_attention(
                q, k, v, block_q=128, block_k=128)))

        def f_dense(q, k, v):
            return jnp.sum(jnp.sin(dense_attention(q, k, v)))

        gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(a, b, atol=5e-5)

    def test_grads_noncausal(self, attn_path):
        q, k, v = _qkv(s=128)

        def f(fn):
            return jax.grad(lambda q: jnp.sum(
                fn(q, k, v, causal=False) ** 2))(q)

        np.testing.assert_allclose(
            f(lambda q, k, v, causal: flash_attention(q, k, v,
                                                      causal=causal)),
            f(lambda q, k, v, causal: dense_attention(q, k, v,
                                                      causal=causal)),
            atol=5e-5)

    def test_xla_fwd_fallback_matches_pallas_kernel(self):
        """`_fwd_blockwise` (the compiled off-TPU forward) vs the Pallas
        forward kernel in interpret mode: o AND lse, both causalities,
        uneven blocks."""
        from edl_tpu.ops.flash_attention import _fwd, _fwd_blockwise
        for causal in (True, False):
            q, k, v = _qkv(s=256)
            scale = 1.0 / q.shape[-1] ** 0.5
            o_ref, lse_ref = _fwd(q, k, v, blk_q=128, blk_k=64,
                                  scale=scale, causal=causal,
                                  interpret=True)
            o_got, lse_got = _fwd_blockwise(q, k, v, blk=64, scale=scale,
                                            causal=causal)
            np.testing.assert_allclose(o_got, o_ref, atol=5e-6)
            np.testing.assert_allclose(lse_got, lse_ref, atol=5e-6)

    def test_pallas_bwd_matches_xla_reference(self):
        """The Pallas dK/dV + dQ kernels vs `_bwd_blockwise` (the plain
        XLA scan they replaced), incl. the dlse cotangent path and
        uneven blk_q != blk_k."""
        from edl_tpu.ops.flash_attention import (_bwd_blockwise,
                                                 _bwd_pallas, _fwd)
        for causal in (True, False):
            q, k, v = _qkv(s=256)
            scale = 1.0 / q.shape[-1] ** 0.5
            o, lse = _fwd(q, k, v, blk_q=128, blk_k=64, scale=scale,
                          causal=causal, interpret=True)
            rng = np.random.default_rng(5)
            do = jnp.asarray(rng.normal(size=q.shape), q.dtype)
            dlse = jnp.asarray(rng.normal(size=lse.shape), jnp.float32)
            for dl in (None, dlse):
                ref = _bwd_blockwise(q, k, v, o, lse, do, blk=64,
                                     scale=scale, causal=causal, dlse=dl)
                got = _bwd_pallas(q, k, v, o, lse, do, blk_q=128,
                                  blk_k=64, scale=scale, causal=causal,
                                  dlse=dl, interpret=True)
                for a, b in zip(got, ref):
                    np.testing.assert_allclose(a, b, atol=5e-5)

    def test_value_and_grad_jits(self):
        q, k, v = _qkv(s=128)
        f = jax.jit(jax.value_and_grad(
            lambda q: jnp.sum(flash_attention(q, k, v))))
        val, grad = f(q)
        assert np.isfinite(float(val))
        assert grad.shape == q.shape


class TestLseOutput:
    def _oracle(self, q, k, v, s):
        scale = 1.0 / q.shape[-1] ** 0.5
        sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        mask = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]
        sc = jnp.where(mask[None, None], sc, -1e30)
        lse = jax.scipy.special.logsumexp(sc, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", jnp.exp(sc - lse[..., None]), v)
        return o, lse.transpose(0, 2, 1)

    def test_lse_values(self, attn_path):
        from edl_tpu.ops.flash_attention import flash_attention_lse
        q, k, v = _qkv(s=128)
        o, lse = flash_attention_lse(q, k, v, block_q=64, block_k=64)
        oo, lo = self._oracle(q, k, v, 128)
        np.testing.assert_allclose(o, oo, atol=2e-5)
        np.testing.assert_allclose(lse, lo, atol=2e-5)

    def test_lse_cotangent_flows(self, attn_path):
        """Gradients through BOTH outputs (the ring-combine consumes
        lse differentiably) must match the dense oracle."""
        from edl_tpu.ops.flash_attention import flash_attention_lse
        q, k, v = _qkv(s=128)

        def loss(fn):
            def f(q, k, v):
                o, lse = fn(q, k, v)
                return jnp.sum(jnp.sin(o)) + jnp.sum(jnp.cos(lse))
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        gf = loss(lambda q, k, v: flash_attention_lse(q, k, v,
                                                      block_q=64,
                                                      block_k=64))
        go = loss(lambda q, k, v: self._oracle(q, k, v, 128))
        for a, b in zip(gf, go):
            np.testing.assert_allclose(a, b, atol=5e-5)

    def test_dispatch_modes_agree_exactly_on_shapes(self):
        """The two off-TPU paths must agree to numerical tolerance on a
        multi-block causal case (guards dispatch-dependent drift)."""
        from edl_tpu.ops.flash_attention import flash_attention_lse
        q, k, v = _qkv(s=256)
        o1, l1 = flash_attention_lse(q, k, v, block_q=128, block_k=128)
        with force_interpret_kernels():
            o2, l2 = flash_attention_lse(q, k, v, block_q=128,
                                         block_k=128)
        np.testing.assert_allclose(o1, o2, atol=2e-5)
        np.testing.assert_allclose(l1, l2, atol=2e-5)


class TestTransformerIntegration:
    def test_flash_config_matches_dense_config(self):
        """Same weights, attention='flash' (interpret) vs 'dense'."""
        from edl_tpu.models.transformer import (Transformer,
                                                TransformerConfig)

        kw = dict(vocab_size=128, d_model=64, n_heads=4, n_layers=2,
                  d_ff=128, max_len=128, dtype=jnp.float32)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 128)
        m_dense = Transformer(TransformerConfig(attention="dense", **kw))
        m_flash = Transformer(TransformerConfig(attention="flash", **kw))
        variables = m_dense.init(jax.random.PRNGKey(0), toks, train=False)
        out_d = m_dense.apply(variables, toks, train=False)
        out_f = m_flash.apply(variables, toks, train=False)
        np.testing.assert_allclose(out_d, out_f, atol=1e-4)
