"""Mesh construction and batch sharding on the 8-device CPU world."""

import jax
import numpy as np
import pytest

from edl_tpu.parallel.mesh import (
    MeshSpec, data_sharding, dp_size, make_mesh, shard_batch)


def test_default_dp_mesh_uses_all_devices():
    mesh = make_mesh()
    assert mesh.shape["dp"] == jax.device_count() == 8


def test_elastic_prefix():
    for n in (1, 2, 4, 8):
        mesh = make_mesh(n_devices=n)
        assert mesh.shape["dp"] == n
    with pytest.raises(ValueError):
        make_mesh(n_devices=9)


def test_2d_mesh_resolution():
    spec = MeshSpec({"dp": -1, "tp": 2})
    mesh = make_mesh(spec)
    assert mesh.shape == {"dp": 4, "tp": 2}
    with pytest.raises(ValueError):
        make_mesh(MeshSpec({"dp": 3, "tp": 2}))  # 6 != 8
    with pytest.raises(ValueError):
        MeshSpec({"dp": -1, "tp": -1}).resolve(8)


def test_shard_batch_layout():
    mesh = make_mesh()
    batch = {"x": np.zeros((16, 3), np.float32)}
    placed = shard_batch(mesh, batch)
    x = placed["x"]
    assert x.sharding == data_sharding(mesh)
    # each device holds 16/8 = 2 rows
    assert x.addressable_shards[0].data.shape == (2, 3)
    assert dp_size(mesh) == 8


def test_fsdp_counts_as_data_axis():
    mesh = make_mesh(MeshSpec({"dp": 2, "fsdp": 4}))
    assert dp_size(mesh) == 8
    batch = shard_batch(mesh, {"x": np.zeros((8, 2), np.float32)})
    assert batch["x"].addressable_shards[0].data.shape == (1, 2)
