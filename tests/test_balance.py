"""Property tests for the distill rebalance math (balance.py invariants
I1-I5; reference distill/balance_table.py:244-310 formulas)."""

import random

from edl_tpu.distill.balance import ServiceBalance, caps


def check_invariants(svc: ServiceBalance, fresh: bool = False):
    C, S = len(svc.clients), len(svc.servers)
    server_cap, client_cap = caps(C, S)
    loads = svc.loads()
    for s, load in loads.items():
        assert load <= server_cap, f"I1: {s} load {load} > {server_cap}"
    for cid, links in svc.clients.items():
        assert len(links.servers) <= max(client_cap, 0), "I2"
        assert len(set(links.servers)) == len(links.servers), "dup links"
        assert all(s in svc.servers for s in links.servers), "stale link"
    if S > 0 and C > 0:
        for cid, links in svc.clients.items():
            assert len(links.servers) == client_cap, \
                f"I3: {cid} has {len(links.servers)} != {client_cap}"
        # I4 holds for fresh AND incremental rebalances: phase 1 keeps
        # legal existing links (minimal churn), and the skew-repair pass
        # shifts links from the most- to the least-loaded server until the
        # gap closes, so a joining teacher is loaded immediately.
        assert max(loads.values()) - min(loads.values()) <= 1, \
            f"I4: unbalanced {loads}"


def test_caps_formulas():
    assert caps(10, 3) == (4, 1)     # ceil(10/3), max(1, 0)
    assert caps(2, 8) == (1, 4)
    assert caps(3, 3) == (1, 1)
    assert caps(1, 40) == (1, 40)    # the EDL headline shape: 40 teachers
    assert caps(0, 5) == (0, 0)
    assert caps(5, 0) == (0, 0)


def test_single_client_gets_all_servers():
    svc = ServiceBalance("s")
    svc.set_servers([f"t{i}" for i in range(5)])
    svc.add_client("c0")
    svc.rebalance()
    assert set(svc.get("c0").servers) == {f"t{i}" for i in range(5)}


def test_more_clients_than_servers_shares():
    svc = ServiceBalance("s")
    svc.set_servers(["t0", "t1"])
    for i in range(5):
        svc.add_client(f"c{i}")
    svc.rebalance()
    check_invariants(svc, fresh=True)
    # 5 clients / 2 servers: every client exactly 1 server, loads {3, 2}.
    assert sorted(svc.loads().values()) == [2, 3]


def test_fresh_assignment_balanced():
    for C, S in [(7, 6), (6, 7), (10, 3), (3, 10), (16, 16)]:
        svc = ServiceBalance("s")
        svc.set_servers([f"t{i}" for i in range(S)])
        for i in range(C):
            svc.add_client(f"c{i}")
        svc.rebalance()
        check_invariants(svc, fresh=True)


def test_version_bumps_iff_set_changes():
    svc = ServiceBalance("s")
    svc.set_servers(["t0", "t1"])
    svc.add_client("c0")
    svc.rebalance()
    v1 = svc.get("c0").version
    assert v1 == 1  # from empty to assigned

    svc.rebalance()  # no membership change
    assert svc.get("c0").version == v1

    svc.set_servers(["t0", "t1", "t2"])
    changed = svc.rebalance()
    assert changed == ["c0"]
    assert svc.get("c0").version == v1 + 1


def test_minimal_churn_on_server_join():
    # A client keeps its current teacher when a new teacher joins and the
    # caps still allow the old link.
    svc = ServiceBalance("s")
    svc.set_servers(["t0"])
    svc.add_client("c0")
    svc.add_client("c1")
    svc.rebalance()
    before = {cid: set(svc.get(cid).servers) for cid in ("c0", "c1")}
    svc.set_servers(["t0", "t1"])
    svc.rebalance()
    check_invariants(svc)
    # Each client now has exactly 1 server and at least one client kept t0.
    kept = sum("t0" in svc.get(cid).servers and "t0" in before[cid]
               for cid in ("c0", "c1"))
    assert kept >= 1


def test_random_join_leave_fuzz():
    rng = random.Random(1234)
    svc = ServiceBalance("s")
    servers: set[str] = set()
    clients: set[str] = set()
    next_id = [0, 0]
    for step in range(400):
        action = rng.random()
        if action < 0.25:
            servers.add(f"t{next_id[0]}")
            next_id[0] += 1
        elif action < 0.45 and servers:
            servers.discard(rng.choice(sorted(servers)))
        elif action < 0.75:
            cid = f"c{next_id[1]}"
            next_id[1] += 1
            clients.add(cid)
            svc.add_client(cid)
        elif clients:
            cid = rng.choice(sorted(clients))
            clients.discard(cid)
            svc.remove_client(cid)
        svc.set_servers(sorted(servers))
        svc.rebalance()
        check_invariants(svc)


def test_versions_monotone_and_delta_consistent():
    # Simulate the heartbeat protocol: a client that replays every version
    # change ends with exactly the final assignment.
    rng = random.Random(7)
    svc = ServiceBalance("s")
    svc.add_client("c0")
    known_version = -1
    cached: tuple = ()
    for step in range(100):
        n = rng.randint(0, 6)
        svc.set_servers([f"t{i}" for i in range(n)])
        svc.rebalance()
        links = svc.get("c0")
        if links.version != known_version:   # what heartbeat returns
            cached = links.servers
            known_version = links.version
        assert cached == svc.get("c0").servers


def test_expire_clients():
    svc = ServiceBalance("s")
    svc.set_servers(["t0"])
    svc.add_client("c0", now=0.0)
    svc.add_client("c1", now=5.0)
    svc.rebalance()
    dead = svc.expire_clients(now=8.0, ttl=6.0)
    assert dead == ["c0"]
    assert set(svc.clients) == {"c1"}
    svc.rebalance()
    check_invariants(svc)


def test_late_joining_server_loaded_immediately():
    # The I4 skew-repair case: a saturated long-lived service gets a new
    # teacher; the next rebalance must shift load onto it instead of
    # waiting for client churn.
    svc = ServiceBalance("s")
    svc.set_servers(["t0", "t1"])
    for i in range(8):
        svc.add_client(f"c{i}")
    svc.rebalance()
    assert sorted(svc.loads().values()) == [4, 4]
    svc.set_servers(["t0", "t1", "t2"])
    changed = svc.rebalance()
    check_invariants(svc)
    loads = svc.loads()
    assert loads["t2"] >= 2, f"new teacher idle: {loads}"
    assert max(loads.values()) - min(loads.values()) <= 1
    assert changed, "no client was re-versioned despite moved links"


def test_utilization_breaks_ties_toward_idle_teachers():
    """I6: among equally-loaded candidates the least-busy teacher gets
    the link, so an under-subscribed service leaves its BUSIEST servers
    idle (utilization is registrar-published; discovery feeds it in)."""
    svc = ServiceBalance("s")
    svc.set_servers(["t0", "t1", "t2", "t3", "t4"])
    svc.set_utilization({"t0": 0.9, "t1": 0.1, "t2": 0.8, "t3": 0.2,
                         "t4": 0.3})
    svc.add_client("c0")
    svc.add_client("c1")
    svc.rebalance()
    check_invariants(svc)
    used = {s for links in (svc.get("c0"), svc.get("c1"))
            for s in links.servers}
    # client_cap = 5//2 = 2 -> 4 links; the idle leftover must be the
    # busiest teacher
    assert len(used) == 4 and "t0" not in used, used


def test_queue_depth_sheds_new_clients_off_backlogged_teacher():
    """Queue-aware weight (serving SLO satellite): with skewed queue
    depths a backlogged teacher loses the tie even against a HIGHER
    utilization on an empty-queue rival — backlog is the leading
    indicator of the latency violation util only trails."""
    svc = ServiceBalance("s")
    svc.set_servers(["backlogged", "working", "idle"])
    # "backlogged" looks cheapest by util alone, but 10 queued requests
    # say otherwise; "working" runs hotter but keeps its queue empty
    svc.set_utilization({"backlogged": 0.2, "working": 0.7, "idle": 0.3},
                        {"backlogged": 10, "working": 0, "idle": 0})
    svc.add_client("c0")
    svc.add_client("c1")
    svc.rebalance()
    check_invariants(svc)
    used = {s for c in ("c0", "c1") for s in svc.get(c).servers}
    # client_cap = 3//2 = 1 -> one teacher idles; it must be the
    # backlogged one, not the higher-util one
    assert used == {"working", "idle"}, used


def test_queue_depth_unknown_defaults_to_zero():
    """A teacher without a queue report competes on util alone — the
    absence of a backlog signal must not penalize (or favor) it; a
    reported backlog adds QUEUE_WEIGHT per queued request."""
    svc = ServiceBalance("s")
    svc.set_utilization({"a": 0.4}, {"a": 0})
    assert svc._busy("a") == 0.4
    assert svc._busy("unknown") == 0.5      # neutral util + no queue term
    svc.set_utilization({"a": 0.4}, {"a": 3})
    assert abs(svc._busy("a")
               - (0.4 + 3 * ServiceBalance.QUEUE_WEIGHT)) < 1e-9


def test_unknown_utilization_is_neutral_not_idle():
    """A non-reporting teacher must not beat one honestly reporting a
    small util (it could be saturated for all we know); it must still
    beat one reporting heavy load."""
    svc = ServiceBalance("s")
    svc.set_servers(["busy", "light", "silent"])
    svc.set_utilization({"busy": 0.9, "light": 0.1})  # silent: unknown
    svc.add_client("c0")  # client_cap = 3 -> takes all; order probes...
    svc.rebalance()
    # 2 clients, 3 servers: client_cap=1, server_cap=1 -> one idle
    svc.add_client("c1")
    svc.rebalance()
    check_invariants(svc)
    used = {s for c in ("c0", "c1") for s in svc.get(c).servers}
    assert used == {"light", "silent"}, used  # busy reporter left out


def test_utilization_never_violates_count_invariants():
    """I6 is a tie-break ONLY: adversarial busy scores cannot skew link
    counts (I1-I4 keep holding)."""
    import random as _random
    rng = _random.Random(7)
    svc = ServiceBalance("s")
    servers = [f"t{i}" for i in range(6)]
    svc.set_servers(servers)
    for i in range(9):
        svc.add_client(f"c{i}")
    for _ in range(30):
        svc.set_utilization({s: rng.random() for s in servers})
        svc.rebalance()
        check_invariants(svc)
        loads = svc.loads()
        assert max(loads.values()) - min(loads.values()) <= 1
