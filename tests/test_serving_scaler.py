"""Serving-elasticity plane: SLO policy, drain actuation, mixed budget.

The serving counterpart of test_scaler.py (ROADMAP item 2): policy
behavior is pinned against the deterministic `SimServingPool` (virtual
time, seeded noise, SLO oracles from the true queueing model); the
actuation tier drives REAL in-process `TeacherServer`s + registrars
over InMemStore — including the graceful-drain protocol and its
hard-kill fallback; the controller tier runs serving and trainer
policies side by side off one journal.
"""

import json
import threading
import time

import numpy as np
import pytest

from edl_tpu.coord.collector import Collector
from edl_tpu.coord.registry import ServiceRegistry
from edl_tpu.coord.store import InMemStore
from edl_tpu.scaler.controller import ScalerConfig, ScalerController
from edl_tpu.scaler.policy import FairSharePolicy, JobView, ThroughputPolicy
from edl_tpu.scaler.serving import (LocalTeacher, ServingConfig,
                                    ServingPolicy, ServingView,
                                    TeacherPoolActuator, selftest)
from edl_tpu.scaler.simulator import (SimServingPool, burst,
                                      run_serving_policy, steady, step)

ROOT = "edl_distill"


def make_policy(**kw):
    kw.setdefault("slo_p95_ms", 250.0)
    kw.setdefault("breach_ticks", 2)
    kw.setdefault("idle_ticks", 5)
    kw.setdefault("cooldown_s", 15.0)
    kw.setdefault("max_teachers", 16)
    return ServingPolicy(ServingConfig(**kw))


class TestServingPolicy:
    def test_steady_load_never_resizes(self):
        """The no-thrash bar: steady in-SLO load, zero resizes, 100%
        attainment."""
        pool = SimServingPool("s", steady(200.0), teachers=1, tick_s=1.0,
                              seed=0)
        out = run_serving_policy(pool, make_policy(), ticks=150)
        assert out["resizes"] == 0
        assert out["slo_attainment"] == 1.0

    @pytest.mark.parametrize("seed", range(4))
    def test_step_restores_slo_within_bound(self, seed):
        """The acceptance bar: after a 4x load step the SLO is restored
        within a bounded number of ticks, the pool converges to the
        oracle size, and steady state stays resize-free."""
        at = 40
        pool = SimServingPool("s", step(100.0, 4.0, at=at), teachers=1,
                              tick_s=1.0, noise=0.01, seed=seed)
        out = run_serving_policy(pool, make_policy(), ticks=160,
                                 settle_ticks=50)
        assert out["last_violation_tick"] - at <= 25, out
        assert out["final_teachers"] == pool.oracle_teachers(400.0), out
        assert out["post_convergence_resizes"] == 0, out

    def test_burst_grows_in_and_drains_out(self):
        """A bounded burst: the pool grows into it and idles back down
        to the steady oracle after it passes."""
        pool = SimServingPool("s", burst(100.0, 4.0, at=30, length=25),
                              teachers=1, tick_s=1.0, seed=0)
        out = run_serving_policy(pool, make_policy(), ticks=200)
        assert out["resizes"] >= 2, out
        assert out["final_teachers"] == pool.oracle_teachers(100.0), out
        assert out["post_convergence_resizes"] == 0, out

    def test_dead_zone_holds_between_idle_and_breach(self):
        """Asymmetric hysteresis: a pool between the low-water mark and
        the SLO never resizes — the dead zone is where it LIVES."""
        policy = make_policy(util_low=0.3)
        view = ServingView("s", 2, util=0.6, queue_depth=1,
                           latency_ms_p95=150.0, slo_p95_ms=250.0)
        for tick in range(50):
            (prop,) = policy.decide([view], float(tick))
            assert not prop.is_resize and prop.reason == "in-band"

    def test_sustained_breach_required(self):
        """One bad sample never grows the pool (breach_ticks filter)."""
        policy = make_policy(breach_ticks=3)
        bad = ServingView("s", 1, util=1.0, latency_ms_p95=900.0)
        good = ServingView("s", 1, util=0.5, latency_ms_p95=100.0)
        for now, view in ((1.0, bad), (2.0, good), (3.0, bad), (4.0, bad)):
            (prop,) = policy.decide([view], now)
            assert not prop.is_resize  # streak broken by the good tick
        (prop,) = policy.decide([bad], 5.0)
        assert prop.is_resize and prop.reason == "slo-breach-grow"

    def test_grow_is_multiplicative_but_bounded(self):
        """A deep breach grows by grow_max_factor at most (and by at
        least one teacher) — fast recovery without one sample
        quadrupling the pool."""
        policy = make_policy(breach_ticks=1, grow_max_factor=2.0)
        view = ServingView("s", 4, util=1.0, queue_depth=100,
                           latency_ms_p95=5000.0, slo_p95_ms=250.0)
        (prop,) = policy.decide([view], 1.0)
        assert prop.desired == 8  # 4 * min(20x, 2.0)

    def test_backlog_draining_holds(self):
        """A breach whose queue is already paying down under existing
        capacity holds instead of growing: more teachers cannot drain
        faster than the arrival deficit already does."""
        policy = make_policy(breach_ticks=1)
        over = ServingView("s", 2, util=0.5, queue_depth=40,
                           latency_ms_p95=900.0)
        (prop,) = policy.decide([over], 1.0)
        assert prop.is_resize  # first look: no trend yet, act on breach

    def test_backlog_draining_trend_suppresses_grow(self):
        policy = make_policy(breach_ticks=2)
        v1 = ServingView("s", 2, util=0.5, queue_depth=40,
                         latency_ms_p95=900.0)
        v2 = ServingView("s", 2, util=0.5, queue_depth=25,
                         latency_ms_p95=700.0)
        v3 = ServingView("s", 2, util=0.5, queue_depth=10,
                         latency_ms_p95=400.0)
        policy.decide([v1], 1.0)
        for now, v in ((2.0, v2), (3.0, v3)):
            (prop,) = policy.decide([v], now)
            assert not prop.is_resize and prop.reason == "backlog-draining"

    def test_cooldown_spaces_resizes_but_streaks_accumulate(self):
        """No two resizes inside the cooldown; the breach streak keeps
        counting DURING cooldown so the first post-cooldown decision
        acts immediately."""
        policy = make_policy(breach_ticks=2, cooldown_s=10.0)
        bad = ServingView("s", 1, util=1.0, latency_ms_p95=900.0)
        policy.decide([bad], 1.0)
        (prop,) = policy.decide([bad], 2.0)
        assert prop.is_resize
        policy.notify_resized("s", 2, 2.0)
        bad2 = ServingView("s", 2, util=1.0, latency_ms_p95=900.0)
        for now in (3.0, 5.0, 9.0, 11.0):
            (prop,) = policy.decide([bad2], now)
            assert prop.reason == "cooldown"
        (prop,) = policy.decide([bad2], 12.5)
        assert prop.is_resize  # streak was already sustained

    def test_idle_shrink_is_one_at_a_time(self):
        policy = make_policy(idle_ticks=3, cooldown_s=1.0)
        idle = ServingView("s", 4, util=0.05, queue_depth=0,
                           latency_ms_p95=30.0)
        props = [policy.decide([idle], float(t))[0] for t in range(3)]
        assert not any(p.is_resize for p in props[:2])
        assert props[2].is_resize and props[2].desired == 3

    def test_restore_resumes_cooldown_from_serving_entries(self):
        """Journal replay: a takeover scaler must not re-resize inside
        the predecessor's cooldown; trainer entries are ignored."""
        policy = make_policy(cooldown_s=20.0, breach_ticks=1)
        now = 1000.0
        policy.restore([
            {"job_id": "trainer_job", "action": "resize", "ts": now - 1},
            {"kind": "serving", "service": "s", "action": "resize",
             "ts": now - 5.0},
        ])
        bad = ServingView("s", 2, util=1.0, latency_ms_p95=900.0)
        (prop,) = policy.decide([bad], now)
        assert prop.reason == "cooldown"
        (prop,) = policy.decide([bad], now + 16.0)
        assert prop.is_resize

    def test_fresh_and_inflight_gates(self):
        policy = make_policy(breach_ticks=1)
        stale = ServingView("s", 2, latency_ms_p95=900.0, fresh=False)
        (prop,) = policy.decide([stale], 1.0)
        assert prop.reason == "no-fresh-serving-stats"
        inflight = ServingView("s", 2, latency_ms_p95=900.0, desired=3)
        (prop,) = policy.decide([inflight], 2.0)
        assert prop.reason == "resize-in-flight"

    def test_selftest_passes(self):
        """The CI smoke is green from inside the suite too."""
        assert selftest(verbose=False) == 0


# -- actuation: real teachers, real drains -----------------------------------


def make_slow_teacher(store, service, *, per_row_s=0.0, gate=None):
    """Spawn an in-process TeacherServer (+registrar) whose predict
    optionally sleeps per row or blocks on `gate` (drain-window
    control)."""
    from edl_tpu.distill.registrar import TeacherRegistrar
    from edl_tpu.distill.teacher_server import TeacherServer

    def predict(feeds):
        if gate is not None:
            gate.wait(timeout=10.0)
        if per_row_s:
            rows = next(iter(feeds.values())).shape[0]
            time.sleep(rows * per_row_s)
        rows = next(iter(feeds.values())).shape[0]
        return {"logits": np.zeros((rows, 2), np.float32)}

    server = TeacherServer(predict, port=0, host="127.0.0.1",
                           max_batch=16, max_wait=0.001).start()
    registrar = TeacherRegistrar(store, service,
                                 f"127.0.0.1:{server.port}", ttl=5.0,
                                 stats_interval=0.1, probe_timeout=5.0)
    registrar.start()
    return LocalTeacher(server, registrar)


class TestTeacherPoolActuator:
    def test_grow_spawns_and_registers(self):
        store = InMemStore()
        actuator = TeacherPoolActuator(
            lambda i: make_slow_teacher(store, "svc"), max_teachers=4,
            service="svc")
        try:
            resp = actuator.resize(2)
            assert resp == {"desired_teachers": 2, "requested": 2,
                            "clamped": False}
            assert actuator.pool_size() == 2
            registry = ServiceRegistry(store, root=ROOT)
            assert len(registry.get_service("svc")) == 2
            assert actuator.resize(9)["clamped"] is True
        finally:
            actuator.close()

    def test_graceful_drain_deregisters_first_and_completes_inflight(self):
        """The drain protocol end-to-end: the shrinking pool deregisters
        the victim immediately (discovery stops handing it out while the
        server still LIVES), an in-flight request completes against the
        draining server, and only then does it stop."""
        from edl_tpu.distill.teacher_server import TeacherClient
        store = InMemStore()
        gate = threading.Event()
        teachers = []

        def spawn(i):
            # first teacher free-running, second one gate-controlled so
            # the test owns the drain window
            t = make_slow_teacher(store, "svc",
                                  gate=gate if i == 1 else None)
            teachers.append(t)
            return t

        actuator = TeacherPoolActuator(spawn, max_teachers=4,
                                       drain_deadline_s=10.0,
                                       drain_poll_s=0.02, service="svc")
        registry = ServiceRegistry(store, root=ROOT)
        try:
            actuator.resize(2)
            victim = teachers[1]  # LIFO: the newest retires first
            client = TeacherClient(victim.endpoint, timeout=10.0)
            pending = client.predict_async(
                {"x": np.zeros((4, 2), np.float32)})  # blocked on gate
            time.sleep(0.1)
            actuator.resize(1)
            deadline = time.monotonic() + 5.0
            while len(registry.get_service("svc")) != 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            # deregistered from discovery BEFORE the server stopped:
            assert len(registry.get_service("svc")) == 1
            assert victim.stats() is not None, "server gone before drain"
            assert not actuator.drain_log, "drain finished with work live"
            gate.set()  # let the in-flight request finish
            out = pending.result()  # completes, no connection reset
            assert out["logits"].shape == (4, 2)
            assert actuator.wait_drains(timeout=10.0)
            (entry,) = actuator.drain_log
            assert entry["drained"] and not entry["hard_killed"], entry
            client.close()
        finally:
            gate.set()
            actuator.close()

    def test_drain_deadline_hard_kill_fallback(self):
        """A teacher that never quiets (stats always show work) is
        hard-killed at the deadline — recorded, never silent."""
        killed = threading.Event()

        class StuckTeacher:
            endpoint = "stuck:1"

            def stats(self):
                return {"queue_depth": 1, "inflight_groups": 1}

            def deregister(self):
                pass

            def stop(self):
                raise AssertionError("graceful stop on a stuck teacher")

            def kill(self):
                killed.set()

        actuator = TeacherPoolActuator(lambda i: StuckTeacher(),
                                       min_teachers=0, max_teachers=2,
                                       drain_deadline_s=0.3,
                                       drain_poll_s=0.02, service="svc")
        actuator.resize(1)
        actuator.resize(0)
        assert actuator.wait_drains(timeout=5.0)
        assert killed.is_set()
        (entry,) = actuator.drain_log
        assert entry["hard_killed"] and not entry["drained"]
        assert entry["wait_s"] >= 0.3

    def test_balancer_reassigns_readers_keep_then_fill(self):
        """The balancer half of the drain story: when the drained
        teacher leaves the registry, a client's next heartbeat delivers
        a re-versioned server set that keeps its surviving teacher and
        fills from the remaining pool."""
        from edl_tpu.distill.discovery_server import BalanceTable
        store = InMemStore()
        registry = ServiceRegistry(store, root=ROOT)
        regs = {ep: registry.register("svc", ep)
                for ep in ("t0:1", "t1:1", "t2:1")}
        table = BalanceTable(store, "disc:1", root=ROOT)
        resp = table.register("reader", "svc")
        assert resp["status"] == "OK"
        before = set(resp["servers"])
        assert len(before) == 3  # client_cap = 3//1
        # drain t1: deregister-first, exactly what the actuator does
        regs["t1:1"].stop()
        registry.deregister("svc", "t1:1")
        table.tick()
        hb = table.heartbeat("reader", "svc", resp["version"])
        assert hb["version"] > resp["version"]
        after = set(hb["servers"])
        assert "t1:1" not in after
        # keep-then-fill: every surviving old link is retained
        assert before - {"t1:1"} <= after
        for reg in regs.values():
            reg.stop()


# -- the latency histogram (SLO signal source) -------------------------------


class TestLatencyHistogram:
    def test_quantiles_from_known_histogram(self):
        from edl_tpu.distill.teacher_server import latency_quantile
        hist = {"10.0": 90, "100.0": 9, "1000.0": 1}
        assert latency_quantile(hist, 0.5) == 10.0
        assert latency_quantile(hist, 0.95) == 100.0
        assert latency_quantile(hist, 0.999) == 1000.0
        assert latency_quantile({}, 0.5) is None
        assert latency_quantile({"10.0": 0}, 0.5) is None

    def test_server_stats_carry_latency_quantiles(self):
        """A served request lands in the histogram; a slow predict_fn
        pushes p95 at least past its sleep."""
        from edl_tpu.distill.teacher_server import (TeacherClient,
                                                    TeacherServer)

        def predict(feeds):
            time.sleep(0.06)
            rows = next(iter(feeds.values())).shape[0]
            return {"y": np.zeros((rows, 2), np.float32)}

        with TeacherServer(predict, port=0, host="127.0.0.1") as server:
            client = TeacherClient(f"127.0.0.1:{server.port}",
                                   timeout=10.0)
            for _ in range(3):
                client.predict({"x": np.zeros((2, 2), np.float32)})
            stats = client.stats()
            client.close()
        assert stats["latency_ms_p95"] >= 50.0
        assert stats["latency_ms_p50"] >= 50.0
        assert sum(stats["latency_hist_ms"].values()) == 3
        assert stats["inflight_groups"] == 0

    def test_registrar_info_windows_the_histogram(self):
        """The registrar publishes WINDOWED p50/p95: a teacher that
        turns slow shows up within one stats interval even with a fast
        cumulative past."""
        from edl_tpu.distill.registrar import TeacherRegistrar
        registrar = TeacherRegistrar(InMemStore(), "svc", "h:1")
        fast_past = {"served_rows": 1000, "busy_s": 1.0, "queue_depth": 0,
                     "batch_rows_hist": {"16": 100},
                     "latency_hist_ms": {"10.0": 1000}}
        now_slow = {"served_rows": 1100, "busy_s": 2.0, "queue_depth": 7,
                    "inflight_groups": 1,
                    "batch_rows_hist": {"16": 110},
                    "latency_hist_ms": {"10.0": 1000, "1000.0": 100}}
        info = json.loads(registrar._utilization_info(now_slow, fast_past,
                                                      dt=5.0))
        assert info["latency_ms_p50"] == 1000.0  # the window is ALL slow
        assert info["latency_ms_p95"] == 1000.0
        assert info["queue_depth"] == 7
        assert info["inflight_groups"] == 1
        # cumulative view would have said p50=10ms
        cold = json.loads(registrar._utilization_info(now_slow, None,
                                                      dt=5.0))
        assert cold["latency_ms_p50"] == 10.0

    def test_collector_rollup_takes_worst_teacher_tail(self):
        store = InMemStore()
        registry = ServiceRegistry(store, root=ROOT)
        registry.register_permanent("svc", "h:1", info=json.dumps(
            {"rows_per_sec": 100.0, "util": 0.2, "queue_depth": 1,
             "latency_ms_p95": 40.0, "latency_ms_p50": 10.0}))
        registry.register_permanent("svc", "h:2", info=json.dumps(
            {"rows_per_sec": 50.0, "util": 0.8, "queue_depth": 5,
             "latency_ms_p95": 400.0, "latency_ms_p50": 100.0}))
        registry.register_permanent("svc", "h:3", info="")  # blind member
        roll = Collector(store, services=("svc",),
                         registry_root=ROOT).service_rollup("svc")
        assert roll["n_teachers"] == 3 and roll["reporting"] == 2
        assert roll["rows_per_sec"] == 150.0
        assert roll["util"] == 0.5
        assert roll["queue_depth"] == 6
        assert roll["latency_ms_p95"] == 400.0  # the slow member's tail


# -- controller: both planes under one election ------------------------------


def publish_teacher(registry, service, server, *, p95=40.0, util=0.3,
                    queue=0, rows=100.0):
    registry.register_permanent(service, server, info=json.dumps(
        {"rows_per_sec": rows, "util": util, "queue_depth": queue,
         "latency_ms_p95": p95, "latency_ms_p50": p95 / 2}))


class TestControllerServingPlane:
    def make_controller(self, store, actuate, **kw):
        cfg = ServingConfig(slo_p95_ms=250.0, breach_ticks=2,
                            cooldown_s=5.0, max_teachers=4)
        kw.setdefault("config", ScalerConfig())
        return ScalerController(
            store, [], ThroughputPolicy(), services=["svc"],
            serving_policy=ServingPolicy(cfg), serving_actuate=actuate,
            serving_config=cfg, elect=False, scope="svc", **kw), cfg

    def test_breach_grows_through_actuator_and_journals(self):
        store = InMemStore()
        registry = ServiceRegistry(store, root=ROOT)
        publish_teacher(registry, "svc", "h:1", p95=900.0, util=1.0,
                        queue=20)
        resizes = []
        ctl, _ = self.make_controller(
            store, lambda s, d: resizes.append((s, d))
            or {"desired_teachers": d})
        e1 = ctl.tick(now=100.0)
        assert e1[0]["kind"] == "serving" and e1[0]["action"] == "hold"
        e2 = ctl.tick(now=101.0)
        assert e2[0]["action"] == "resize" and e2[0]["applied"] == 2
        assert resizes == [("svc", 2)]
        # registry still shows 1 teacher: in-flight until the spawn lands
        e3 = ctl.tick(now=102.0)
        assert e3[0]["reason"] == "resize-in-flight"
        publish_teacher(registry, "svc", "h:2", p95=40.0, util=0.4)
        e4 = ctl.tick(now=110.0)
        assert e4[0]["reason"] in ("in-band", "backlog-draining")

    def test_no_actuation_path_journals_error(self):
        store = InMemStore()
        registry = ServiceRegistry(store, root=ROOT)
        publish_teacher(registry, "svc", "h:1", p95=900.0, util=1.0)
        ctl, _ = self.make_controller(store, None)
        ctl.tick(now=1.0)
        (entry,) = ctl.tick(now=2.0)
        assert entry["action"] == "error"
        assert "no serving actuation path" in entry["reason"]

    def test_empty_pool_is_not_fresh(self):
        store = InMemStore()
        ctl, _ = self.make_controller(store, lambda s, d: {})
        (entry,) = ctl.tick(now=1.0)
        assert entry["reason"] == "no-fresh-serving-stats"
        assert not entry["fresh"]

    def test_takeover_replays_serving_cooldown(self):
        """A successor controller must not double-resize inside the
        predecessor's serving cooldown window."""
        store = InMemStore()
        registry = ServiceRegistry(store, root=ROOT)
        publish_teacher(registry, "svc", "h:1", p95=900.0, util=1.0)
        ctl, _ = self.make_controller(
            store, lambda s, d: {"desired_teachers": d})
        ctl.tick(now=100.0)
        ctl.tick(now=101.0)  # resize journaled at ts=101
        # successor: same scope, fresh process; registry now shows the
        # new world so the view itself is actionable again
        publish_teacher(registry, "svc", "h:2", p95=900.0, util=1.0)
        succ, _ = self.make_controller(
            store, lambda s, d: {"desired_teachers": d})
        (entry,) = succ.tick(now=103.0)
        assert entry["action"] == "hold" and entry["reason"] == "cooldown"

    def test_trainer_and_serving_side_by_side(self):
        """One tick, one journal, both planes: trainer jobs keep their
        entry shape (job_id), pools theirs (kind=serving)."""
        store = InMemStore()
        registry = ServiceRegistry(store, root=ROOT)
        publish_teacher(registry, "svc", "h:1")
        cfg = ServingConfig(slo_p95_ms=250.0)
        ctl = ScalerController(
            store, ["job"], ThroughputPolicy(), services=["svc"],
            serving_policy=ServingPolicy(cfg),
            serving_actuate=lambda s, d: {"desired_teachers": d},
            serving_config=cfg, elect=False, scope="both", dry_run=True)
        entries = ctl.tick(now=1.0)
        kinds = [(e.get("job_id"), e.get("kind")) for e in entries]
        assert kinds == [("job", None), (None, "serving")]

    def test_services_without_serving_policy_requires_mixed(self):
        with pytest.raises(ValueError):
            ScalerController(InMemStore(), [], ThroughputPolicy(),
                             services=["svc"], elect=False)
        # FairShare exposes decide_mixed: accepted
        ScalerController(InMemStore(), [], FairSharePolicy(4),
                         services=["svc"], elect=False, scope="s")


# -- fair share across trainers AND pools ------------------------------------


class TestFairShareMixed:
    def test_pool_demand_latency_and_util_bounds(self):
        pol = FairSharePolicy(8, cooldown_s=15.0, horizon_s=60.0)
        # latency over target: demand scales n by p95 / (0.75 * slo)
        v = ServingView("s", 2, util=0.5, latency_ms_p95=600.0,
                        slo_p95_ms=250.0, max_teachers=8)
        assert pol.pool_demand(v) == 7  # ceil(2 * 600 / 187.5)
        # no latency signal: utilization bound keeps rho <= 0.75
        v = ServingView("s", 4, util=0.9, max_teachers=8)
        assert pol.pool_demand(v) == 5  # ceil(4 * 0.9 / 0.75)
        # healthy pool: demand shrinks to the utilization floor
        # (ceil(4 * 0.2 / 0.75) = 2 — never below what keeps rho sane)
        v = ServingView("s", 4, util=0.2, latency_ms_p95=30.0,
                        slo_p95_ms=250.0)
        assert pol.pool_demand(v) == 2
        # near-zero traffic: demand collapses to min_teachers
        v = ServingView("s", 4, util=0.0, latency_ms_p95=None,
                        slo_p95_ms=250.0)
        assert pol.pool_demand(v) == 1

    def test_budget_conserved_and_pool_outranks_trainers(self):
        """A breaching pool is granted its SLO demand FIRST; trainers
        water-fill the remainder; the joint total never exceeds the
        budget."""
        pol = FairSharePolicy(8, cooldown_s=0.0, horizon_s=60.0)
        for n, rate in ((1, 100.0), (2, 195.0), (3, 285.0)):
            pol.model("job").observe(n, rate)
        trainer = JobView("job", 3, 285.0, 1, 8, downtime_s=0.1)
        pool = ServingView("s", 2, util=1.0, latency_ms_p95=750.0,
                           slo_p95_ms=250.0, max_teachers=8)
        t_alloc, p_alloc = pol.plan_mixed([trainer], [pool])
        assert p_alloc["s"] == pol.pool_demand(pool) == 8
        assert t_alloc["job"] + p_alloc["s"] <= 8
        t_props, s_props = pol.decide_mixed([trainer], [pool], now=1.0)
        total_after = sum(p.desired for p in t_props + s_props)
        assert total_after <= 8
        (sp,) = s_props
        assert sp.desired > sp.current  # the pool got its grow

    def test_mixed_shrink_before_grow_within_budget(self):
        """The trainer's shrink funds the pool's grow inside one tick's
        accounting — the transient never exceeds the budget."""
        pol = FairSharePolicy(6, cooldown_s=0.0, horizon_s=60.0)
        for n, rate in ((1, 100.0), (4, 110.0)):
            pol.model("job").observe(n, rate)  # flat: 4 nodes wasted
        trainer = JobView("job", 4, 110.0, 1, 8, downtime_s=0.1)
        pool = ServingView("s", 2, util=1.0, latency_ms_p95=500.0,
                           slo_p95_ms=250.0, max_teachers=8)
        t_props, s_props = pol.decide_mixed([trainer], [pool], now=1.0)
        (tp,), (sp,) = t_props, s_props
        assert tp.desired < tp.current       # trainer shrinks
        assert sp.desired > sp.current       # pool grows
        assert tp.desired + sp.desired <= 6  # jointly inside the budget

    def test_mixed_co_simulation_step_shifts_budget(self):
        """Co-sim: a load step on the pool pulls budget from a flat
        trainer; the budget is respected on every tick."""
        from edl_tpu.scaler.simulator import SimCluster, SimJob, flat
        budget = 6
        pol = FairSharePolicy(budget, cooldown_s=2.0, horizon_s=60.0,
                              gain_threshold=0.05)
        cluster = SimCluster([SimJob("job", flat(100.0), 1, 8, nodes=4,
                                     noise=0.0)],
                             tick_s=1.0, downtime_s=0.5, seed=0)
        pool = SimServingPool("s", step(100.0, 4.0, at=20), teachers=1,
                              tick_s=1.0, max_teachers=8, seed=0)
        for _ in range(80):
            t_views = cluster.tick()
            s_view = pool.tick()
            t_props, s_props = pol.decide_mixed(t_views, [s_view],
                                                cluster.now)
            for prop in t_props:
                if prop.is_resize:
                    actual = cluster.resize(prop.job_id, prop.desired)
                    pol.notify_resized(prop.job_id, actual, cluster.now)
            (sp,) = s_props
            if sp.is_resize:
                actual = pool.resize(sp.desired)
                pol.notify_resized("s", actual, cluster.now)
            live = (cluster.jobs["job"].nodes + pool.ready
                    + len(pool._pending_spawns))
            assert live <= budget + 1, f"budget blown: {live}"
        assert pool.ready >= 2          # the pool grew into the step
        assert cluster.jobs["job"].nodes < 4  # the flat trainer paid
