"""Ring attention vs dense oracle on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import edl_tpu.parallel.ring_attention as ra
from edl_tpu.parallel import mesh as mesh_lib


def make_qkv(b=2, s=16, h=4, d=8, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("axes", [{"sp": 8}, {"dp": 2, "sp": 4},
                                  {"dp": 2, "sp": 2, "tp": 2}])
def test_ring_matches_dense(causal, axes):
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(axes))
    q, k, v = make_qkv()
    want = ra.dense_attention(q, k, v, causal=causal)
    got = jax.jit(lambda q, k, v: ra.ring_attention(
        q, k, v, mesh=mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_dense():
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 2, "sp": 4}))
    q, k, v = make_qkv()

    def loss_ring(q, k, v):
        return jnp.sum(ra.ring_attention(q, k, v, mesh=mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(ra.dense_attention(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=5e-4, atol=5e-4)


def test_ring_bf16_runs():
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"sp": 8}))
    q, k, v = (x.astype(jnp.bfloat16) for x in make_qkv())
    out = jax.jit(lambda q, k, v: ra.ring_attention(
        q, k, v, mesh=mesh))(q, k, v)
    assert out.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(out, np.float32)).all()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("axes", [{"sp": 8}, {"dp": 2, "sp": 4},
                                  {"dp": 2, "sp": 2, "tp": 2}])
def test_ring_flash_matches_dense(causal, axes):
    """Flash-kernel-per-block ring (use_flash=True) vs the dense oracle —
    the composed long-context path (ops/flash_attention.py inside the
    sp ring)."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(axes))
    q, k, v = make_qkv(s=32)
    want = ra.dense_attention(q, k, v, causal=causal)
    got = jax.jit(lambda q, k, v: ra.ring_attention(
        q, k, v, mesh=mesh, causal=causal, use_flash=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_gradients_match_dense():
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": 2, "sp": 4}))
    q, k, v = make_qkv(s=32)

    def loss_ring(q, k, v):
        return jnp.sum(ra.ring_attention(q, k, v, mesh=mesh,
                                         use_flash=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(ra.dense_attention(q, k, v) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_ring_flash_bf16_runs():
    """Regression: bf16 io crashed lax.switch (future branch returned
    float32 while diag/past returned bf16)."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"sp": 8}))
    q, k, v = (x.astype(jnp.bfloat16) for x in make_qkv(s=32))
    out = jax.jit(lambda q, k, v: ra.ring_attention(
        q, k, v, mesh=mesh, use_flash=True))(q, k, v)
    assert out.dtype == jnp.bfloat16
    want = ra.dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(want, np.float32), atol=3e-2)
