"""Consistent hash: balance + monotonicity.

Test model: reference test_consistent_hash.py:22-81 (statistical balance
>3000/10000 per node across 3 nodes; stability of untouched keys under
remove/re-add).
"""

from edl_tpu.coord.consistent_hash import ConsistentHash


def test_balance():
    ring = ConsistentHash(["n0", "n1", "n2"])
    counts = {"n0": 0, "n1": 0, "n2": 0}
    for i in range(10000):
        counts[ring.lookup(f"key-{i}")] += 1
    assert sum(counts.values()) == 10000
    for node, c in counts.items():
        assert c > 2400, f"{node} underloaded: {counts}"


def test_remove_moves_only_owned_keys():
    ring = ConsistentHash(["n0", "n1", "n2"])
    before = {f"key-{i}": ring.lookup(f"key-{i}") for i in range(2000)}
    ring.remove_node("n1")
    for key, owner in before.items():
        new = ring.lookup(key)
        if owner != "n1":
            assert new == owner  # untouched keys must not move
        else:
            assert new in ("n0", "n2")


def test_re_add_restores_mapping():
    ring = ConsistentHash(["n0", "n1", "n2"])
    before = {f"key-{i}": ring.lookup(f"key-{i}") for i in range(2000)}
    ring.remove_node("n1")
    ring.add_node("n1")
    after = {k: ring.lookup(k) for k in before}
    assert before == after


def test_versioning():
    ring = ConsistentHash(["a"])
    v0 = ring.version
    ring.add_node("b")
    assert ring.version == v0 + 1
    ring.add_node("b")  # no-op
    assert ring.version == v0 + 1
    ring.set_nodes(["a", "b"])  # same set, no-op
    assert ring.version == v0 + 1
    ring.set_nodes(["a"])
    assert ring.version == v0 + 2


def test_empty_ring():
    ring = ConsistentHash([])
    assert ring.lookup("anything") is None
